"""Single-node plan executor.

Reference: the worker-side execution stack — LocalExecutionPlanner turns a
fragment into operator pipelines (sql/planner/LocalExecutionPlanner.java:549)
and Driver pushes pages between operators (operator/Driver.java:372). Here a
plan node maps to a jitted kernel call; XLA fuses within each call, and
adjacent Filter/Project nodes are evaluated inside one jit (the fusion
PageProcessor codegen gives Trino). The distributed variant lives in
parallel/ (stages over a mesh); this executor is also the per-shard body.

Adaptive fallbacks (SURVEY.md §7 hard part 1):
- sort-aggregation output capacity doubles and re-runs when the group table
  fills (the analog of GroupByHash rehash);
- joins with duplicate build keys fall back to a host expansion join until
  the device expansion kernel lands.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .. import ir
from ..batch import (Batch, Column, batch_from_numpy, batch_to_numpy,
                     bucket_capacity)
from ..catalog import Catalog
from ..ops.aggregate import (AggSpec, direct_group_aggregate,
                             global_aggregate, sort_group_aggregate)
from ..batch import pad_capacity
from ..ops.join import (join_expand, join_mark, join_unique_build,
                        join_unique_build_dense, join_unique_build_merge)
from ..ops.project import apply_filter, filter_project, project
from ..ops.sort import limit_batch, sort_batch
from ..planner import logical as L


@dataclass
class ExecStats:
    """Per-query execution counters (OperatorStats pyramid, minimal)."""
    scans: int = 0
    rows_scanned: int = 0
    join_fallbacks: int = 0
    join_expansion_retries: int = 0
    join_domain_fallbacks: int = 0   # dense-LUT stats were stale
    agg_capacity_retries: int = 0
    dynamic_filter_compactions: int = 0
    agg_spill_chunks: int = 0
    mxu_agg_calls: int = 0
    fact_cache_chunks: int = 0       # chunks sliced from device-resident
    chunk_lut_joins: int = 0         # sync-free reused-LUT probes
    fused_chunk_pipelines: int = 0   # whole-chunk-path single programs
    pallas_gather_calls: int = 0     # probe sites dispatched with the
                                     # tiled-gather kernel enabled
    jit_compiles: int = 0            # new jitted programs built (fused
                                     # chunk pipelines compiled fresh)
    escaped_window_reruns: int = 0   # adapted fused runs whose window /
                                     # capacity guesses were violated
    compaction_overflows: int = 0    # in-program compaction capacity hit
    spilled_joins: int = 0           # joins retried through host-spill
                                     # radix partitioning (exec/spill.py)
    spilled_aggregations: int = 0    # aggregations/partial states spilled
    spilled_sorts: int = 0           # sorts retried on host (TopN under
                                     # pressure)
    hash_agg_calls: int = 0          # VMEM hash-table aggregations run
                                     # (ops/pallas_hash.py)
    hash_agg_escapes: int = 0        # hash-agg overflow escapes that
                                     # radix-partitioned and re-entered
    hash_join_calls: int = 0         # hybrid hash-join builds attempted
    hash_join_escapes: int = 0       # join builds that overflowed the
                                     # table and degraded partition-wise
    mesh_partitioned_joins: int = 0  # joins hash-repartitioned over the
                                     # mesh (parallel/dist_executor.py)
    dynamic_filter_rows_pruned: int = 0   # probe rows cut by build-side
                                          # bounds before the join ran
    scan_zones_pruned: int = 0       # zone-map row ranges skipped at scan
                                     # materialization (exec/zonemap.py)
    scan_rows_pruned: int = 0        # rows those zones would have decoded
    scan_chunks_skipped: int = 0     # chunked-driver chunks skipped whole
    scan_prefetched_chunks: int = 0  # chunks served from the prefetch
                                     # pipeline (exec/chunked.py)
    scan_prefetch_stalls: int = 0    # consumer waits on an unstaged chunk
    multijoin_fused_probes: int = 0  # fused multiway star passes run
                                     # (ops/pallas_hash.multiway_probe)
    multijoin_degrades: int = 0      # star dimensions degraded back to
                                     # the pairwise ladder (any reason)


class QueryDeadlineError(RuntimeError):
    """query_max_run_time_s exceeded (QUERY_MAX_RUN_TIME's role).

    Non-retryable user error: retrying cannot beat a wall clock that
    already ran out, so the dispatcher surfaces it straight to the
    client (same taxonomy path as QUERY_EXCEEDED_MEMORY)."""
    error_name = "QUERY_EXCEEDED_RUN_TIME"
    error_code = 4


class QueryTerminatedError(RuntimeError):
    """terminate() requested cancellation of the running query; the
    executor raises this at the next cooperative check point (plan-node,
    chunk, spill-partition, or prefetch boundary) so the exec lock frees
    within a bounded grace. Carries USER_CANCELED taxonomy — the state
    machine has usually already recorded the real reason."""
    error_name = "USER_CANCELED"
    error_code = 2


# serializes ExecStats->metrics snapshot diffs across task threads
import threading as _threading  # noqa: E402

_FLUSH_LOCK = _threading.Lock()


def _subtree_scans(node: "L.PlanNode"):
    if isinstance(node, L.ScanNode):
        yield node
    for c in L.children(node):
        yield from _subtree_scans(c)


def _subtree_nodes(node: "L.PlanNode"):
    yield node
    for c in L.children(node):
        yield from _subtree_nodes(c)


class Executor:
    def __init__(self, catalog: Catalog):
        from collections import OrderedDict
        self.catalog = catalog
        self._scan_cache: "OrderedDict[tuple, Batch]" = OrderedDict()
        self._scalar_cache: Dict[object, object] = {}
        self.stats = ExecStats()
        self.profile = False           # EXPLAIN ANALYZE per-node timing
        self.node_stats: Dict[int, tuple] = {}   # id(node) -> (wall_s, rows)
        from .memory import MemoryPool, parse_bytes
        # per-query memory limit: TRINO_TPU_QUERY_MAX_MEMORY env (bytes,
        # B/kB/MB/GB suffixes) or the 64 GiB default; the session applies
        # its query_max_memory_mb property per query via set_limit
        env_limit = os.environ.get("TRINO_TPU_QUERY_MAX_MEMORY")
        self.pool = MemoryPool(parse_bytes(env_limit) if env_limit
                               else (64 << 30))
        self._node_bytes: Dict[int, int] = {}
        # host-spill survival chain (exec/spill.py): when a join/agg
        # reservation cannot fit even after revocation, the operator
        # retries partition-wise through the host/disk tier
        self.enable_spill = True
        self.spill_partitions = 8
        self.spill_force_disk = False     # tests/chaos: all spills to disk
        self.spiller = None               # lazy HostSpiller
        self._kill_reason: Optional[str] = None   # LowMemoryKiller's flag
        self._cancel_reason: Optional[str] = None  # terminate() fan-out
        self._no_decisions = 0            # >0: bypass the decision cache
                                          # (partition-wise spill phases)
        # executor-owned caches hold REVOCABLE reservations: under
        # pressure the pool asks this callback to spill them (drop; they
        # re-run or re-ingest on next use)
        self._revocation_handle = self.pool.register_revocation(
            self._revoke_caches, tag="executor-caches")
        # chunked-mode substitutions: id(plan node) -> precomputed Batch
        # (streamed scan chunk, pinned build side, or merged partials)
        self._subst: Dict[int, Batch] = {}
        # ids of substitutions whose batch is NOT derivable from the
        # node's structure key (worker split chunks, streamed driver
        # chunks, merged partials). Pinned deterministic builds are
        # structure-faithful and do NOT register here, so decision
        # caching stays live through the chunked build phase.
        self._subst_opaque: set = set()
        # bounded-memory aggregation: process scan chains in chunks of this
        # many rows (the spill-to-host analog; None = off)
        self.spill_chunk_rows: Optional[int] = None
        # Pallas MXU aggregation (ops/pallas_agg.py): "auto" picks it in
        # its measured win region (small-G direct aggregates past
        # MXU_AGG_MIN_GROUPS on TPU); "true"/"false" force
        self.enable_mxu_agg = "auto"
        # Pallas tiled-gather probe kernel (ops/pallas_gather.py):
        # "auto" = on for TPU backends; "true" forces it (interpret mode
        # off-TPU, which is how tier-1 exercises the kernel logic);
        # "false" = every site keeps its jnp.take path
        self.enable_pallas_gather = "auto"
        # Pallas VMEM hash-table kernel (ops/pallas_hash.py): hash
        # aggregation + hybrid hash join; same auto/true/false contract
        self.enable_pallas_hash = "auto"
        self.hash_table_slots = 0      # 0 = size from stats; tests pin
        # fused multiway star join (ops/pallas_hash.multiway_probe):
        # same auto/true/false contract; the planner consults its OWN
        # copy of the property when deciding to emit MultiJoinNode, this
        # one gates the executor's kernel-vs-ladder choice
        self.enable_multiway_join = "auto"
        self.multiway_max_dims = 5
        # resident-table budget for the fused pass, in KiB (per-dim
        # tables share one slot count; dims are dropped largest-first to
        # the pairwise path until the stack fits)
        self.multiway_vmem_kb = 8192
        # per-query record of the strategy each operator class actually
        # ran with (EXPLAIN `agg strategy:` lines, operator_stats column)
        self.strategy_decisions: Dict[str, str] = {}
        # session-property knobs (exec/session.py wires these per query)
        self.enable_dynamic_filtering = True
        self.enable_merge_join = True
        # zone-map scan pruning (exec/zonemap.py): skip decoding /
        # materializing row ranges the pushed-down scan predicate
        # provably cannot match. Advisory — the residual filter always
        # re-runs, so "off" is bit-exact with "on".
        self.enable_zone_map_pruning = True
        from .zonemap import DEFAULT_ZONE_ROWS
        self.zone_map_rows = DEFAULT_ZONE_ROWS
        # chunked-driver prefetch pipeline depth: how many decoded+staged
        # chunks may sit ahead of the device (0 = the serial loop)
        self.prefetch_depth = 2
        self.prewarm_chunks = False
        # seeded FailureInjector (server/failureinjector.py) for chaos
        # coverage of executor-side worker threads; None outside tests
        self.failure_injector = None
        self.deadline: Optional[float] = None     # time.monotonic() cutoff
        self.scan_cache_max_bytes = 24 << 30      # LRU cap (device bytes)
        self._scan_cache_bytes: Dict[tuple, int] = {}
        # zone-prune verdicts replayed on cache hits so EXPLAIN ANALYZE
        # still renders the scan line for a cached (pruned) batch
        self._scan_prune_info: Dict[tuple, str] = {}
        # build sides estimated above this stream chunk-wise through the
        # dense LUT instead of materializing on device (0/None = off)
        self.stream_build_bytes: Optional[int] = None
        # chunked-mode build results keyed by structural plan hash —
        # persists across query executions for deterministic sources;
        # cached batches keep their memory-pool reservation until evicted
        self._build_cache: Dict[str, Batch] = {}
        self._build_cache_bytes: Dict[str, int] = {}
        # chunk-mode state: inside the chunked driver loop every host
        # sync costs a tunnel round trip (~260 ms measured), so joins
        # build+validate their dense LUT once per pinned build and then
        # probe sync-free; compaction (which needs a row count) is
        # skipped for the loop's duration
        self.chunk_mode = False
        self._chunk_lut_cache: Dict[tuple, object] = {}
        # cross-run caches for the FUSED chunk pipeline: jitted per-chunk
        # programs keyed by plan-structure hash, and validated dense LUTs
        # keyed by (build structure, domain)
        self._fused_cache: Dict[str, object] = {}
        self._lut_cache: Dict[tuple, object] = {}
        # device-resident narrowed fact columns (exec/device_cache.py):
        # steady-state chunked scans slice HBM instead of re-streaming
        # the host link (~30 MB/s through this rig's tunnel)
        from .device_cache import FactTableCache
        self.fact_cache = FactTableCache()
        self.enable_fact_cache = True
        # cross-run DECISION cache: every data-dependent host decision
        # (join dup/oob validation, live counts for compaction capacity,
        # key-packing layouts) is a pure function of a deterministic
        # subtree, so its fetched integers are cached by structure key.
        # Steady-state re-execution then runs the whole plan as one
        # async dispatch chain with a single final result fetch — each
        # avoided sync is a ~100-260 ms tunnel round trip here.
        self._decision_cache: Dict[tuple, tuple] = {}
        # the decision cache persists to disk (keys are sha256 wire-form
        # hashes — stable across processes), so a FRESH process replays a
        # previous run's decisions: identical capacities/layouts mean the
        # persistent XLA code cache hits too, collapsing cold-start to
        # ingest + cached-program load. The reference's analog is the
        # long-lived JVM keeping ExpressionCompiler output warm
        # (sql/gen/ExpressionCompiler.java:38).
        self._decision_dirty = False
        self._decision_loaded = False
        # per-execution memo of build_structure_key: id(node) -> (node,
        # key). The node reference keeps temporaries alive so CPython
        # cannot reuse their id within one execution; cleared at query
        # start
        self._skey_memo: Dict[int, tuple] = {}

    # ------------------------------------------------------------------

    def _revoke_caches(self, target_bytes: int) -> int:
        """Revocation callback: evict cached build batches (revocable
        reservations) until the target is met. Evicted builds re-run on
        next use — correctness never depends on the cache."""
        freed = 0
        for key in list(self._build_cache):
            if freed >= target_bytes:
                break
            self._build_cache.pop(key, None)
            b = self._build_cache_bytes.pop(key, 0)
            self.pool.free_revocable(b, tag="build-cache")
            freed += b
        return freed

    def request_kill(self, reason: str) -> None:
        """Cluster LowMemoryKiller's hook: the next plan-node boundary
        raises MemoryKilledError (surfaced as QUERY_EXCEEDED_MEMORY)."""
        self._kill_reason = reason

    def request_cancel(self, reason: str) -> None:
        """terminate() fan-out's hook: the next cooperative check point
        raises QueryTerminatedError so a locally-executing query frees
        the exec lock within a bounded grace."""
        self._cancel_reason = reason

    def check_cancel(self) -> None:
        """Cooperative cancellation/deadline check, called between plan
        nodes (run()), between driver chunks (exec/chunked.py), between
        spill partitions (exec/spill.py), and by the prefetch pipeline —
        the boundaries where a stuck query can actually be stopped."""
        if self._kill_reason is not None:
            from .memory import MemoryKilledError
            raise MemoryKilledError(self._kill_reason)
        if self._cancel_reason is not None:
            raise QueryTerminatedError(self._cancel_reason)
        if self.deadline is not None:
            import time as _t
            if _t.monotonic() > self.deadline:
                raise QueryDeadlineError(
                    "query exceeded query_max_run_time_s")

    class _NoDecisions:
        def __init__(self, ex):
            self.ex = ex

        def __enter__(self):
            self.ex._no_decisions += 1

        def __exit__(self, *exc):
            self.ex._no_decisions -= 1
            return False

    def no_decisions(self) -> "Executor._NoDecisions":
        """Bypass the cross-run decision cache inside the block — the
        spill paths run the SAME plan node over per-partition data, so
        cached counts would poison replay."""
        return Executor._NoDecisions(self)

    def _scan_key(self, node) -> tuple:
        """Scan-cache key. The pushed-down predicate participates only
        when zone-map pruning is on: a pruned batch holds fewer rows than
        the full table, so it must never be served to a different
        predicate (or to the same scan with pruning disabled). Subclasses
        that re-cache a scan (e.g. the mesh executor's sharded placement)
        must use this same key so they replace the base entry instead of
        duplicating it."""
        pruning = node.predicate is not None and self.enable_zone_map_pruning
        return (node.catalog, node.schema_name, node.table,
                node.column_indices,
                repr(node.predicate) if pruning else None)

    def invalidate_scan_cache(self) -> None:
        """Drop cached scans AND their byte accounting together — clearing
        only the OrderedDict leaves ghost sizes that permanently shrink the
        effective LRU budget. Device-resident fact columns alias the same
        tables, so they drop too."""
        self._scan_cache.clear()
        self._scan_cache_bytes.clear()
        self._scan_prune_info.clear()
        self.fact_cache.invalidate()
        # decision values never cache for mutable catalogs, but clearing
        # costs nothing and removes any doubt after DML
        self._decision_cache.clear()

    def flush_metrics(self) -> None:
        """Mirror ExecStats deltas since the last flush into the process
        metrics registry (trino_tpu_exec_events_total{event=...}).
        ExecStats stays the cheap cumulative in-object view (bench and
        tests read it directly); the registry gets increments so
        /v1/metrics scrapes see the same counters fleet-wide. Guarded by
        its own lock (NOT the executor lock — flushing must never block
        behind a running query)."""
        import dataclasses

        from ..metrics import EXEC_EVENTS, OPERATOR_ROWS
        with _FLUSH_LOCK:
            cur = dataclasses.asdict(self.stats)
            prev = getattr(self, "_stats_flushed", {})
            for k, v in cur.items():
                d = v - prev.get(k, 0)
                if d:
                    EXEC_EVENTS.inc(d, event=k)
            d = cur["rows_scanned"] - prev.get("rows_scanned", 0)
            if d:
                OPERATOR_ROWS.inc(d, operator="scan")
            self._stats_flushed = cur

    def execute(self, root: L.OutputNode) -> Batch:
        assert isinstance(root, L.OutputNode)
        from .profiler import RECORDER
        RECORDER.bind_stats(self.stats)
        self._kill_reason = None
        self._cancel_reason = None
        self.strategy_decisions = {}
        # release reservations surviving from the previous query (the root
        # batch lives until its results are drained)
        for b in self._node_bytes.values():
            self.pool.free(b)
        self._node_bytes.clear()
        self._subst.clear()
        self._subst_opaque.clear()
        self._skey_memo.clear()
        try:
            if self.spill_chunk_rows:
                from .chunked import execute_chunked
                out = execute_chunked(self, root)
                if out is not None:
                    return out
            return self.run(root.child)
        finally:
            self.save_decisions()

    # TRINO_TPU_TRACE_NODES=1 prints per-node dispatch timings to stderr
    # (async dispatch time; sync waits inside a node attribute to it) —
    # the printf tier of EXPLAIN ANALYZE, usable when a query never
    # finishes
    TRACE = bool(os.environ.get("TRINO_TPU_TRACE_NODES"))

    def run(self, node: L.PlanNode) -> Batch:
        # bind this executor's stats to the dispatch thread so the
        # compile recorder attributes fresh XLA compiles here
        from .profiler import RECORDER
        RECORDER.bind_stats(self.stats)
        sub = self._subst.get(id(node))
        if sub is not None:
            return sub
        self.check_cancel()
        from .memory import ExceededMemoryLimitError, MemoryKilledError, \
            batch_bytes
        try:
            out = self._dispatch_timed(node)
            b = batch_bytes(out)
            self.pool.reserve(b)
        except MemoryKilledError:
            raise                         # the killer's verdict is final
        except ExceededMemoryLimitError:
            # memory-pressure survival: joins/aggregations retry through
            # the host-spill radix partitioner; anything else fails
            # cleanly as QUERY_EXCEEDED_MEMORY
            out = self._spill_retry(node)
            b = batch_bytes(out)
            self.pool.reserve(b)
        # memory accounting: reserve this node's output, release the
        # children's (their batches die once the parent has consumed them)
        # — the operator->query context pyramid collapsed to plan nodes
        self._node_bytes[id(node)] = b
        for c in L.children(node):
            if id(c) in self._subst:
                continue    # pinned (chunked-mode build/merge): lives on
            self.pool.free(self._node_bytes.pop(id(c), 0))
        return out

    def _spill_retry(self, node: L.PlanNode) -> Batch:
        """Retry a memory-failed Join/Aggregate partition-wise through
        the host-spill tier (exec/spill.py). The innermost failing
        operator spills first; if its shape is unsupported, the original
        error propagates so an enclosing operator (or the query
        boundary) handles it."""
        if not self.enable_spill or \
                not isinstance(node, (L.JoinNode, L.MultiJoinNode,
                                      L.AggregateNode, L.SortNode)):
            raise
        # drop this subtree's partial reservations from the failed
        # attempt; the spill path re-executes the children bounded
        self.release_path_reservations(node, keep=self._subst)
        from .spill import spill_aggregate, spill_join, spill_sort
        if isinstance(node, L.MultiJoinNode):
            # the spill tier partitions pairwise joins: reconstruct the
            # exact ladder the star fused and spill its top hop
            self._note_multijoin_degrade("spill", len(node.dims))
            out = spill_join(self, L.multijoin_to_ladder(node))
        elif isinstance(node, L.JoinNode):
            out = spill_join(self, node)
        elif isinstance(node, L.AggregateNode):
            out = spill_aggregate(self, node)
        else:
            out = spill_sort(self, node)
        if out is None:
            raise
        return out

    def _dispatch_timed(self, node: L.PlanNode) -> Batch:
        if self.TRACE:
            import sys
            import time as _t
            t0 = _t.monotonic()
            print(f"[trace] > {type(node).__name__}", file=sys.stderr,
                  flush=True)
            out = self.dispatch(node)
            print(f"[trace] < {type(node).__name__} "
                  f"{_t.monotonic() - t0:.1f}s", file=sys.stderr,
                  flush=True)
        elif self.profile:
            import time
            from .profiler import RECORDER
            c0 = RECORDER.thread_compile_seconds()
            t0 = time.monotonic()
            out = self.dispatch(node)
            t1 = time.monotonic()
            # fencing per node serializes XLA async dispatch, so profiled
            # times cover the node's own device work (OperatorStats role,
            # operator/OperatorStats.java:37). The fence splits wall into
            # components: device = time blocked on the fence, compile =
            # recorder-attributed compile seconds during the dispatch,
            # host = the dispatch remainder; the three sum to wall
            # exactly (the misattribution He et al. warn about — async
            # device time landing on whichever later op blocks — cannot
            # happen across a fence).
            jax.block_until_ready(out)
            t2 = time.monotonic()
            compile_s = min(max(RECORDER.thread_compile_seconds() - c0,
                                0.0), t1 - t0)
            device_s = t2 - t1
            host_s = (t1 - t0) - compile_s
            rows = int(jnp.sum(out.live))
            op = type(node).__name__
            self.node_stats[id(node)] = (t2 - t0, rows, device_s,
                                         host_s, compile_s)
            from ..metrics import (OPERATOR_COMPILE_MS,
                                   OPERATOR_DEVICE_MS, OPERATOR_ROWS)
            OPERATOR_ROWS.inc(rows, operator=op)
            OPERATOR_DEVICE_MS.inc(device_s * 1000, operator=op)
            if compile_s:
                OPERATOR_COMPILE_MS.inc(compile_s * 1000, operator=op)
        else:
            # always-on operator metrics: host dispatch wall only (device
            # work stays async — a per-node sync here would serialize the
            # whole pipeline, which is exactly what profile mode pays for)
            import time as _time
            t0 = _time.monotonic()
            out = self.dispatch(node)
            from ..metrics import OPERATOR_DISPATCHES, OPERATOR_WALL_MS
            op = type(node).__name__
            OPERATOR_DISPATCHES.inc(operator=op)
            OPERATOR_WALL_MS.inc((_time.monotonic() - t0) * 1000,
                                 operator=op)
        return out

    def build_structure_key(self, node: L.PlanNode) -> Optional[str]:
        """Cross-run cache key for a DETERMINISTIC build subtree: the
        wire-form hash (serde is canonical), or None when any scan
        reads a mutable catalog (memory tables change between runs)."""
        scans = [s for s in _subtree_scans(node)]
        if any(s.catalog not in ("tpch", "tpcds", "bench")
               for s in scans) or not scans:
            return None
        import hashlib
        from ..server import serde
        return hashlib.sha256(serde.dumps(node).encode()).hexdigest()

    def _decision_salt(self) -> tuple:
        """Session knobs that change runtime decision values for the
        SAME plan structure (dynamic filtering alters intermediate live
        counts, merge-join toggles which kernel's dup check runs)."""
        return (self.enable_dynamic_filtering, self.enable_merge_join,
                str(self.enable_mxu_agg), bool(self.stream_build_bytes),
                self.spill_chunk_rows, self.hash_mode() != "off",
                self.hash_table_slots, self.multiway_mode() != "off",
                self.multiway_vmem_kb)

    _DECISION_CACHE_FILE = "decisions.pkl"

    def _decision_path(self) -> Optional[str]:
        if os.environ.get("TRINO_TPU_DECISION_CACHE") == "0":
            return None
        from ..connectors.diskcache import cache_root
        return os.path.join(cache_root(), self._DECISION_CACHE_FILE)

    def _load_decisions(self) -> None:
        """Merge the on-disk decision cache in (once per executor).
        Entries exist only for immutable generator catalogs, so merging
        stale files is safe; corruption just means a cold start."""
        self._decision_loaded = True
        path = self._decision_path()
        if path is None or not os.path.isfile(path):
            return
        import pickle
        try:
            with open(path, "rb") as f:
                disk = pickle.load(f)
            for k, v in disk.items():
                self._decision_cache.setdefault(k, v)
        except Exception:
            pass

    # on-disk entry cap: this session's entries always survive; older
    # disk entries backfill up to the cap so the file can't grow without
    # bound across workloads (entries are ~150 B each)
    _DECISION_FILE_MAX = 65536

    def save_decisions(self) -> None:
        """Persist new decision values (atomic tmp+rename; merge with
        any concurrent writer's file first). The dirty flag clears only
        after a successful write so transient disk failures retry."""
        if not self._decision_dirty:
            return
        path = self._decision_path()
        if path is None:
            self._decision_dirty = False
            return
        import pickle
        try:
            merged = dict(self._decision_cache)
            if os.path.isfile(path):
                with open(path, "rb") as f:
                    for k, v in pickle.load(f).items():
                        if len(merged) >= self._DECISION_FILE_MAX:
                            break
                        merged.setdefault(k, v)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "wb") as f:
                pickle.dump(merged, f)
            os.replace(tmp, path)
            self._decision_dirty = False
        except Exception:
            pass

    def decisions_cacheable(self, node) -> bool:
        """May `node`'s runtime decision values go through the cross-run
        decision cache? Chunk mode bypasses (the driver chunk differs per
        iteration); an OPAQUE substitution anywhere in the subtree
        bypasses (per-split worker data, streamed chunks, merge batches
        carry data the structure key doesn't describe — split 2 of a
        worker task must not reuse split 1's counts). Structure-faithful
        substitutions (pinned deterministic builds) do NOT bypass."""
        if self.chunk_mode or self._no_decisions:
            return False
        if not self._subst_opaque:
            return True
        return not any(id(n) in self._subst_opaque
                       for n in _subtree_nodes(node))

    def fetch_ints(self, node, tag: str, *vals) -> tuple:
        """Fetch small device integers (validation flags, row counts,
        min/max stats) as host ints — through the cross-run decision
        cache when `node`'s subtree is deterministic. On a hit the
        blocking device round trip is skipped entirely; the device-side
        computation of `vals` was async-dispatched and is dead code XLA
        never waits on."""
        key = None
        if node is not None and self.decisions_cacheable(node):
            skey = self.memo_structure_key(node)
            if skey is not None:
                if not self._decision_loaded:
                    self._load_decisions()
                key = (tag, skey, self._decision_salt())
                hit = self._decision_cache.get(key)
                if hit is not None:
                    return hit
        out = tuple(int(v) for v in np.asarray(jnp.stack(
            [jnp.asarray(v).astype(jnp.int64) for v in vals])))
        if key is not None:
            if len(self._decision_cache) >= 4096:
                self._decision_cache.clear()
            self._decision_cache[key] = out
            self._decision_dirty = True
        return out

    def memo_structure_key(self, node: L.PlanNode) -> Optional[str]:
        """build_structure_key with a per-execution id(node) memo: a join
        makes several decision fetches against the same subtree and the
        serde+sha walk is O(subtree) host work each time. The memo holds
        the NODE too, not just its id — short-lived dataclasses.replace
        temporaries (packed-key joins) would otherwise free their id for
        reuse by a later temp, which would inherit the wrong key and
        poison the cross-run decision cache."""
        nid = id(node)
        hit = self._skey_memo.get(nid)
        if hit is not None:
            return hit[1]
        skey = self.build_structure_key(node)
        self._skey_memo[nid] = (node, skey)
        return skey

    def run_cached_build(self, node: L.PlanNode) -> Batch:
        """Execute a chunked-mode build subtree with a cross-run cache:
        the key is the subtree's wire-form hash (serde is canonical), so
        a re-planned but structurally identical build reuses the pinned
        device batch. Only deterministic generator catalogs participate
        (a memory-connector table can change between runs)."""
        key = self.build_structure_key(node)
        if key is None:
            return self.run(node)
        hit = self._build_cache.get(key)
        if hit is not None:
            return hit
        out = self.run(node)
        if len(self._build_cache) >= 8:      # bounded: drop eldest
            old = next(iter(self._build_cache))
            self._build_cache.pop(old)
            self.pool.free_revocable(
                self._build_cache_bytes.pop(old, 0), tag="build-cache")
        # transfer the reservation run() made from the per-query ledger
        # to the cache's REVOCABLE ledger: the batch outlives the query,
        # so the pool keeps counting it until eviction — but as spillable
        # bytes the revocation callback may reclaim under pressure
        from .memory import batch_bytes
        b = self._node_bytes.pop(id(node), None)
        if b is not None:
            self.pool.free(b)
        else:
            b = batch_bytes(out)
        self.pool.reserve_revocable(b, tag="build-cache")
        self._build_cache[key] = out
        self._build_cache_bytes[key] = b
        return out

    def release_all_reservations(self) -> None:
        """Free every per-node reservation (the distributed scheduler's
        merge path runs plan nodes without execute()'s per-query cleanup
        — under a small pool those leaked bytes starve later queries)."""
        for b in self._node_bytes.values():
            self.pool.free(b)
        self._node_bytes.clear()

    def release_path_reservations(self, node: L.PlanNode, keep) -> None:
        """Free reservations of `node`'s subtree (chunked mode: the
        per-chunk pipeline recomputes these next iteration). Nodes in
        `keep` (pinned substitutions) stay reserved."""
        if id(node) not in keep:
            self.pool.free(self._node_bytes.pop(id(node), 0))
            for c in L.children(node):
                self.release_path_reservations(c, keep)

    def dispatch(self, node: L.PlanNode) -> Batch:
        if isinstance(node, L.ScanNode):
            return self.run_scan(node)
        if isinstance(node, L.FilterNode):
            # fuse Filter over Project/Scan chains into one jit call
            pred = self.fold_scalars(node.predicate)
            if isinstance(node.child, L.ProjectNode):
                child = self.run(node.child.child)
                return filter_project_fused(
                    child, self.fold_scalars_tuple(node.child.exprs), pred)
            return apply_filter(self.run(node.child), pred)
        if isinstance(node, L.ProjectNode):
            exprs = self.fold_scalars_tuple(node.exprs)
            if isinstance(node.child, L.FilterNode):
                child = self.run(node.child.child)
                return filter_project(
                    child, self.fold_scalars(node.child.predicate), exprs)
            return filter_project(self.run(node.child), None, exprs)
        if isinstance(node, L.AggregateNode):
            return self.run_aggregate(node)
        if isinstance(node, L.JoinNode):
            return self.run_join(node)
        if isinstance(node, L.MultiJoinNode):
            return self.run_multijoin(node)
        if isinstance(node, L.WindowNode):
            return self.run_window(node)
        if isinstance(node, L.SortNode):
            keys = tuple((k.index, k.ascending, k.nulls_first)
                         for k in node.keys)
            child = self.run(node.child)
            # at scale, pack ORDER BY keys into one int64 so the sort
            # stays 2-operand (see SORT_SMALL_ROWS)
            if keys and child.capacity > SORT_SMALL_ROWS:
                from ..ops.sort import sort_batch_packed, sort_pack_plan
                plan = sort_pack_plan(
                    child, keys,
                    fetch=lambda *v: self.fetch_ints(node, "sortpack", *v))
                if plan is not None:
                    kmins, bits = plan
                    return sort_batch_packed(child, jnp.asarray(kmins),
                                             keys, bits, node.limit)
            return sort_batch(child, keys, node.limit)
        if isinstance(node, L.LimitNode):
            return limit_batch(self.run(node.child),
                               jnp.asarray(node.count, dtype=jnp.int64))
        if isinstance(node, L.OutputNode):
            return self.run(node.child)
        if isinstance(node, L.ValuesNode):
            return self.run_values(node)
        if isinstance(node, L.SetOpNode):
            return self.run_setop(node)
        if isinstance(node, L.UnnestNode):
            return self.run_unnest(node)
        raise NotImplementedError(type(node).__name__)

    def run_unnest(self, node: L.UnnestNode) -> Batch:
        """UNNEST expansion (operator/unnest/UnnestOperator.java:42):
        repeat each live row once per element of its array. Arrays are
        pool ids (types.py), so the expansion is a host-edge transform
        like the other pool operations — flat offsets are precomputed
        per pool, rows gather through np.repeat."""
        child = self.run(node.child)
        arrays, valids = batch_to_numpy(child)
        ids = arrays[node.array_col]
        id_valid = valids[node.array_col]
        pool = node.array_pool
        lengths = np.array([len(t) for t in pool], dtype=np.int64)
        flat = [v for t in pool for v in t]
        offsets = np.concatenate([[0], np.cumsum(lengths)])
        reps = np.where(id_valid, lengths[ids], 0)   # NULL array: 0 rows
        row_idx = np.repeat(np.arange(len(ids)), reps)
        within = np.arange(len(row_idx)) - np.repeat(
            np.cumsum(reps) - reps, reps)
        elem_pos = offsets[ids[row_idx]] + within
        elem_vals = [flat[int(p)] for p in elem_pos]
        elem_valid = np.array([v is not None for v in elem_vals],
                              dtype=np.bool_)
        t = node.element_dtype
        from ..types import TypeKind as TK
        if t.kind is TK.VARCHAR:
            index = {s: i for i, s in enumerate(node.element_pool or ())}
            elem = np.array([index.get(v, 0) for v in elem_vals],
                            dtype=np.int32)
        else:
            elem = np.array([v if v is not None else 0
                             for v in elem_vals], dtype=t.np_dtype)
        out_arrays = [a[row_idx] for a in arrays] + [elem]
        out_valids = [v[row_idx] for v in valids] + [elem_valid]
        if node.ordinality:
            out_arrays.append((within + 1).astype(np.int64))
            out_valids.append(np.ones(len(row_idx), dtype=np.bool_))
        return batch_from_numpy(out_arrays, valids=out_valids)

    def run_values(self, node: L.ValuesNode) -> Batch:
        if node.arrays:
            return batch_from_numpy(list(node.arrays),
                                    valids=list(node.valids))
        # zero-column values (SELECT without FROM): live mask only
        cap = pad_capacity(node.num_rows)
        live = np.zeros(cap, dtype=np.bool_)
        live[:node.num_rows] = True
        return Batch(columns=(), live=jnp.asarray(live))

    def run_setop(self, node: L.SetOpNode) -> Batch:
        left = remap_codes(self.run(node.left), node.left_remaps)
        right = remap_codes(self.run(node.right), node.right_remaps)
        if node.op == "union_all":
            return concat_batches(left, right)
        return self.run_setop_host(node.op, left, right)

    def run_setop_host(self, op: str, left: Batch, right: Batch) -> Batch:
        """DISTINCT/INTERSECT/EXCEPT variants, host-side. NULLs compare
        equal (set ops use IS NOT DISTINCT semantics, like GROUP BY)."""
        from collections import Counter
        la, lv = batch_to_numpy(left)
        ra, rv = batch_to_numpy(right)

        def rows_of(arrays, valids):
            n = len(arrays[0]) if arrays else 0
            return [tuple(arrays[j][i].item() if valids[j][i] else None
                          for j in range(len(arrays)))
                    for i in range(n)]

        lrows, rrows = rows_of(la, lv), rows_of(ra, rv)

        def dedup(rows):
            seen, out = set(), []
            for r in rows:
                if r not in seen:
                    seen.add(r)
                    out.append(r)
            return out

        if op == "union":
            out = dedup(lrows + rrows)
        elif op == "intersect":
            rset = set(rrows)
            out = [r for r in dedup(lrows) if r in rset]
        elif op == "intersect_all":
            rcount = Counter(rrows)
            used: Counter = Counter()
            out = []
            for r in lrows:
                if used[r] < rcount.get(r, 0):
                    used[r] += 1
                    out.append(r)
        elif op == "except":
            rset = set(rrows)
            out = [r for r in dedup(lrows) if r not in rset]
        elif op == "except_all":
            rcount = Counter(rrows)
            used = Counter()
            out = []
            for r in lrows:
                if used[r] < rcount.get(r, 0):
                    used[r] += 1
                else:
                    out.append(r)
        else:
            raise NotImplementedError(op)

        ncols = len(la)
        arrays = []
        valids = []
        for j in range(ncols):
            vals = [r[j] for r in out]
            valid = np.array([v is not None for v in vals], dtype=np.bool_)
            data = np.array([v if v is not None else 0 for v in vals],
                            dtype=la[j].dtype)
            arrays.append(data)
            valids.append(valid)
        if not arrays:
            live = np.zeros(pad_capacity(len(out)), dtype=np.bool_)
            live[:len(out)] = True
            return Batch(columns=(), live=jnp.asarray(live))
        return batch_from_numpy(arrays, valids=valids)

    # ------------------------------------------------------------------

    def run_scan(self, node: L.ScanNode) -> Batch:
        if node.catalog == "system" or \
                node.schema_name == "information_schema":
            # volatile introspection state: never scan-cache (a cached
            # batch would pin the first snapshot, and its dictionary
            # codes go stale against freshly planned decode scopes)
            data = self.catalog.get_table(node.catalog, node.schema_name,
                                          node.table)
            arrays = [data.columns[i] for i in node.column_indices]
            valids = None if data.valids is None else \
                [data.valids[i] for i in node.column_indices]
            self.stats.scans += 1
            self.stats.rows_scanned += data.num_rows
            return batch_from_numpy(arrays, valids=valids)
        pruning = node.predicate is not None and self.enable_zone_map_pruning
        key = self._scan_key(node)
        hit = self._scan_cache.get(key)
        if hit is not None:
            self._scan_cache.move_to_end(key)     # LRU touch
            info = self._scan_prune_info.get(key)
            if info is not None:
                self.strategy_decisions[f"TableScan[{node.table}]"] = info
            return hit
        data = self._scan_table_data(node, pruning)
        arrays = [data.columns[i] for i in node.column_indices]
        valids = None
        if data.valids is not None:
            valids = [data.valids[i] for i in node.column_indices]
        if pruning:
            arrays, valids, kept_rows = self._prune_scan_rows(
                node, data, arrays, valids)
        else:
            kept_rows = data.num_rows
        if sum(getattr(a, "nbytes", 0) for a in arrays) > (64 << 20):
            from .device_cache import warm_transfer_path
            warm_transfer_path()
        batch = batch_from_numpy(arrays, valids=valids)
        self.stats.scans += 1
        self.stats.rows_scanned += kept_rows
        # bounded scan cache: evict least-recently-scanned tables so a
        # long-lived server's device memory stays flat (the round-2 cache
        # pinned every table ever scanned)
        from .memory import batch_bytes
        b = batch_bytes(batch)
        total = sum(self._scan_cache_bytes.values())
        while self._scan_cache and total + b > self.scan_cache_max_bytes:
            old_key, _ = self._scan_cache.popitem(last=False)
            total -= self._scan_cache_bytes.pop(old_key, 0)
        self._scan_cache[key] = batch
        self._scan_cache_bytes[key] = b
        if pruning:
            dec = self.strategy_decisions.get(f"TableScan[{node.table}]")
            if dec is not None:
                self._scan_prune_info[key] = dec
        return batch

    def _scan_table_data(self, node: L.ScanNode, pruning: bool):
        """Fetch the table, preferring a connector-side pruned decode
        (ORC stripe / Parquet row-group skipping) when the scan carries a
        pushed predicate, the connector supports it, and the full table
        is not already decoded in its cache. Dictionary-encoded scan
        columns disqualify the pruned path: a pruned decode rebuilds
        string pools from surviving rows only, and those codes would
        not line up with the dictionaries the plan was analyzed against."""
        if pruning:
            try:
                conn = self.catalog.connector(node.catalog)
            except KeyError:
                conn = None
            if conn is not None and \
                    hasattr(conn, "get_table_pruned") and \
                    (node.schema_name, node.table) not in \
                    getattr(conn, "_cache", {}) and \
                    all(node.table_schema.fields[i].dictionary is None
                        for i in node.column_indices):
                from .zonemap import column_ranges
                ranges = column_ranges(node.predicate, node.column_indices,
                                       node.table_schema)
                if ranges:
                    try:
                        return conn.get_table_pruned(
                            node.schema_name, node.table, ranges)
                    except Exception:
                        pass      # fall back to the full decode
        return self.catalog.get_table(node.catalog, node.schema_name,
                                      node.table)

    def _prune_scan_rows(self, node: L.ScanNode, data, arrays, valids):
        """Drop row ranges the pushed predicate provably cannot match
        (zone-map evaluation); surviving ranges concatenate in order, so
        the post-residual-filter row stream is identical to the unpruned
        scan's."""
        from . import zonemap
        zm = zonemap.zone_map_for(data, self.zone_map_rows)
        idx = zonemap.surviving_zone_indices(zm, node.predicate,
                                             node.column_indices)
        pruned = zm.num_zones - len(idx)
        if pruned == 0:
            return arrays, valids, data.num_rows
        ranges = []
        for i in idx:
            s, c = zm.starts[i], zm.counts[i]
            if ranges and ranges[-1][0] + ranges[-1][1] == s:
                ranges[-1][1] += c
            else:
                ranges.append([s, c])

        def take(a):
            a = np.asarray(a)
            if not ranges:
                return a[:0]
            if len(ranges) == 1:
                s, c = ranges[0]
                return a[s:s + c]
            return np.concatenate([a[s:s + c] for s, c in ranges])

        kept_rows = sum(c for _, c in ranges)
        arrays = [take(a) for a in arrays]
        if valids is not None:
            valids = [None if v is None else take(v) for v in valids]
        self.stats.scan_zones_pruned += pruned
        self.stats.scan_rows_pruned += data.num_rows - kept_rows
        from ..metrics import SCAN_ZONES_PRUNED
        SCAN_ZONES_PRUNED.inc(pruned)
        self.strategy_decisions[
            f"TableScan[{node.table}]"] = \
            f"zone-pruned:{pruned}/{zm.num_zones}"
        return arrays, valids, kept_rows

    def run_window(self, node: L.WindowNode) -> Batch:
        from ..ops.window import WinSpec, window_compute
        child = self.run(node.child)
        keys = tuple((k.index, k.ascending, k.nulls_first)
                     for k in node.order_by)
        specs = tuple(WinSpec(s.func, s.arg, s.frame, s.offset, s.default)
                      for s in node.specs)
        return window_compute(child, node.partition_by, keys, specs)

    def run_aggregate(self, node: L.AggregateNode) -> Batch:
        aggs = tuple(AggSpec(
            a.func,
            a.arg.index if a.arg is not None else None,
            a.distinct)
            for a in node.aggs)
        child = self.run(node.child)
        return self.aggregate_batch(node, child, aggs)

    def gather_mode(self) -> str:
        """Resolved Pallas tiled-gather mode for this query: 'device' |
        'interpret' | 'off' (see ops/pallas_gather.resolve_mode)."""
        from ..ops.pallas_gather import resolve_mode
        return resolve_mode(self.enable_pallas_gather)

    def hash_mode(self) -> str:
        """Resolved Pallas hash-table mode: 'device' | 'interpret' |
        'off' (ops/pallas_hash.resolve_mode; interpret is the CPU/tier-1
        path, like the tiled gather's)."""
        from ..ops.pallas_hash import resolve_mode
        return resolve_mode(self.enable_pallas_hash)

    def multiway_mode(self) -> str:
        """Resolved fused multiway-join mode: 'device' | 'interpret' |
        'off' (ops/pallas_hash.resolve_mode — the same contract as the
        other Pallas kernels; interpret is the CPU/tier-1 path)."""
        from ..ops.pallas_hash import resolve_mode
        return resolve_mode(self.enable_multiway_join)

    def _note_multijoin_degrade(self, reason: str,
                                count: int = 1) -> None:
        """Count star dimensions degraded back to the pairwise ladder,
        per reason (kernel_off/vmem/dup/escape/dtype/mesh/spill)."""
        self.stats.multijoin_degrades += count
        from ..metrics import MULTIJOIN_DEGRADES
        MULTIJOIN_DEGRADES.inc(count, reason=reason)

    def _note_strategy(self, op: str, strategy: str, kind: str) -> None:
        """Record the strategy an operator actually ran with: the
        per-query EXPLAIN/operator_stats surface plus the
        {agg,join}_strategy_decisions counter families."""
        self.strategy_decisions[op] = strategy
        from ..metrics import (AGG_STRATEGY_DECISIONS,
                               JOIN_STRATEGY_DECISIONS)
        if kind == "agg":
            AGG_STRATEGY_DECISIONS.inc(strategy=strategy)
        else:
            JOIN_STRATEGY_DECISIONS.inc(strategy=strategy)

    # auto mxu_agg gate: the one-hot matmul kernel's HBM plane
    # materialization loses to the fused XLA reduction graph at q1's
    # G=6 (7.4ms vs 2.1ms, kernel docstring) but the XLA graph grows
    # linearly in G while the kernel stays one matmul pass — the
    # measured crossover sits near the top of the dense-domain range
    MXU_AGG_MIN_GROUPS = 12

    def use_mxu_agg(self, child: Batch, aggs, domains) -> bool:
        """Pallas MXU aggregation (ops/pallas_agg.py): TPU backend,
        sum/count aggregates over integer columns, small dense group
        domain. `mxu_agg` = auto picks it only in its measured win
        region (G >= MXU_AGG_MIN_GROUPS — the docstring documents it
        losing at the q1 shape); true/false force."""
        setting = str(self.enable_mxu_agg).lower()
        if setting in ("false", "0"):
            return False
        import jax as _jax
        if _jax.default_backend() != "tpu":
            return False
        from ..ops.pallas_agg import supports
        if not supports(aggs, domains):
            return False
        for a in aggs:
            if a.arg_index is not None and not jnp.issubdtype(
                    child.columns[a.arg_index].data.dtype, jnp.integer):
                return False
        if setting in ("true", "1"):
            return True
        g = 1
        for d in domains:
            g *= d
        return g >= self.MXU_AGG_MIN_GROUPS

    def aggregate_batch(self, node: L.AggregateNode, child: Batch, aggs):
        """One partial aggregation (the PARTIAL step)."""
        if node.strategy == "global":
            self._note_strategy("AggregateNode", "global", "agg")
            return global_aggregate(child, aggs)
        if node.strategy == "direct":
            if self.use_mxu_agg(child, aggs, node.key_domains):
                from ..ops.pallas_agg import direct_group_aggregate_mxu
                self.stats.mxu_agg_calls += 1
                self._note_strategy("AggregateNode", "mxu", "agg")
                return direct_group_aggregate_mxu(
                    child, node.group_keys, node.key_domains, aggs)
            self._note_strategy("AggregateNode", "direct", "agg")
            return direct_group_aggregate(child, node.group_keys,
                                          node.key_domains, aggs)
        if node.strategy == "hash":
            out = self.hash_aggregate(node, child, aggs)
            if out is not None:
                return out
            # kernel off / keys unpackable / value shape unsupported:
            # the sort path below is the general fallback
        capacity = node.out_capacity
        # planner NDV products overestimate real group counts by orders
        # of magnitude on join outputs, and the sorted kernel's key
        # readback gathers scale with OUT capacity — so once a run has
        # measured the true group count, later runs size the output
        # tightly from the decision cache (one recompile, then every
        # re-execution gathers at the real G instead of the estimate)
        if self.decisions_cacheable(node):
            skey = self.memo_structure_key(node)
            if skey is not None and not self._decision_loaded:
                self._load_decisions()
            known = self._decision_cache.get(
                ("aggfinal", skey, self._decision_salt())) \
                if skey is not None else None
            if known is not None:
                capacity = max(1024, bucket_capacity(known[0]))
        # big inputs: pack all keys into one int64 so the sort has 2
        # operands — the general kernel's 2-per-key operand count makes
        # XLA TPU compiles explode at scale (see SORT_COMPILE_BUDGET)
        pack = None
        # pack when rows are big OR the key list is wide: the general
        # kernel sorts ~2 operands per key and XLA TPU sort compiles
        # explode in operand count at ANY row count (q10's 7-key GROUP
        # BY was a >900s compile at 131k rows)
        wide_keys = 2 * len(node.group_keys) + 4 > MAX_SORT_OPERANDS
        if not any(a.distinct for a in aggs) and node.group_keys and \
                (child.capacity > SORT_SMALL_ROWS or wide_keys):
            from ..ops.aggregate import (key_pack_plan_words,
                                         packed_sort_group_aggregate)
            pack = key_pack_plan_words(
                child, node.group_keys,
                fetch=lambda *v: self.fetch_ints(node, "aggpack", *v))
        self._note_strategy("AggregateNode", "sort", "agg")
        gm = self.gather_mode()
        while True:
            if pack is not None:
                kmins, bits, splits = pack
                out = packed_sort_group_aggregate(
                    child, jnp.asarray(kmins), node.group_keys, bits,
                    aggs, capacity, splits, gm)
            else:
                out = sort_group_aggregate(child, node.group_keys, aggs,
                                           capacity, gm)
            n_groups = self.fetch_ints(node, f"agggroups{capacity}",
                                       jnp.sum(out.live))[0]
            if n_groups < capacity or capacity >= child.capacity:
                break
            capacity *= 4
            self.stats.agg_capacity_retries += 1
        if self.decisions_cacheable(node):
            skey = self.memo_structure_key(node)
            if skey is not None:
                self._decision_cache[
                    ("aggfinal", skey, self._decision_salt())] = (n_groups,)
                self._decision_dirty = True
        if n_groups == 0 and not node.group_keys:
            # zero-key sort aggregation (global DISTINCT) over an empty
            # input: SQL still requires one output row (0 counts / NULL
            # sums) — duplicates are irrelevant on empty input, so the
            # plain global kernel supplies it
            plain = tuple(AggSpec(a.func, a.arg_index) for a in aggs)
            return global_aggregate(child, plain)
        return out

    # ---- hash aggregation (ops/pallas_hash.py) -----------------------

    def hash_aggregate(self, node: L.AggregateNode, child: Batch,
                       aggs) -> Optional[Batch]:
        """Strategy 'hash': the VMEM hash-table kernel with the
        escape -> radix-partition -> re-enter degradation chain. The
        group-count estimate sizes the table (the decision cache's
        measured count on re-execution, the planner estimate first
        time). None = shape unsupported; caller runs the sort path."""
        est = node.out_capacity
        if self.decisions_cacheable(node):
            skey = self.memo_structure_key(node)
            if skey is not None and not self._decision_loaded:
                self._load_decisions()
            known = self._decision_cache.get(
                ("aggfinal", skey, self._decision_salt())) \
                if skey is not None else None
            if known is not None:
                est = max(1, known[0])
        out = self.try_hash_group_agg(child, node.group_keys, aggs,
                                      est, node=node)
        if out is None:
            return None
        self._note_strategy("AggregateNode", "hash", "agg")
        return out

    def try_hash_group_agg(self, child: Batch, keys: tuple, aggs,
                           est_groups: int,
                           node=None) -> Optional[Batch]:
        """One hash aggregation over `child` grouped by `keys`:
        kernel-first, and on overflow escape the batch radix-partitions
        by the spill tier's splitmix64 key hash so every group lands
        wholly inside one partition and each partition re-enters the
        kernel (still-escaping partitions finish on the sort kernel —
        exact either way). Used for both the PARTIAL step and the
        hash-partial FINAL merge. None = ineligible."""
        from ..ops import pallas_hash as ph
        mode = self.hash_mode()
        if mode == "off" or not keys:
            return None
        if not ph.supports_aggs(child, aggs) or \
                any(a.distinct for a in aggs):
            return None
        from ..ops.aggregate import key_pack_plan
        pack = key_pack_plan(
            child, keys,
            fetch=(lambda *v: self.fetch_ints(node, "hashpack", *v))
            if node is not None else None)
        if pack is None:
            return None                  # unpackable keys: sort path
        kmins, bits = pack
        cap = ph.max_table_slots(aggs)
        if self.hash_table_slots:
            t = ph.MIN_TABLE_SLOTS
            while t * 2 <= min(self.hash_table_slots, cap):
                t *= 2
            slots, fits = t, True        # pinned size: escapes decide
        else:
            slots, fits = ph.pick_table_slots(max(1, int(est_groups)),
                                              aggs)
        self.stats.hash_agg_calls += 1
        kmins_d = jnp.asarray(kmins)
        if fits:
            out, esc, occ = ph.hash_group_aggregate(
                child, kmins_d, keys, bits, aggs, slots, mode)
            esc_h, n_groups = self.fetch_ints(
                node, f"hashagg{slots}", esc, occ)
            if esc_h == 0:
                if node is not None and self.decisions_cacheable(node):
                    skey = self.memo_structure_key(node)
                    if skey is not None:
                        self._decision_cache[
                            ("aggfinal", skey,
                             self._decision_salt())] = (n_groups,)
                        self._decision_dirty = True
                return out
        self.stats.hash_agg_escapes += 1
        return self._partitioned_hash_agg(child, keys, aggs, kmins_d,
                                          bits, est_groups, slots, mode)

    def _partitioned_hash_agg(self, child: Batch, keys: tuple, aggs,
                              kmins_d, bits: tuple, est_groups: int,
                              slots: int, mode: str) -> Batch:
        """The escape path: radix-partition the batch host-side with
        the SAME splitmix64 partitioner the host-spill tier uses
        (exec/spill._partition_ids), so a partition that later spills
        under memory pressure is already kernel-shaped. Groups never
        straddle partitions, so per-partition results concatenate
        exactly."""
        from ..batch import batch_from_numpy, batch_to_numpy, \
            bucket_capacity
        from ..ops import pallas_hash as ph
        from ..ops.aggregate import sort_group_aggregate
        from .spill import _partition_ids
        arrs, vals = batch_to_numpy(child)
        n = len(arrs[0]) if arrs else 0
        load = ph.LOAD_NUM / ph.LOAD_DEN
        want = max(2, -(-int(max(est_groups, 1)) //
                        max(1, int(slots * load))))
        count = 2
        while count < want and count < 256:
            count *= 2
        part = _partition_ids(arrs, vals, keys, count)
        outs: List[tuple] = []
        with self.no_decisions():
            for p in range(count):
                m = part == p
                if not m.any():
                    continue
                pb = batch_from_numpy([a[m] for a in arrs],
                                      valids=[v[m] for v in vals])
                out, esc, _occ = ph.hash_group_aggregate(
                    pb, kmins_d, keys, bits, aggs, slots, mode)
                if int(esc) > 0:
                    # still too many groups in this partition (skew):
                    # the sort kernel finishes it — groups are disjoint
                    # across partitions either way
                    out = sort_group_aggregate(
                        pb, keys, aggs, bucket_capacity(int(m.sum())),
                        self.gather_mode())
                oa, ov = batch_to_numpy(out)
                if oa and len(oa[0]):
                    outs.append((oa, ov))
        if not outs:
            empty = batch_from_numpy(
                [np.zeros(0, np.asarray(a).dtype) for a in arrs],
                valids=[np.zeros(0, np.bool_) for _ in arrs])
            # shape the empty output like the kernel's (keys + states)
            out, _e, _o = ph.hash_group_aggregate(
                empty, kmins_d, keys, bits, aggs, ph.MIN_TABLE_SLOTS,
                mode)
            return out
        ncols = len(outs[0][0])
        return batch_from_numpy(
            [np.concatenate([o[0][j] for o in outs])
             for j in range(ncols)],
            valids=[np.concatenate([o[1][j] for o in outs])
                    for j in range(ncols)])

    def merge_group_aggregate(self, node: L.AggregateNode,
                              merged: Batch, merge_aggs,
                              capacity: int) -> Batch:
        """FINAL merge of grouped partial states (keys at 0..n_keys-1,
        mergeable states after): hash-partial merge when the operator's
        gate picked hash and the partial batch qualifies, the sort
        merge otherwise — shared by the chunked driver's PartialState
        and the spill tier's partial pages."""
        from ..ops.aggregate import sort_group_aggregate
        n_keys = len(node.group_keys)
        if node.strategy == "hash":
            out = self.try_hash_group_agg(merged, tuple(range(n_keys)),
                                          merge_aggs, capacity)
            if out is not None:
                return out
        return sort_group_aggregate(merged, tuple(range(n_keys)),
                                    merge_aggs, capacity,
                                    self.gather_mode())

    # ---- uncorrelated scalar subqueries (fold to constants) ----------

    def fold_scalars(self, expr):
        """Replace ScalarSubqueryRef / InSubqueryRef with computed
        constants before tracing (Trino runs uncorrelated subqueries as
        separate stages; here the subplan executes eagerly and memoized)."""
        if expr is None:
            return None
        has_sub = any(isinstance(e, (ir.ScalarSubqueryRef,
                                     ir.InSubqueryRef))
                      for e in ir.walk(expr))
        if not has_sub:
            return expr

        def fn(e):
            if isinstance(e, ir.ScalarSubqueryRef):
                return ir.Literal(self.scalar_value(e), e.dtype)
            if isinstance(e, ir.InSubqueryRef):
                return self.fold_in_subquery(e)
            return None
        return ir.transform(expr, fn)

    def fold_in_subquery(self, ref: ir.InSubqueryRef) -> ir.Expr:
        """Execute the subquery and fold x IN (...) to an InList, mapping
        varchar values into the probe's dictionary and injecting Kleene
        NULL when the subquery produced one (x IN S is NULL for unmatched
        x when S contains NULL)."""
        if ref not in self._scalar_cache:
            batch = self.run(ref.plan)
            arrays, valids = batch_to_numpy(batch)
            vals, has_null = [], False
            arg_t = ref.arg.dtype
            from ..types import TypeKind as TK
            for v, ok in zip(arrays[0], valids[0]):
                if not ok:
                    has_null = True
                    continue
                v = v.item() if hasattr(v, "item") else v
                if arg_t.kind is TK.VARCHAR:
                    # translate through pools: sub code -> string -> probe
                    s = ref.sub_field.dictionary[int(v)]
                    pool = ref.arg_field.dictionary if ref.arg_field \
                        else None
                    if pool is None or s not in pool:
                        continue            # absent: can never match
                    v = pool.index(s)
                vals.append(v)
            self._scalar_cache[ref] = (tuple(sorted(set(vals))), has_null)
        vals, has_null = self._scalar_cache[ref]
        folded: ir.Expr = ir.InList(
            ref.arg, tuple(ir.Literal(v, ref.arg.dtype) for v in vals))
        if has_null:
            from ..types import BOOLEAN
            folded = ir.Logical("or", (folded,
                                       ir.Literal(None, BOOLEAN)))
        return folded

    def fold_scalars_tuple(self, exprs):
        return tuple(self.fold_scalars(e) for e in exprs)

    def scalar_value(self, ref: ir.ScalarSubqueryRef):
        # keyed by the ref itself (hashes by plan identity) so the cache
        # keeps the plan object alive — id() reuse cannot alias entries
        if ref not in self._scalar_cache:
            batch = self.run(ref.plan)
            arrays, valids = batch_to_numpy(batch)
            if len(arrays[0]) > 1:
                raise RuntimeError(
                    "scalar subquery returned more than one row")
            if len(arrays[0]) == 0 or not bool(valids[0][0]):
                val = None
            else:
                v = arrays[0][0]
                val = v.item() if hasattr(v, "item") else v
            self._scalar_cache[ref] = val
        return self._scalar_cache[ref]

    # compact when live rows fit in 1/SHRINK of capacity: every dead lane
    # still pays full price in the join's random gathers, while compaction
    # itself is cheap (ascending-index gathers are quasi-sequential HBM)
    COMPACT_SHRINK = 2

    def maybe_compact(self, batch: Batch,
                      live: Optional[int] = None,
                      node: Optional[L.PlanNode] = None) -> Batch:
        """Compact when live rows shrank enough. `live` should be passed
        when the caller already synced a row count (join totals): the
        device round trip for jnp.sum is ~60ms over a tunneled chip, so
        every avoidable sync matters to end-to-end latency. `node` keys
        the cross-run decision cache when the count must be fetched."""
        if live is None:
            if batch.capacity < (1 << 16):
                return batch          # too small for compaction to pay
            if self.chunk_mode:
                return batch          # the chunked loop stays sync-free:
                                      # a row-count fetch is ~260 ms here
            live = self.fetch_ints(node, "complive",
                                   jnp.sum(batch.live))[0]
        new_cap = bucket_capacity(live)
        if new_cap * self.COMPACT_SHRINK <= batch.capacity:
            self.stats.dynamic_filter_compactions += 1
            return compact_batch(batch, new_cap)
        return batch

    def run_join(self, node: L.JoinNode) -> Batch:
        probe = self.run(node.left)
        # oversized build sides stream chunk-wise into the dense LUT
        # instead of materializing on device (spill tier v2; the decision
        # must precede running the build child)
        if self.stream_build_bytes:
            est = self._estimate_build_bytes(node.right)
            if est is not None and est > self.stream_build_bytes:
                from .chunked import streaming_build_join
                out = streaming_build_join(self, node, probe)
                if out is not None:
                    return out
        build = self.run(node.right)
        # >2-column keys (or values past 2^31) overflow the kernels'
        # fixed 32-bit-per-column packing: range-compress both sides'
        # keys into ONE appended int64 column (shared min/max so equality
        # is preserved), run the join single-key, strip the extras after
        packed = self.pack_join_keys(probe, build, node.left_keys,
                                     node.right_keys, node=node)
        if packed is not None:
            probe2, build2, pk, bk = packed
            import dataclasses as _dc
            residual2 = node.residual
            if residual2 is not None:
                # kernel layout gains the packed column after the probe
                # columns: shift build-side references right by one
                n_probe = len(probe.columns)

                def _shift(e):
                    if isinstance(e, ir.ColumnRef) and \
                            e.index >= n_probe:
                        return ir.ColumnRef(e.index + 1, e.dtype, e.name)
                    return None
                residual2 = ir.transform(node.residual, _shift)
            node2 = _dc.replace(node, left_keys=pk, right_keys=bk,
                                residual=residual2,
                                build_key_domain=None)
            out = self._run_join_inner(node2, probe2, build2)
            return _strip_packed_columns(out, node, len(probe.columns),
                                         len(build.columns))
        return self._run_join_inner(node, probe, build)

    def _estimate_build_bytes(self, node: L.PlanNode) -> Optional[int]:
        """Size of a Scan/Filter(Scan) build side, for the streaming
        decision (shape must match streaming_build_join's support)."""
        scan = node.child if isinstance(node, L.FilterNode) else node
        if not isinstance(scan, L.ScanNode):
            return None
        try:
            rows = self.catalog.get_table(scan.catalog, scan.schema_name,
                                          scan.table).num_rows
        except Exception:        # noqa: BLE001 — stats probe only
            return None
        return rows * max(1, len(scan.column_indices)) * 8

    def pack_join_keys(self, probe: Batch, build: Batch, pkeys, bkeys,
                       node=None):
        """None when the fixed 32-bit packing is safe (<=2 in-range
        columns); else (probe', build', probe_keys', build_keys') with
        one range-compressed key column appended to each side."""
        if len(pkeys) <= 1:
            return None
        if len(pkeys) == 2:
            # the fixed packing is fine when trailing key values fit 31
            # bits — ONE fused fetch for the check
            stats = []
            for side, keys in ((build, bkeys), (probe, pkeys)):
                for ki in keys[1:]:
                    col = side.columns[ki]
                    m = side.live & col.valid
                    d = col.data.astype(jnp.int64)
                    stats.append(jnp.min(jnp.where(m, d, 0)))
                    stats.append(jnp.max(jnp.where(m, d, 0)))
            vals = self.fetch_ints(node, "jpack31", *stats)
            if all(0 <= int(vals[i]) and int(vals[i + 1]) < (1 << 31)
                   for i in range(0, len(vals), 2)):
                return None
        stats = []
        big = jnp.iinfo(jnp.int64)
        for side, keys in ((probe, pkeys), (build, bkeys)):
            for ki in keys:
                col = side.columns[ki]
                m = side.live & col.valid
                d = col.data.astype(jnp.int64)
                stats.append(jnp.min(jnp.where(m, d, big.max)))
                stats.append(jnp.max(jnp.where(m, d, big.min)))
        vals = self.fetch_ints(node, "jpack", *stats)
        k = len(pkeys)
        kmins, bits, total = [], [], 0
        for i in range(k):
            lo = min(int(vals[2 * i]), int(vals[2 * (k + i)]))
            hi = max(int(vals[2 * i + 1]), int(vals[2 * (k + i) + 1]))
            if hi < lo:
                lo, hi = 0, 0
            b = max(2, int(hi - lo + 3).bit_length())
            kmins.append(lo)
            bits.append(b)
            total += b
        if total > 62:
            raise RuntimeError(
                "multi-column join key spans exceed 62 packed bits")
        kmins_d = jnp.asarray(np.asarray(kmins, dtype=np.int64))
        bits = tuple(bits)
        probe2 = _append_packed_key(probe, kmins_d, pkeys, bits)
        build2 = _append_packed_key(build, kmins_d, bkeys, bits)
        return (probe2, build2, (len(probe.columns),),
                (len(build.columns),))

    def _run_join_inner(self, node: L.JoinNode, probe: Batch,
                        build: Batch) -> Batch:
        probe = self.apply_dynamic_filter(node, probe, build)
        if node.kind == "mark":
            return self.run_mark_join(node, probe, build)
        if node.kind in ("semi", "anti"):
            return self.run_membership_join(node, probe, build)
        probe = self.maybe_compact(probe, node=node)
        domain = node.build_key_domain
        if node.build_unique:
            out = self.try_unique_join(node, probe, build, domain)
            if out is not None:
                return out            # already compacted (fused sync)
            # planner's uniqueness proof was wrong — degrade gracefully
            self.stats.join_fallbacks += 1
        cap = probe.capacity
        while True:
            out, total, oob = join_expand(probe, build, node.left_keys,
                                          node.right_keys, node.kind,
                                          cap, domain)
            total, oob = self.fetch_ints(node, f"expand{cap}:{domain}",
                                         total, oob)
            if oob > 0:             # stale stats: keys escaped the domain
                domain = None
                self.stats.join_domain_fallbacks += 1
                continue
            if total <= cap:
                self._note_strategy("JoinNode", "expand", "join")
                # `total` IS the live row count: reuse it instead of
                # paying a second device sync inside maybe_compact
                return self.maybe_compact(out, live=total) \
                    if node.kind == "inner" else out
            cap = bucket_capacity(total)  # coarse: caches across runs
            self.stats.join_expansion_retries += 1

    def try_unique_join(self, node: L.JoinNode, probe: Batch,
                        build: Batch, domain) -> Optional[Batch]:
        """Unique-build fast paths. inner/left take the gather-free
        sort-merge kernel (the fastest primitive on TPU is the sort
        network); dense LUT / sorted probing remain for membership and
        wide-row fallbacks. None = build had duplicate keys (caller
        expands)."""
        # Compile-cost gate for the multi-operand merge sort, measured in
        # SORT OPERAND-ELEMENTS (rows x sort operands, where each column
        # contributes data+valid operands). Measured on v5e: ~240M
        # operand-elements compile in ~2 min, ~190M in the merge kernel
        # ran past 10 MINUTES (its flood scans compound the sort), while
        # <64M compiles in tens of seconds. Above the gate the dense-LUT
        # /gather path carries the join: it compiles in seconds at any
        # size (9.4s at 60M measured) and runs at gather speed.
        # chunk mode: build+validate the dense LUT once per pinned build,
        # then probe every chunk sync-free (see _chunk_lut_join)
        if self.chunk_mode and domain is not None and \
                node.kind in ("inner", "left"):
            out = self._chunk_lut_join(node, probe, build, domain)
            if out is not None:
                return out
        gm = self.gather_mode()
        if gm != "off":
            self.stats.pallas_gather_calls += 1
        n_sort_ops = 2 * (len(probe.columns) + len(build.columns)) + 4
        merge_ok = self.enable_merge_join and \
            n_sort_ops <= MAX_SORT_OPERANDS and \
            (probe.capacity + build.capacity) <= SORT_SMALL_ROWS
        # every branch fuses (dup[, oob], live-count) into ONE device
        # fetch, then compacts with the known count — one tunnel round
        # trip per join instead of three
        if node.kind in ("inner", "left") and merge_ok and \
                len(probe.columns) <= 63 and len(build.columns) <= 63:
            out, dup = join_unique_build_merge(
                probe, build, node.left_keys, node.right_keys, node.kind)
            dup, live = self.fetch_ints(node, "jmerge", dup,
                                        jnp.sum(out.live))
            if dup == 0:
                self._note_strategy("JoinNode", "sort-merge", "join")
                return self.maybe_compact(out, live=live)
            return None
        if domain is not None:
            if node.kind == "inner" and probe.capacity > SORT_SMALL_ROWS:
                # two-phase: probe the LUT, THEN decide — a selective
                # join compacts matched rows before paying per-column
                # build gathers at full probe capacity (gathers are the
                # dense join's whole cost)
                from ..ops.join import dense_join_compacted, dense_probe
                src, matched, dup, oob, live = dense_probe(
                    probe, build, node.left_keys, node.right_keys,
                    domain)
                dup, oob, live = self.fetch_ints(
                    node, f"jdense2:{domain}", dup, oob, live)
                if oob == 0:
                    if dup != 0:
                        return None
                    self._note_strategy("JoinNode", "dense-lut", "join")
                    new_cap = bucket_capacity(live)
                    if new_cap * self.COMPACT_SHRINK <= probe.capacity:
                        self.stats.dynamic_filter_compactions += 1
                        return dense_join_compacted(
                            probe, src, matched, build, node.left_keys,
                            node.right_keys, new_cap, gm)
                    out, dup2, oob2 = join_unique_build_dense(
                        probe, build, node.left_keys, node.right_keys,
                        node.kind, domain, gm)
                    return out
                self.stats.join_domain_fallbacks += 1
            else:
                out, dup, oob = join_unique_build_dense(
                    probe, build, node.left_keys, node.right_keys,
                    node.kind, domain, gm)
                dup, oob, live = self.fetch_ints(
                    node, f"jdense:{domain}", dup, oob,
                    jnp.sum(out.live))
                if oob == 0:
                    if dup != 0:
                        return None
                    self._note_strategy("JoinNode", "dense-lut", "join")
                    return self.maybe_compact(out, live=live)
                self.stats.join_domain_fallbacks += 1
        # sparse key domain (no dense LUT): the hybrid hash join beats
        # the sorted fallback's ~24 serial searchsorted gather rounds
        status, hout = self.try_hash_join(node, probe, build,
                                          allow_dup=False)
        if status == "ok":
            return hout
        if status == "dup":
            return None                # caller expands (dup build keys)
        out, dup = join_unique_build(probe, build, node.left_keys,
                                     node.right_keys, node.kind)
        dup, live = self.fetch_ints(node, "jsorted", dup,
                                    jnp.sum(out.live))
        if dup == 0:
            self._note_strategy("JoinNode", "sorted", "join")
            return self.maybe_compact(out, live=live)
        return None

    def try_hash_join(self, node: L.JoinNode, probe: Batch,
                      build: Batch, allow_dup: bool):
        """Hybrid hash join (ops/pallas_hash.py): build side hashed into
        the VMEM kernel table (min(row_id) per key), probe walks the
        linear chains with pallas_gather-fused plane gathers. When the
        build exceeds the table's load cap, degrade partition-by-
        partition to the host equi-join over the SAME splitmix64 radix
        fanout the spill tier uses — spilled partitions are already
        kernel-shaped.

        Returns (status, batch): 'ok' = joined; 'dup' = build broke the
        uniqueness contract (caller falls back to the expansion join);
        'skip' = shape unsupported (caller continues down its ladder)."""
        from ..ops import pallas_hash as ph
        mode = self.hash_mode()
        if mode == "off" or node.kind not in ("inner", "left", "semi",
                                              "anti") or \
                node.residual is not None or node.null_aware:
            return "skip", None
        # the partitioned degrade needs integer-typed keys host-side
        for side, keys in ((probe, node.left_keys),
                           (build, node.right_keys)):
            for k in keys:
                dt = side.columns[k].data.dtype
                if not (jnp.issubdtype(dt, jnp.integer) or
                        dt == jnp.bool_):
                    return "skip", None
        slots, fits = ph.join_table_slots(build.capacity)
        if self.hash_table_slots:
            t = ph.MIN_TABLE_SLOTS
            while t * 2 <= min(self.hash_table_slots,
                               ph.MAX_TABLE_SLOTS):
                t *= 2
            slots = t
            fits = t * ph.LOAD_NUM // ph.LOAD_DEN >= build.capacity
        self.stats.hash_join_calls += 1
        if fits:
            # chunk mode: build + validate ONCE per pinned build, probe
            # every chunk sync-free (the dense LUT's caching policy)
            ckey = (id(node), "hash", slots)
            rec = self._chunk_lut_cache.get(ckey) if self.chunk_mode \
                else None
            if rec is None:
                tkl, tkh, src, dup, esc = ph.build_join_table(
                    build, node.right_keys, slots, mode)
                dup_h, esc_h = self.fetch_ints(
                    node, f"hashbuild{slots}", dup, esc)
                rec = (tkl, tkh, src, dup_h, esc_h)
                if self.chunk_mode:
                    self._chunk_lut_cache[ckey] = rec
            tkl, tkh, src, dup_h, esc_h = rec
            if esc_h == 0:
                if dup_h > 0 and not allow_dup:
                    return "dup", None
                out = ph.hash_join_probe(
                    probe, build, tkl, tkh, src, node.left_keys,
                    node.right_keys, node.kind, self.gather_mode())
                self._note_strategy("JoinNode", "hybrid-hash", "join")
                if node.kind == "inner" and not self.chunk_mode:
                    live = self.fetch_ints(node, "hashjoinlive",
                                           jnp.sum(out.live))[0]
                    out = self.maybe_compact(out, live=live)
                return "ok", out
        self.stats.hash_join_escapes += 1
        out = self._partitioned_hash_join(node, probe, build)
        if out is None:
            return "skip", None
        self._note_strategy("JoinNode", "hybrid-hash", "join")
        return "ok", out

    def _partitioned_hash_join(self, node: L.JoinNode, probe: Batch,
                               build: Batch) -> Optional[Batch]:
        """Graceful degradation ("Design Trade-offs for a Robust
        Dynamic Hybrid Hash Join"): both sides radix-partition by the
        exchange's splitmix64 hash and each partition joins alone
        through the host equi-join the spill tier already proves
        bit-exact (exec/spill._host_equi_join). Handles duplicate build
        keys by expansion, so the unique-build contract cannot be
        violated here."""
        from ..batch import batch_from_numpy, batch_to_numpy
        from .spill import _host_equi_join, _partition_ids
        parrs, pvalids = batch_to_numpy(probe)
        barrs, bvalids = batch_to_numpy(build)
        from ..ops import pallas_hash as ph
        load_cap = ph.MAX_TABLE_SLOTS * ph.LOAD_NUM // ph.LOAD_DEN
        want = max(2, -(-len(barrs[0]) // load_cap)) if barrs else 2
        count = 2
        while count < want and count < 256:
            count *= 2
        part_p = _partition_ids(parrs, pvalids, node.left_keys, count)
        part_b = _partition_ids(barrs, bvalids, node.right_keys, count)
        outs: List[list] = []
        outs_v: List[list] = []
        for p in range(count):
            mp = part_p == p
            mb = part_b == p
            if not mp.any():
                continue
            arrs, vals = _host_equi_join(
                [a[mp] for a in parrs], [v[mp] for v in pvalids],
                [a[mb] for a in barrs], [v[mb] for v in bvalids],
                node.left_keys, node.right_keys, node.kind)
            if arrs and len(arrs[0]):
                outs.append(arrs)
                outs_v.append(vals)
        if not outs:
            out_arrs = []
            out_valids = []
            srcs = list(probe.columns)
            if node.kind in ("inner", "left"):
                srcs += list(build.columns)
            for c in srcs:
                out_arrs.append(np.zeros(0, np.asarray(c.data).dtype))
                out_valids.append(np.zeros(0, np.bool_))
            return batch_from_numpy(out_arrs, valids=out_valids)
        ncols = len(outs[0])
        return batch_from_numpy(
            [np.concatenate([o[j] for o in outs]) for j in range(ncols)],
            valids=[np.concatenate([o[j] for o in outs_v])
                    for j in range(ncols)])

    def _chunk_lut_join(self, node: L.JoinNode, probe: Batch,
                        build: Batch, domain: int) -> Optional[Batch]:
        """Chunk-mode unique-build join: the dense LUT is built and
        dup/oob-validated ONCE per pinned build side (one device fetch),
        cached for the life of the chunked loop, and every subsequent
        probe chunk joins sync-free at probe capacity (no compaction).
        None = validation failed (caller takes the general fallbacks) or
        kernel limits don't apply."""
        if len(probe.columns) > 63 or len(build.columns) > 63:
            return None
        key = (id(node), domain)
        rec = self._chunk_lut_cache.get(key)
        if rec is None:
            from ..ops.join import dense_build_lut
            lut, dup, oob = dense_build_lut(build, node.right_keys,
                                            domain)
            dup, oob = (int(v) for v in np.asarray(jnp.stack(
                (dup.astype(jnp.int64), oob))))
            rec = lut if dup == 0 and oob == 0 else False
            self._chunk_lut_cache[key] = rec
            if rec is False:
                self.stats.join_domain_fallbacks += oob > 0
        if rec is False:
            return None
        from ..ops.join import dense_join_with_lut
        self.stats.chunk_lut_joins += 1
        return dense_join_with_lut(probe, build, rec, node.left_keys,
                                   node.right_keys, node.kind,
                                   self.gather_mode())

    # ------------------------------------------------------------------
    # fused multiway star join (MultiJoinNode)
    # ------------------------------------------------------------------

    def run_multijoin(self, node: "L.MultiJoinNode") -> Batch:
        """Lower a MultiJoinNode to the fused single-pass kernel
        (ops/pallas_hash.multiway_probe), degrading DIMENSION-BY-
        DIMENSION to the pairwise path whenever a dim's table overflows
        the VMEM budget, its build keys turn out duplicated, or its
        insert escaped — and wholesale to the reconstructed ladder when
        the kernel is off or fewer than two dims survive.  Every output
        is bit-exact vs `multijoin_to_ladder`'s pairwise ladder: fused
        dims ride the SAME payload-gather machinery the dense/hash
        joins use, and column order is restored to ladder order at the
        end.  The fact side is authoritative (never flipped to build).

        Chunk mode caches the validated dimension tables per node, so
        each streamed fact chunk probes sync-free like the pairwise
        dense-LUT path."""
        from ..ops import pallas_hash as ph
        mode = self.multiway_mode()
        if mode == "off":
            self._note_multijoin_degrade("kernel_off", len(node.dims))
            return self._run_multijoin_ladder(node)
        fact = self.run(node.fact)
        dims = [self.run(d) for d in node.dims]
        k = len(dims)
        ckey = (id(node), "multiway")
        rec = self._chunk_lut_cache.get(ckey) if self.chunk_mode \
            else None
        if rec is None:
            degraded: Dict[int, str] = {}
            sized = []
            for d in range(k):
                ok_dtype = True
                for side, keys in ((fact, node.fact_keys[d]),
                                   (dims[d], node.dim_keys[d])):
                    for ki in keys:
                        dt = side.columns[ki].data.dtype
                        if not (jnp.issubdtype(dt, jnp.integer) or
                                dt == jnp.bool_):
                            ok_dtype = False
                if not ok_dtype:
                    degraded[d] = "dtype"
                    continue
                slots, fits = ph.join_table_slots(dims[d].capacity)
                if self.hash_table_slots:
                    t = ph.MIN_TABLE_SLOTS
                    while t * 2 <= min(self.hash_table_slots,
                                       ph.MAX_TABLE_SLOTS):
                        t *= 2
                    slots = t
                    fits = t * ph.LOAD_NUM // ph.LOAD_DEN >= \
                        dims[d].capacity
                if not fits:
                    degraded[d] = "vmem"
                    continue
                sized.append((d, slots))
            # all resident tables share ONE slot count (rectangular
            # stack on the bucket_capacity-style power-of-two lattice);
            # shed the largest dims until the stack fits the budget
            budget = self.multiway_vmem_kb << 10
            while sized and ph.multiway_table_bytes(
                    len(sized), max(s for _, s in sized)) > budget:
                drop = max(sized, key=lambda x: x[1])
                sized.remove(drop)
                degraded[drop[0]] = "vmem"
            fused = []
            if len(sized) >= 2:
                table_slots = max(s for _, s in sized)
                builds, checks = [], []
                for d, _s in sized:
                    tkl, tkh, src, dup, esc = ph.build_join_table(
                        dims[d], node.dim_keys[d], table_slots, mode)
                    builds.append((d, tkl, tkh, src))
                    checks.extend((dup, esc))
                # ONE fused validation fetch for all k builds
                vals = self.fetch_ints(node, f"mjbuild{table_slots}",
                                       *checks)
                for i, b in enumerate(builds):
                    if vals[2 * i] > 0:
                        degraded[b[0]] = "dup"
                    elif vals[2 * i + 1] > 0:
                        degraded[b[0]] = "escape"
                    else:
                        fused.append(b)
            for _d, reason in sorted(degraded.items()):
                self._note_multijoin_degrade(reason)
            rec = (fused, sorted(degraded))
            if self.chunk_mode:
                self._chunk_lut_cache[ckey] = rec
        fused, degraded_dims = rec
        if len(fused) < 2:
            # nothing left worth a fused pass: run the whole ladder
            # over the already-materialized children
            return self._run_multijoin_ladder(node, fact, dims)
        from ..metrics import (JOIN_STRATEGY_DECISIONS,
                               MULTIJOIN_FUSED_PROBES)
        found, _miss = ph.multiway_probe(
            fact,
            jnp.stack([b[1] for b in fused]),
            jnp.stack([b[2] for b in fused]),
            jnp.stack([b[3] for b in fused]),
            tuple(node.fact_keys[b[0]] for b in fused), mode)
        self.stats.multijoin_fused_probes += 1
        MULTIJOIN_FUSED_PROBES.inc()
        self.strategy_decisions["MultiJoinNode"] = \
            f"multiway[k={len(fused)}]"
        JOIN_STRATEGY_DECISIONS.inc(strategy="multiway")
        # payload assembly: fused dims first (their found rows align to
        # fact rows), then each degraded dim through the pairwise path;
        # unique-build hops are commutative live-mask ANDs and dup
        # expansions keep their original relative order, so the row
        # sequence matches the ladder's
        from ..ops.join import _combined_key, _gather_build_payload
        gm = self.gather_mode()
        acc = fact
        acc_out = list(node.fact.output)
        col_ranges: Dict[int, tuple] = {}
        pos = len(fact.columns)
        for i, (d, _tl, _th, _sr) in enumerate(fused):
            matched = found[i] >= 0
            pk, _pk_valid = _combined_key(fact, node.fact_keys[d])
            src_c = jnp.clip(found[i], 0, dims[d].capacity - 1)
            acc = _gather_build_payload(acc, dims[d], src_c, matched,
                                        pk, node.dim_keys[d], "inner",
                                        gm)
            col_ranges[d] = (pos, len(dims[d].columns))
            acc_out.extend(node.dims[d].output)
            pos += len(dims[d].columns)
        for d in degraded_dims:
            # chunk mode: keep the synthesized hop alive across chunks
            # so its id stays stable — the pairwise LUT/hash caches key
            # on id(node), and a per-chunk temporary could both miss
            # every chunk AND alias a dead node's reused id
            jkey = (id(node), "mjpair", d)
            j = self._chunk_lut_cache.get(jkey) if self.chunk_mode \
                else None
            if j is None:
                j = L.JoinNode(
                    "inner", node.fact, node.dims[d],
                    node.fact_keys[d], node.dim_keys[d], None, True,
                    tuple(acc_out) + tuple(node.dims[d].output),
                    distribution=node.distribution,
                    build_key_domain=node.dim_domains[d])
                if self.chunk_mode:
                    self._chunk_lut_cache[jkey] = j
            # per-partition batches differ from what the structure key
            # describes (fused columns ride along): no cached decisions
            with self.no_decisions():
                acc = self._run_join_inner(j, acc, dims[d])
            col_ranges[d] = (pos, len(dims[d].columns))
            acc_out.extend(node.dims[d].output)
            pos += len(dims[d].columns)
        perm = list(range(len(fact.columns)))
        for d in range(k):
            start, ln = col_ranges[d]
            perm.extend(range(start, start + ln))
        if perm != list(range(len(acc.columns))):
            acc = Batch(tuple(acc.columns[i] for i in perm), acc.live)
        if not self.chunk_mode and not degraded_dims:
            acc = self.maybe_compact(acc, node=node)
        return acc

    def _run_multijoin_ladder(self, node: "L.MultiJoinNode",
                              fact: Optional[Batch] = None,
                              dims: Optional[list] = None) -> Batch:
        """Full degrade: execute the exact pairwise ladder the star
        fused.  Already-run children are substituted in so they are not
        recomputed; the ladder is cached per node in chunk mode so the
        pairwise LUT/hash caches stay keyed on stable node ids."""
        from ..metrics import JOIN_STRATEGY_DECISIONS
        self.strategy_decisions["MultiJoinNode"] = "ladder"
        JOIN_STRATEGY_DECISIONS.inc(strategy="ladder")
        lkey = (id(node), "mjladder")
        ladder = self._chunk_lut_cache.get(lkey) if self.chunk_mode \
            else None
        if ladder is None:
            ladder = L.multijoin_to_ladder(node)
            if self.chunk_mode:
                self._chunk_lut_cache[lkey] = ladder
        temp = []
        try:
            if fact is not None:
                for child, batch in zip((node.fact,) + node.dims,
                                        [fact] + list(dims)):
                    if id(child) not in self._subst:
                        self._subst[id(child)] = batch
                        temp.append(id(child))
            out = self.run(ladder)
        finally:
            for i in temp:
                self._subst.pop(i, None)
        # the outer run() re-reserves this result under the
        # MultiJoinNode's own id; drop the ladder-top ledger entry so
        # the bytes are not double-counted
        self.pool.free(self._node_bytes.pop(id(ladder), 0))
        return out

    def enter_chunk_mode(self) -> None:
        self.chunk_mode = True

    def exit_chunk_mode(self) -> None:
        self.chunk_mode = False
        self._chunk_lut_cache.clear()

    def apply_dynamic_filter(self, node: L.JoinNode, probe: Batch,
                             build: Batch) -> Batch:
        """Dynamic filtering (server/DynamicFilterService.java:103 +
        operator/DynamicFilterSourceOperator): the build side's key range
        prunes probe rows before the join. TPU adaptation: the filter is a
        live-mask AND (free), and when it kills most of the probe the
        batch is compacted to a smaller capacity so every downstream
        kernel (sort/join/agg) runs at the reduced size — the analog of
        Trino skipping probe splits entirely.

        Skipped for anti joins (they keep non-matching rows), left joins
        (outer rows survive), and mark joins (non-matching rows carry
        mark=false)."""
        if not self.enable_dynamic_filtering:
            return probe
        if node.kind in ("anti", "left", "mark") or node.null_aware:
            return probe
        for pk_i, bk_i in zip(node.left_keys, node.right_keys):
            bk = build.columns[bk_i]
            m = build.live & bk.valid
            info = jnp.iinfo(bk.data.dtype) if \
                jnp.issubdtype(bk.data.dtype, jnp.integer) else None
            if info is None:
                continue
            kmin = jnp.min(jnp.where(m, bk.data, info.max))
            kmax = jnp.max(jnp.where(m, bk.data, info.min))
            pk = probe.columns[pk_i]
            keep = pk.valid & (pk.data >= kmin) & (pk.data <= kmax)
            probe = probe.with_live(probe.live & keep)
        if probe.capacity >= (1 << 16) and not self.chunk_mode:
            # small probes skip the sync; so does the chunked loop (the
            # range mask above still applies — only compaction needs the
            # row-count round trip)
            live = self.fetch_ints(node, "dflive",
                                   jnp.sum(probe.live))[0]
            new_cap = bucket_capacity(live)
            if new_cap * 4 <= probe.capacity:
                self.stats.dynamic_filter_compactions += 1
                probe = compact_batch(probe, new_cap)
        return probe

    def run_mark_join(self, node: L.JoinNode, probe: Batch,
                      build: Batch) -> Batch:
        """EXISTS truth as an appended boolean column (JoinNode.Type.MARK
        in the reference): every probe row survives; the mark powers
        disjunctive EXISTS filters downstream. Build duplicates are
        irrelevant (membership semantics)."""
        domain = node.build_key_domain
        if node.residual is None:
            out = None
            if domain is not None:
                dout, _dup, oob = join_unique_build_dense(
                    probe, build, node.left_keys, node.right_keys,
                    "semi", domain, self.gather_mode())
                if self.fetch_ints(node, f"markoob:{domain}",
                                   oob)[0] == 0:
                    out = dout
                else:
                    self.stats.join_domain_fallbacks += 1
            if out is None:
                out, _dup = join_unique_build(
                    probe, build, node.left_keys, node.right_keys, "semi")
            mark = out.live          # live & matched
        else:
            residual = self.fold_scalars(node.residual)
            cap = probe.capacity
            while True:
                mark, total, oob = join_mark(
                    probe, build, node.left_keys, node.right_keys,
                    residual, cap, domain)
                total, oob = self.fetch_ints(
                    node, f"markexp{cap}:{domain}", total, oob)
                if oob > 0:
                    domain = None
                    self.stats.join_domain_fallbacks += 1
                    continue
                if total <= cap:
                    break
                cap = bucket_capacity(total)
                self.stats.join_expansion_retries += 1
            mark = probe.live & mark
        return Batch(probe.columns +
                     (Column(mark, jnp.ones_like(mark)),), probe.live)

    def run_membership_join(self, node: L.JoinNode, probe: Batch,
                            build: Batch) -> Batch:
        """semi/anti joins. Build duplicates are irrelevant (membership);
        residuals go through the mark-join expansion kernel."""
        if node.null_aware:
            # NOT IN: any NULL in the subquery output -> no row can pass
            bk = build.columns[node.right_keys[0]]
            if self.fetch_ints(node, "nullaware",
                               jnp.any(build.live & ~bk.valid))[0]:
                return probe.with_live(jnp.zeros_like(probe.live))
        domain = node.build_key_domain
        if node.residual is None:
            if domain is not None:
                out, _dup, oob = join_unique_build_dense(
                    probe, build, node.left_keys, node.right_keys,
                    node.kind, domain, self.gather_mode())
                if self.fetch_ints(node, f"memoob:{domain}",
                                   oob)[0] == 0:
                    self._note_strategy("JoinNode", "dense-lut", "join")
                    return out
                self.stats.join_domain_fallbacks += 1
            # membership joins tolerate duplicate build keys (the hash
            # table keeps one row per key, which IS the semantics)
            status, hout = self.try_hash_join(node, probe, build,
                                              allow_dup=True)
            if status == "ok":
                return hout
            out, _dup = join_unique_build(probe, build, node.left_keys,
                                          node.right_keys, node.kind)
            self._note_strategy("JoinNode", "sorted", "join")
            return out
        residual = self.fold_scalars(node.residual)
        cap = probe.capacity
        while True:
            mark, total, oob = join_mark(probe, build, node.left_keys,
                                         node.right_keys, residual, cap,
                                         domain)
            total, oob = self.fetch_ints(
                node, f"memexp{cap}:{domain}", total, oob)
            if oob > 0:
                domain = None
                self.stats.join_domain_fallbacks += 1
                continue
            if total <= cap:
                break
            cap = bucket_capacity(total)
            self.stats.join_expansion_retries += 1
        live = probe.live & (mark if node.kind == "semi" else ~mark)
        return probe.with_live(live)

    def result_to_host(self, root: L.OutputNode, batch: Batch):
        """Compact + return (names, columns, valids) on host. Selective
        results compact on device first so the host fetch moves live rows,
        not padded capacity (a 60M-capacity TopN result is 10 rows).
        Small batches skip the live-count probe: its device sync costs a
        tunnel round trip and the fetch moves little data anyway."""
        # mid-size results only probe when the decision cache can absorb
        # the sync on re-execution (deterministic subtree); one-shot
        # mutable-catalog queries keep the old 64K threshold — for them
        # the probe costs a round trip and the fetch moves little data
        probe_floor = (1 << 13) if self.decisions_cacheable(root) and \
            self.memo_structure_key(root) is not None else (1 << 16)
        if batch.columns and batch.capacity >= probe_floor:
            live = self.fetch_ints(root, "resultlive",
                                   jnp.sum(batch.live))[0]
            new_cap = bucket_capacity(live)
            if new_cap * 2 <= batch.capacity:
                batch = compact_batch(batch, new_cap)
        arrays, valids = batch_to_numpy(batch)
        # decisions taken during result materialization (resultlive)
        # happen after execute()'s save — persist them too
        self.save_decisions()
        return list(root.names), arrays, valids


import functools
import jax

from .profiler import recorded_jit


def explain_strategy_lines(root: L.PlanNode, executor) -> List[str]:
    """EXPLAIN's `agg strategy:` / `join strategy:` verdict lines: what
    the per-operator strategy gate will pick for this plan (pre-order,
    matching explain_text). After EXPLAIN ANALYZE the executor's
    recorded decision is appended when it differs from the prediction
    (e.g. a hash plan whose keys could not pack fell back to sort)."""
    lines: List[str] = []
    hash_on = executor.hash_mode() != "off"
    multiway_on = executor.multiway_mode() != "off"
    max_dims = int(getattr(executor, "multiway_max_dims", 5))
    ran = executor.strategy_decisions

    def verdict(predicted: str, op: str) -> str:
        actual = ran.get(op)
        if actual is not None and actual != predicted.split(" ")[0]:
            return f"{predicted} [ran: {actual}]"
        return predicted

    def walk(node: L.PlanNode, spine: bool = False) -> None:
        if isinstance(node, L.AggregateNode) and \
                node.strategy != "global":
            if node.strategy == "direct":
                g = 1
                for d in node.key_domains:
                    g *= d
                pred = f"direct ({g} groups)"
            elif node.strategy == "hash":
                pred = (f"hash (est {node.out_capacity} groups)"
                        if hash_on else
                        f"hash (est {node.out_capacity} groups; "
                        f"kernel off -> sort)")
            else:
                pred = f"sort (est {node.out_capacity} groups)"
            lines.append("agg strategy: "
                         + verdict(pred, "AggregateNode"))
        elif isinstance(node, L.JoinNode):
            # star-detector verdict at the TOP of each probe spine: why
            # a ladder that stayed pairwise would (not) fuse — printed
            # either way, so declined stars are as visible as fused ones
            if not spine:
                sv = L.star_verdict(node, max_dims)
                if sv is not None:
                    lines.append("multiway star: " + sv)
            if node.build_key_domain is not None and node.build_unique:
                pred = f"dense-lut (domain {node.build_key_domain})"
            elif not node.build_unique:
                pred = "expand"
            elif hash_on:
                pred = "hybrid-hash"
            else:
                pred = "sort-merge"
            lines.append("join strategy: " + verdict(pred, "JoinNode"))
            # mesh placement verdict (parallel/dist_executor.py gate):
            # the planner's stats choice, overridden by what the mesh
            # executor actually ran (a partitioned ask can degrade to
            # broadcast on shape/skew grounds)
            dist = getattr(node, "distribution", "auto")
            lines.append("join distribution: "
                         + verdict(dist, "JoinDistribution"))
        elif isinstance(node, L.MultiJoinNode):
            kk = len(node.dims)
            pred = f"multiway[k={kk}]" if multiway_on else \
                f"multiway[k={kk}] (kernel off -> ladder)"
            lines.append("join strategy: "
                         + verdict(pred, "MultiJoinNode"))
            lines.append("join distribution: "
                         + verdict(node.distribution,
                                   "JoinDistribution"))
        if isinstance(node, L.JoinNode):
            walk(node.left, spine=True)
            walk(node.right)
        elif isinstance(node, L.FilterNode):
            walk(node.child, spine=spine)
        else:
            for c in L.children(node):
                walk(c)

    walk(root)
    return lines


@recorded_jit(static_argnums=(1, 2))
def filter_project_fused(batch: Batch, exprs, predicate) -> Batch:
    """Project-then-filter in one jit (Filter over Project)."""
    projected = project(batch, exprs)
    return apply_filter(projected, predicate)


def remap_codes(batch: Batch, remaps) -> Batch:
    """Translate dictionary codes through per-column LUTs (merged set-op
    pools). One device gather per remapped column."""
    if all(r is None for r in remaps):
        return batch
    cols = []
    for col, rm in zip(batch.columns, remaps):
        if rm is None:
            cols.append(col)
        else:
            lut = jnp.asarray(np.asarray(rm, dtype=np.int32))
            cols.append(Column(jnp.take(lut, col.data, axis=0), col.valid))
    return Batch(tuple(cols), batch.live)


# XLA TPU compile cost for lax.sort blows up in BOTH dimensions
# (measured v5e): rows x operands — 60M x 4 operands = 119s, 60M x 12 =
# 385s — and operand count alone: a 1.57M x 22-operand sort ran past 8
# MINUTES while a 22-argument non-sort kernel compiled in 1.4s. So big
# sorts must stay under an operand-element budget AND a hard operand
# cap; above either, sort the minimum (keys + index) and move payload
# columns with gathers (~1.6s per 60M column at runtime, compile in
# seconds).
SORT_COMPILE_BUDGET = 1 << 26
MAX_SORT_OPERANDS = 12
# rows below which a multi-operand sort still compiles in seconds;
# above it every sort should be (packed key, index) or argsort+gather
SORT_SMALL_ROWS = 1 << 19


def compact_batch(batch: Batch, new_capacity: int) -> Batch:
    """Move live rows (in order) into a smaller-capacity batch.
    Small shapes: ONE multi-operand stable sort by deadness + free
    slicing (the fastest primitive on TPU is the sort network,
    SURVEY.md §7 hard part 1). Large shapes: 2-operand argsort of
    deadness + per-column gathers, trading gather runtime for a compile
    that finishes (SORT_COMPILE_BUDGET).
    Caller guarantees new_capacity >= live count."""
    n_operands = 2 + 2 * len(batch.columns)
    if batch.capacity <= SORT_SMALL_ROWS and \
            n_operands <= MAX_SORT_OPERANDS:
        return _compact_sort(batch, new_capacity)
    return _compact_gather(batch, new_capacity)


@recorded_jit(static_argnums=(2, 3))
def _append_packed_key(batch: Batch, kmins, keys: tuple,
                       bits: tuple) -> Batch:
    """Append one int64 column packing the key columns by shared range
    compression (see pack_join_keys); valid = AND of the key validities,
    so NULL keys keep their never-match semantics."""
    packed = jnp.zeros(batch.capacity, dtype=jnp.int64)
    valid = jnp.ones(batch.capacity, dtype=jnp.bool_)
    for j, (ki, b) in enumerate(zip(keys, bits)):
        col = batch.columns[ki]
        norm = col.data.astype(jnp.int64) - kmins[j] + 1
        packed = (packed << b) | jnp.where(col.valid, norm, 0)
        valid = valid & col.valid
    return Batch(batch.columns + (Column(packed, valid),), batch.live)


def _strip_packed_columns(out: Batch, node: L.JoinNode, n_probe: int,
                          n_build: int) -> Batch:
    """Remove the appended key columns so the output matches
    node.output."""
    cols = list(out.columns)
    if node.kind in ("inner", "left"):
        # layout: probe cols + packed_p + build cols + packed_b
        del cols[n_probe + 1 + n_build]
        del cols[n_probe]
    elif node.kind == "mark":
        # probe cols + packed_p + mark
        del cols[n_probe]
    else:                               # semi/anti: probe cols + packed
        del cols[n_probe]
    return Batch(tuple(cols), out.live)


@recorded_jit(static_argnums=(1,))
def _compact_sort(batch: Batch, new_capacity: int) -> Batch:
    operands = [(~batch.live).astype(jnp.int8)]
    for c in batch.columns:
        operands.append(c.data)
        operands.append(c.valid)
    operands.append(batch.live)
    out = jax.lax.sort(tuple(operands), num_keys=1, is_stable=True)
    cols = []
    for i in range(len(batch.columns)):
        cols.append(Column(out[1 + 2 * i][:new_capacity],
                           out[2 + 2 * i][:new_capacity]))
    return Batch(tuple(cols), out[-1][:new_capacity])


@recorded_jit(static_argnums=(1,))
def _compact_gather(batch: Batch, new_capacity: int) -> Batch:
    idx = jnp.argsort(~batch.live, stable=True)[:new_capacity]
    cols = tuple(Column(jnp.take(c.data, idx, axis=0),
                        jnp.take(c.valid, idx, axis=0))
                 for c in batch.columns)
    return Batch(cols, jnp.take(batch.live, idx, axis=0))


@recorded_jit()
def concat_batches(a: Batch, b: Batch) -> Batch:
    """UNION ALL: columnwise concatenation on device (UnionNode lowering —
    Trino's union is a pass-through exchange, ours is one concat per
    column; capacity is the sum so no rows can drop)."""
    cols = tuple(
        Column(jnp.concatenate([ca.data, cb.data]),
               jnp.concatenate([ca.valid, cb.valid]))
        for ca, cb in zip(a.columns, b.columns))
    return Batch(cols, jnp.concatenate([a.live, b.live]))
