"""Device-resident fact-column cache with range-compressed dtypes.

Reference role: Trino's memory-pinned page cache / the Hive split cache
keep hot table pages in RAM near the workers; the columnar formats
(ORC/Parquet) store integers bit-packed so the hot set fits. On TPU the
scarce tier is HBM and the host link is the bottleneck (measured here:
~30 MB/s random, ~60 MB/s compressible through the tunnel — even a real
PCIe v5e host link is dwarfed by 800 GB/s HBM), so the same two ideas
move on-device: keep the fact table's scanned columns resident in HBM,
and store them in the NARROWEST integer dtype their value range allows
(connector stats or a one-time host min/max pass), widening to the
engine's int64 lanes chunk-by-chunk inside the jitted pipeline.

A 600M-row TPC-H SF100 lineitem q5 projection drops from 19.2 GB
(int64) to 7.8 GB (int32 keys/prices, int8 discount) — it fits a single
v5e chip's HBM, so steady-state queries never touch the host link at
all; the chunked driver (exec/chunked.py) then slices chunks directly
from the resident arrays.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np


class NarrowColumn:
    """One device-resident column: narrow-dtype data + optional validity."""

    __slots__ = ("data", "valid", "wide_dtype")

    def __init__(self, data, valid, wide_dtype):
        self.data = data          # jax.Array, narrowest safe dtype
        self.valid = valid        # jax.Array bool or None (all valid)
        self.wide_dtype = wide_dtype  # dtype the engine's lanes expect

    @property
    def nbytes(self) -> int:
        n = self.data.size * self.data.dtype.itemsize
        if self.valid is not None:
            n += self.valid.size
        return n


_INT_STEPS = (np.int8, np.int16, np.int32, np.int64)

_tunnel_warmed = False


def warm_transfer_path() -> None:
    """One small INCOMPRESSIBLE transfer before the first bulk ingest.

    Measured on the tunneled TPU rig: the first sizeable host->device
    transfer of a process crawls at ~25 MB/s while every later one runs
    at ~1.3 GB/s — a transport slow-start. A 4 MB random warmup (~0.25 s)
    opens the fast path, turning a 7.8 GB fact ingest from ~270-435 s
    into ~6 s. No-op on non-tunneled backends (costs one cheap copy)."""
    global _tunnel_warmed
    if _tunnel_warmed:
        return
    _tunnel_warmed = True
    try:
        import jax
        x = np.random.default_rng(0).integers(
            0, 1 << 30, size=1_000_000, dtype=np.int32)
        jax.block_until_ready(jax.device_put(x))
    except Exception:     # noqa: BLE001 — warmup must never break a query
        pass


def _narrow_dtype(arr: np.ndarray, valid: Optional[np.ndarray]):
    """Smallest signed integer dtype holding the column's valid values."""
    if not np.issubdtype(arr.dtype, np.integer):
        return arr.dtype                       # floats/bools ship as-is
    if valid is not None:
        vals = arr[valid]
        if len(vals) == 0:
            return np.int8
        lo, hi = int(vals.min()), int(vals.max())
    elif len(arr) == 0:
        return np.int8
    else:
        lo, hi = int(arr.min()), int(arr.max())
    for dt in _INT_STEPS:
        info = np.iinfo(dt)
        if info.min <= lo and hi <= info.max:
            return dt
    return np.int64


class FactTableCache:
    """LRU of device-resident narrowed fact tables, capped by HBM bytes.

    Keys are (catalog, schema, table, column_indices, table_version) so a
    mutated memory-connector table never aliases a stale resident copy.
    """

    def __init__(self, max_bytes: int = 9 << 30):
        self.max_bytes = max_bytes
        self._entries: "OrderedDict[tuple, Tuple[List[NarrowColumn], int]]" \
            = OrderedDict()
        self._bytes: Dict[tuple, int] = {}

    def total_bytes(self) -> int:
        return sum(self._bytes.values())

    def get(self, key) -> Optional[List[NarrowColumn]]:
        hit = self._entries.get(key)
        if hit is None:
            return None
        self._entries.move_to_end(key)
        return hit[0]

    def invalidate(self) -> int:
        """Drop everything (DML invalidation); returns bytes released."""
        freed = self.total_bytes()
        self._entries.clear()
        self._bytes.clear()
        return freed

    def estimate_bytes(self, data, column_indices) -> int:
        """Cheap upper estimate WITHOUT the min/max pass: assumes int32
        narrowing for int64 (the common case for keys/prices) and adds
        validity bytes. Used to early-reject tables that cannot fit."""
        n = data.num_rows
        total = 0
        for i in column_indices:
            arr = np.asarray(data.columns[i])
            itemsize = min(arr.dtype.itemsize, 4) \
                if np.issubdtype(arr.dtype, np.integer) else \
                arr.dtype.itemsize
            total += n * itemsize
            if data.valids is not None and data.valids[i] is not None:
                total += n
        return total

    def load(self, key, data, column_indices) -> \
            Optional[List[NarrowColumn]]:
        """Narrow + ship `column_indices` of `data` to device, evicting
        LRU entries to fit. None if the table can't fit the budget."""
        import jax

        hit = self.get(key)
        if hit is not None:
            return hit
        warm_transfer_path()
        cols: List[NarrowColumn] = []
        total = 0
        for i in column_indices:
            arr = np.asarray(data.columns[i])
            valid_np = None
            if data.valids is not None and data.valids[i] is not None:
                valid_np = np.asarray(data.valids[i])
            dt = _narrow_dtype(arr, valid_np)
            total += arr.shape[0] * np.dtype(dt).itemsize + \
                (arr.shape[0] if valid_np is not None else 0)
            if total > self.max_bytes:
                return None
            narrow = arr if arr.dtype == dt else arr.astype(dt)
            if valid_np is not None and narrow is not arr:
                # invalid slots may hold out-of-range garbage: zero them
                # so the narrowed cast is well-defined
                narrow = np.where(valid_np, narrow, np.zeros((), dt))
            cols.append(NarrowColumn(
                jax.device_put(narrow),
                None if valid_np is None else jax.device_put(valid_np),
                arr.dtype))
        while self._entries and self.total_bytes() + total > self.max_bytes:
            old, _ = self._entries.popitem(last=False)
            self._bytes.pop(old, None)
        self._entries[key] = (cols, data.num_rows)
        self._bytes[key] = total
        return cols
