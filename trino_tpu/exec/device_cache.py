"""Device-resident fact-column cache with range-compressed dtypes.

Reference role: Trino's memory-pinned page cache / the Hive split cache
keep hot table pages in RAM near the workers; the columnar formats
(ORC/Parquet) store integers bit-packed so the hot set fits. On TPU the
scarce tier is HBM and the host link is the bottleneck (measured here:
~30 MB/s random, ~60 MB/s compressible through the tunnel — even a real
PCIe v5e host link is dwarfed by 800 GB/s HBM), so the same two ideas
move on-device: keep the fact table's scanned columns resident in HBM,
and store them in the NARROWEST integer dtype their value range allows
(connector stats or a one-time host min/max pass), widening to the
engine's int64 lanes chunk-by-chunk inside the jitted pipeline.

A 600M-row TPC-H SF100 lineitem q5 projection drops from 19.2 GB
(int64) to 7.8 GB (int32 keys/prices, int8 discount) — it fits a single
v5e chip's HBM, so steady-state queries never touch the host link at
all; the chunked driver (exec/chunked.py) then slices chunks directly
from the resident arrays.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np


class NarrowColumn:
    """One device-resident column: narrow-dtype data + optional validity."""

    __slots__ = ("data", "valid", "wide_dtype")

    def __init__(self, data, valid, wide_dtype):
        self.data = data          # jax.Array, narrowest safe dtype
        self.valid = valid        # jax.Array bool or None (all valid)
        self.wide_dtype = wide_dtype  # dtype the engine's lanes expect

    @property
    def nbytes(self) -> int:
        n = self.data.size * self.data.dtype.itemsize
        if self.valid is not None:
            n += self.valid.size
        return n


_INT_STEPS = (np.int8, np.int16, np.int32, np.int64)

# ---------------------------------------------------------------------------
# transfer encodings: the tunnel transparently compresses, and its raw
# bandwidth fluctuates ~20x (measured 20 MB/s .. 1.3 GB/s), so shipping
# LOW-ENTROPY byte streams is the one lever the engine controls. Sorted
# key columns delta-encode (mostly tiny repeated values -> compresses to
# ~nothing); other multi-byte integers split into byte PLANES so the
# near-constant high bytes compress away. Decode happens ON DEVICE right
# after the put; steady state sees ordinary narrow columns.
# ---------------------------------------------------------------------------

def encode_transfer(narrow: np.ndarray):
    """-> (enc, payload ndarray, meta dict). enc: raw | delta8 | planes."""
    if narrow.dtype.itemsize == 1 or \
            not np.issubdtype(narrow.dtype, np.integer) or \
            narrow.size < 2:
        return "raw", narrow, {}
    d = np.diff(narrow)
    if d.size and int(d.min()) >= -128 and int(d.max()) <= 127:
        return "delta8", d.astype(np.int8), {
            "base": int(narrow[0]), "dtype": str(narrow.dtype)}
    k = narrow.dtype.itemsize
    planes = np.ascontiguousarray(
        narrow.view(np.uint8).reshape(-1, k).T)
    return "planes", planes, {"dtype": str(narrow.dtype)}


def decode_transfer(enc: str, payload, meta: dict):
    """Device-side decode (payload already device-resident)."""
    import jax
    import jax.numpy as jnp
    if enc == "raw":
        return payload
    dt = jnp.dtype(meta["dtype"])
    if enc == "delta8":
        base = meta["base"]
        acc = jnp.int64 if dt.itemsize > 4 else jnp.int32

        @jax.jit
        def _dec(d):
            cs = jnp.cumsum(d.astype(acc))
            full = jnp.concatenate(
                [jnp.zeros(1, acc), cs]) + jnp.asarray(base, acc)
            return full.astype(dt)
        return _dec(payload)

    @jax.jit
    def _dec_planes(p):
        u = jnp.uint64 if dt.itemsize > 4 else jnp.uint32
        word = p[0].astype(u)
        for j in range(1, p.shape[0]):
            word = word | (p[j].astype(u) << (8 * j))
        return jax.lax.bitcast_convert_type(
            word.astype(jnp.dtype(f"uint{dt.itemsize * 8}")), dt)
    return _dec_planes(payload)


# TRINO_TPU_CHUNK_PROFILE=1: per-phase walls to stderr (read at call
# time so the toggle works however late it is set); shared by the
# chunked driver and the ingest path
def profile_enabled() -> bool:
    import os
    return bool(os.environ.get("TRINO_TPU_CHUNK_PROFILE"))


def prof(msg: str) -> None:
    if profile_enabled():
        import sys
        import time
        print(f"[chunk {time.monotonic():.3f}] {msg}", file=sys.stderr,
              flush=True)


_tunnel_warmed = False


def warm_transfer_path() -> None:
    """One small INCOMPRESSIBLE transfer before the first bulk ingest.

    Measured on the tunneled TPU rig: the first sizeable host->device
    transfer of a process crawls at ~25 MB/s while every later one runs
    at ~1.3 GB/s — a transport slow-start. A 4 MB random warmup (~0.25 s)
    opens the fast path, turning a 7.8 GB fact ingest from ~270-435 s
    into ~6 s. No-op on non-tunneled backends (costs one cheap copy)."""
    global _tunnel_warmed
    if _tunnel_warmed:
        return
    _tunnel_warmed = True
    try:
        import jax
        x = np.random.default_rng(0).integers(
            0, 1 << 30, size=1_000_000, dtype=np.int32)
        jax.block_until_ready(jax.device_put(x))
    except Exception:     # noqa: BLE001 — warmup must never break a query
        pass


def _narrow_dtype(arr: np.ndarray, valid: Optional[np.ndarray]):
    """Smallest signed integer dtype holding the column's valid values."""
    if not np.issubdtype(arr.dtype, np.integer):
        return arr.dtype                       # floats/bools ship as-is
    if valid is not None:
        vals = arr[valid]
        if len(vals) == 0:
            return np.int8
        lo, hi = int(vals.min()), int(vals.max())
    elif len(arr) == 0:
        return np.int8
    else:
        lo, hi = int(arr.min()), int(arr.max())
    for dt in _INT_STEPS:
        info = np.iinfo(dt)
        if info.min <= lo and hi <= info.max:
            return dt
    return np.int64


class FactTableCache:
    """LRU of device-resident narrowed fact tables, capped by HBM bytes.

    Keys are (catalog, schema, table, column_indices, table_version) so a
    mutated memory-connector table never aliases a stale resident copy.
    """

    def __init__(self, max_bytes: int = 9 << 30):
        self.max_bytes = max_bytes
        self._entries: "OrderedDict[tuple, Tuple[List[NarrowColumn], int]]" \
            = OrderedDict()
        self._bytes: Dict[tuple, int] = {}

    def total_bytes(self) -> int:
        return sum(self._bytes.values())

    def get(self, key) -> Optional[List[NarrowColumn]]:
        hit = self._entries.get(key)
        if hit is None:
            return None
        self._entries.move_to_end(key)
        return hit[0]

    def invalidate(self) -> int:
        """Drop everything (DML invalidation); returns bytes released."""
        freed = self.total_bytes()
        self._entries.clear()
        self._bytes.clear()
        return freed

    def estimate_bytes(self, data, column_indices) -> int:
        """Cheap upper estimate WITHOUT the min/max pass: assumes int32
        narrowing for int64 (the common case for keys/prices) and adds
        validity bytes. Used to early-reject tables that cannot fit."""
        n = data.num_rows
        total = 0
        for i in column_indices:
            arr = np.asarray(data.columns[i])
            itemsize = min(arr.dtype.itemsize, 4) \
                if np.issubdtype(arr.dtype, np.integer) else \
                arr.dtype.itemsize
            total += n * itemsize
            if data.valids is not None and data.valids[i] is not None:
                total += n
        return total

    def _narrow_disk_dir(self, key) -> str:
        import hashlib
        import os as _os
        from ..connectors.diskcache import cache_root
        h = hashlib.sha256(repr(key).encode()).hexdigest()[:24]
        return _os.path.join(cache_root(), f"narrow_{h}")

    @staticmethod
    def _source_fingerprint(data, column_indices) -> str:
        """Cheap content fingerprint of the SOURCE columns: row count +
        per-column dtype + head/tail samples. Catches regenerated tables
        (same name, new data) without reading the full source."""
        import hashlib
        h = hashlib.sha256(str(data.num_rows).encode())
        for i in column_indices:
            arr = np.asarray(data.columns[i])
            h.update(str(arr.dtype).encode())
            h.update(np.ascontiguousarray(arr[:1024]).tobytes())
            h.update(np.ascontiguousarray(arr[-1024:]).tobytes())
        return h.hexdigest()

    def _load_narrow_disk(self, key, data, column_indices):
        """mmap previously-narrowed columns in their TRANSFER ENCODING
        (the astype + min/max + encode host passes over the full-width
        source cost ~45 s at SF100; the encoded form ships straight from
        the mmap)."""
        import json as _json
        import os as _os
        d = self._narrow_disk_dir(key)
        meta_p = _os.path.join(d, "meta.json")
        if not _os.path.isfile(meta_p):
            return None
        try:
            with open(meta_p) as f:
                meta = _json.load(f)
            if meta.get("v") != 2 or meta.get("fingerprint") != \
                    self._source_fingerprint(data, column_indices):
                return None           # format or table changed
            out = []
            for j, cm in enumerate(meta["cols"]):
                payload = np.load(_os.path.join(d, f"c{j}.npy"),
                                  mmap_mode="r")
                valid = None
                vp = _os.path.join(d, f"v{j}.npy")
                if _os.path.isfile(vp):
                    valid = np.load(vp, mmap_mode="r")
                out.append((cm, payload, valid))
            return out
        except Exception:     # noqa: BLE001 — corrupt cache = cold start
            return None

    def _save_narrow_disk(self, key, encoded, fingerprint) -> None:
        import json as _json
        import os as _os
        d = self._narrow_disk_dir(key)
        tmp = d + f".tmp{_os.getpid()}"
        try:
            _os.makedirs(tmp, exist_ok=True)
            cols = []
            for j, (cm, payload, valid) in enumerate(encoded):
                np.save(_os.path.join(tmp, f"c{j}.npy"), payload)
                if valid is not None:
                    np.save(_os.path.join(tmp, f"v{j}.npy"), valid)
                cols.append(cm)
            with open(_os.path.join(tmp, "meta.json"), "w") as f:
                _json.dump({"v": 2, "cols": cols,
                            "fingerprint": fingerprint}, f)
            if _os.path.isdir(d):     # os.replace cannot overwrite a
                import shutil          # non-empty directory
                shutil.rmtree(d, ignore_errors=True)
            _os.replace(tmp, d)
        except Exception:     # noqa: BLE001 — cache write is best-effort
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)

    def load(self, key, data, column_indices, persist_ok=False) -> \
            Optional[List[NarrowColumn]]:
        """Narrow + ship `column_indices` of `data` to device, evicting
        LRU entries to fit. None if the table can't fit the budget.
        With persist_ok (deterministic catalogs only) the narrowed host
        arrays also cache on disk, so later processes mmap them straight
        to the device with no host passes."""
        import jax

        import os as _os
        import sys as _sys
        import time as _time
        from ..metrics import DEVICE_CACHE_HITS, DEVICE_CACHE_MISSES
        prof_on = profile_enabled()
        hit = self.get(key)
        if hit is not None:
            DEVICE_CACHE_HITS.inc()
            return hit
        DEVICE_CACHE_MISSES.inc()
        t0 = _time.monotonic()
        warm_transfer_path()
        if prof_on:
            print(f"[ingest] warmup {_time.monotonic()-t0:.1f}s",
                  file=_sys.stderr, flush=True)
        disk = self._load_narrow_disk(key, data, column_indices) \
            if persist_ok else None
        cols: List[NarrowColumn] = []
        total = 0
        to_persist = []
        for j, i in enumerate(column_indices):
            t0 = _time.monotonic()
            if disk is not None:
                cm, payload, valid_np = disk[j]
                enc, wide_dt = cm["enc"], np.dtype(cm["wide"])
                narrow_nbytes = data.num_rows * \
                    np.dtype(cm.get("dtype", "int8")).itemsize \
                    if enc != "raw" else payload.nbytes
            else:
                arr = np.asarray(data.columns[i])
                wide_dt = arr.dtype
                valid_np = None
                if data.valids is not None and data.valids[i] is not None:
                    valid_np = np.asarray(data.valids[i])
                dt = _narrow_dtype(arr, valid_np)
                narrow = arr if arr.dtype == dt else arr.astype(dt)
                if valid_np is not None and narrow is not arr:
                    # invalid slots may hold out-of-range garbage: zero
                    # them so the narrowed cast is well-defined
                    narrow = np.where(valid_np, narrow, np.zeros((), dt))
                enc, payload, em = encode_transfer(narrow)
                cm = dict(em, enc=enc, wide=str(wide_dt),
                          dtype=str(narrow.dtype))
                narrow_nbytes = narrow.nbytes
            total += narrow_nbytes + \
                (data.num_rows if valid_np is not None else 0)
            if total > self.max_bytes:
                return None
            t1 = _time.monotonic()
            dev_payload = jax.device_put(np.ascontiguousarray(payload))
            d = decode_transfer(enc, dev_payload, cm)
            dv = None if valid_np is None else \
                jax.device_put(np.ascontiguousarray(valid_np))
            if prof_on:
                jax.block_until_ready(d)
                print(f"[ingest] col {i}: {payload.nbytes/1e6:.0f}MB "
                      f"enc={enc} host {t1-t0:.1f}s put+decode "
                      f"{_time.monotonic()-t1:.1f}s "
                      f"disk={disk is not None}",
                      file=_sys.stderr, flush=True)
            cols.append(NarrowColumn(d, dv, wide_dt))
            if persist_ok and disk is None:
                to_persist.append((cm, payload, valid_np))
        if to_persist:
            self._save_narrow_disk(key, to_persist,
                                   self._source_fingerprint(
                                       data, column_indices))
        while self._entries and self.total_bytes() + total > self.max_bytes:
            old, _ = self._entries.popitem(last=False)
            self._bytes.pop(old, None)
        self._entries[key] = (cols, data.num_rows)
        self._bytes[key] = total
        return cols
