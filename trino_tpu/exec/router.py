"""Cost-based CPU/TPU co-routing + the host numpy execution path.

Reference: "Revisiting Co-Processing for Hash Joins on the Coupled
CPU-GPU Architecture" (PAPERS.md) — route small operators to the host
and reserve the accelerator for work that amortizes its dispatch cost.
The bench makes the local case concrete: q6 SF1 is bounded by a single
tunnel RTT (~10 ms of device compute behind 100-260 ms of round trips),
so a concurrent mix of point queries would serialize on the device
dispatch lock and starve scan-heavy work.

Two pieces:

- ``decide_route``: given a pruned logical plan, pick 'host' or
  'device'. Forced by the ``routing_mode`` session property; in 'auto'
  mode the per-fingerprint history baseline (server/history.py) wins
  when present (a statement that finishes in a few ms belongs on the
  host regardless of what the estimator thinks), otherwise the
  planner's scan-row estimates against ``router_host_max_rows``.

- ``run_host``: a numpy interpreter for the host-eligible plan subset
  (Scan/Filter/Project/global-Aggregate/Sort/Limit/Values over the
  scalar expression IR). It never touches jax, the device, or the
  shared Executor — host-routed queries run WITHOUT the coordinator's
  exec lock, which is what lets hundreds of point queries proceed while
  a scan-heavy plan owns the device. Semantics mirror ops/project.py's
  eval_expr row for row; the subtle shared helpers (decimal rescale /
  compare, avg finalizer) are literally the same functions called with
  ``xp=np``.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from .. import ir
from ..planner import logical as L
from ..types import TypeKind


class HostUnsupported(Exception):
    """Plan (or expression) outside the host interpreter's subset — the
    router falls back to the device path, never fails the query."""


@dataclass(frozen=True)
class RouteDecision:
    target: str          # 'host' | 'device'
    reason: str
    est_rows: float = 0.0


_HOST_AGGS = ("sum", "count", "count_star", "min", "max")

# expression kinds the numpy evaluator implements; anything else makes
# the plan device-only (ScalarSubqueryRef/InSubqueryRef need the
# executor's subquery folding, general ScalarFunc/ExtractField the jax
# kernels). The two-limb decimal-sum scalars are whitelisted: wide
# decimal SUM plans route through them and they are two int ops each.
_HOST_EXPRS = (ir.ColumnRef, ir.Literal, ir.Arith, ir.Negate, ir.Compare,
               ir.Logical, ir.Not, ir.IsNull, ir.InList, ir.Between,
               ir.Case, ir.Cast, ir.DictPredicate, ir.DictValueMap,
               ir.DerivedDict, ir.DecimalAvg, ir.ArrayConst)

_HOST_SCALAR_FUNCS = ("$limb_hi", "$limb_lo", "$limb_combine")


def _subtree_nodes(node: L.PlanNode):
    yield node
    for c in L.children(node):
        yield from _subtree_nodes(c)


def _node_exprs(node: L.PlanNode):
    if isinstance(node, L.FilterNode):
        return (node.predicate,)
    if isinstance(node, L.ProjectNode):
        return node.exprs
    return ()


def _expr_supported(expr: ir.Expr) -> Optional[str]:
    for n in ir.walk(expr):
        if isinstance(n, ir.ScalarFunc):
            if n.name not in _HOST_SCALAR_FUNCS:
                return f"scalar function {n.name}"
        elif not isinstance(n, _HOST_EXPRS):
            return f"expression {type(n).__name__}"
    return None


def host_supported(root: L.PlanNode) -> Optional[str]:
    """None when the host interpreter can run this plan, else the first
    reason it cannot (surfaced in EXPLAIN's routing annotation)."""
    for node in _subtree_nodes(root):
        if isinstance(node, (L.OutputNode, L.LimitNode, L.ScanNode,
                             L.ValuesNode)):
            pass
        elif isinstance(node, L.SortNode):
            pass
        elif isinstance(node, (L.FilterNode, L.ProjectNode)):
            for e in _node_exprs(node):
                why = _expr_supported(e)
                if why is not None:
                    return why
        elif isinstance(node, L.AggregateNode):
            if node.group_keys or node.strategy != "global":
                return "grouped aggregation"
            for a in node.aggs:
                if a.distinct:
                    return "distinct aggregate"
                if a.func not in _HOST_AGGS:
                    return f"aggregate {a.func}"
                if a.arg is not None and not isinstance(a.arg,
                                                        ir.ColumnRef):
                    return "computed aggregate argument"
        else:
            return f"operator {type(node).__name__}"
    return None


def plan_scan_rows(planner, root: L.PlanNode) -> float:
    """Total estimated rows read by the plan's scans — the router's cost
    proxy (dispatch cost amortizes over rows touched, not rows
    returned)."""
    total = 0.0
    for n in _subtree_nodes(root):
        if isinstance(n, L.ScanNode):
            try:
                total += planner.estimate_rows(n)
            except Exception:       # noqa: BLE001 — stats are best-effort
                total += 1e6
        elif isinstance(n, L.ValuesNode):
            total += float(n.num_rows)
    return total


class TenantFairShare:
    """Per-tenant device-contention tracker for the router.

    The device tier serializes behind the coordinator's exec lock, so
    "contended" means: some OTHER tenant's query currently holds (or
    waits for) the device. Under contention a tenant's host-eligible
    queries overflow to the host tier instead of queueing behind a
    neighbor's scan — the co-processing split from "Revisiting
    Co-Processing for Hash Joins on the Coupled CPU-GPU Architecture":
    keep the accelerator for the work that amortizes it, and keep small
    tenants' latency off the contention path entirely. A tenant is
    never overflowed by ITS OWN in-flight device work (its queries
    serializing behind each other is its own fair queue)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight: dict = {}

    def device_begin(self, tenant: str) -> None:
        with self._lock:
            self._inflight[tenant] = self._inflight.get(tenant, 0) + 1

    def device_end(self, tenant: str) -> None:
        with self._lock:
            n = self._inflight.get(tenant, 0) - 1
            if n <= 0:
                self._inflight.pop(tenant, None)
            else:
                self._inflight[tenant] = n

    def contended_by_others(self, tenant: str) -> bool:
        with self._lock:
            return any(n > 0 for t, n in self._inflight.items()
                       if t != tenant)

    def inflight(self) -> dict:
        with self._lock:
            return dict(self._inflight)


def decide_route(planner, root: L.PlanNode, properties,
                 history=None, fingerprint: Optional[str] = None,
                 tenant: Optional[str] = None,
                 fair_share: Optional[TenantFairShare] = None,
                 prewarm=None) -> RouteDecision:
    """Pick the execution target for a pruned local plan."""
    mode = str(properties.get("routing_mode", "auto")).lower()
    unsupported = host_supported(root)
    if mode == "device":
        return RouteDecision("device", "forced by routing_mode")
    if mode == "host":
        if unsupported is not None:
            return RouteDecision(
                "device", f"routing_mode=host but {unsupported}")
        return RouteDecision("host", "forced by routing_mode")
    if unsupported is not None:
        return RouteDecision("device", unsupported)
    # compile-aware routing (exec/prewarm.py): while this fingerprint's
    # device program is cold — a prewarm is still compiling it, or no
    # device run has compiled it yet — a host-eligible query runs on
    # the bit-exact numpy interpreter instead of blocking on a
    # multi-second XLA compile; the serving layer kicks a background
    # warm and the fingerprint swaps to device once it lands. A None /
    # disabled engine never reaches here, so prewarm-off behavior is
    # byte-identical to the pre-prewarm router.
    if prewarm is not None and fingerprint and \
            prewarm.device_cold(fingerprint):
        return RouteDecision(
            "host", "device program cold (prewarm in flight)"
            if prewarm.is_inflight(fingerprint)
            else "device program cold")
    # per-tenant fair share: under device contention from OTHER tenants,
    # a host-eligible plan overflows to the host tier even when history
    # would have preferred the device — bounded at 4x the host row gate
    # so a genuinely scan-heavy plan still waits for the device rather
    # than grinding the host interpreter
    if fair_share is not None and tenant is not None and \
            fair_share.contended_by_others(tenant):
        rows = plan_scan_rows(planner, root)
        limit = int(properties.get("router_host_max_rows", 200_000))
        if rows <= limit * 4:
            return RouteDecision(
                "host", "fair-share overflow: device contended by "
                        f"other tenants, ~{rows:,.0f} scanned rows "
                        "host-eligible", rows)
    # per-fingerprint history baseline: observed latency beats estimates
    if history is not None and fingerprint:
        try:
            base = history.baseline(fingerprint, "elapsed_s")
        except Exception:           # noqa: BLE001 — history is advisory
            base = None
        if base is not None:
            med_ms = base[0] * 1000.0
            gate = float(properties.get("router_host_latency_ms", 30.0))
            if med_ms <= gate:
                return RouteDecision(
                    "host", f"history median {med_ms:.1f}ms <= "
                            f"{gate:g}ms over {base[2]} runs")
            return RouteDecision(
                "device", f"history median {med_ms:.1f}ms > {gate:g}ms")
    rows = plan_scan_rows(planner, root)
    limit = int(properties.get("router_host_max_rows", 200_000))
    if rows <= limit:
        return RouteDecision(
            "host", f"~{rows:,.0f} scanned rows <= {limit:,}", rows)
    return RouteDecision(
        "device", f"~{rows:,.0f} scanned rows > {limit:,}", rows)


# --------------------------------------------------------------------------
# host numpy interpreter
# --------------------------------------------------------------------------

# numpy int64 overflow warnings: the device path wraps silently (XLA
# semantics); the host mirror must not spam stderr while matching it
_NP_ERR = {"over": "ignore"}


class _HostRows:
    """Compacted host relation: columns as (data, valid) numpy pairs,
    no dead rows (the Batch live-mask discipline collapses to slicing)."""

    __slots__ = ("arrays", "valids", "n")

    def __init__(self, arrays: List[np.ndarray],
                 valids: List[np.ndarray], n: int):
        self.arrays = arrays
        self.valids = valids
        self.n = n

    def take(self, mask: np.ndarray) -> "_HostRows":
        return _HostRows([a[mask] for a in self.arrays],
                         [v[mask] for v in self.valids],
                         int(mask.sum()) if mask.dtype == np.bool_
                         else len(mask))


def _np_literal(expr: ir.Literal, n: int):
    if expr.value is None:
        return (np.zeros(n, dtype=expr.dtype.np_dtype),
                np.zeros(n, dtype=np.bool_))
    if expr.dtype.kind is TypeKind.VARCHAR:
        return (np.zeros(n, dtype=np.int32), np.ones(n, dtype=np.bool_))
    return (np.full(n, expr.value, dtype=expr.dtype.np_dtype),
            np.ones(n, dtype=np.bool_))


def np_eval(expr: ir.Expr, rows: _HostRows):
    """(data, valid) numpy evaluation mirroring ops/project.py eval_expr
    (same three-valued logic, decimal scale rules, truncating integer
    division, NULL-on-division-by-zero)."""
    from ..ops.project import (_apply_cmp, _decimal_compare,
                               _to_comparable, rescale)
    n = rows.n

    if isinstance(expr, ir.ColumnRef):
        return rows.arrays[expr.index], rows.valids[expr.index]

    if isinstance(expr, ir.Literal):
        return _np_literal(expr, n)

    if isinstance(expr, ir.Arith):
        ld, lv = np_eval(expr.left, rows)
        rd, rv = np_eval(expr.right, rows)
        valid = lv & rv
        out = expr.dtype
        lt, rt = expr.left.dtype, expr.right.dtype
        with np.errstate(**_NP_ERR):
            if out.kind is TypeKind.DECIMAL:
                if expr.op == '*':
                    res = ld.astype(np.int64) * rd.astype(np.int64)
                else:
                    l = rescale(ld, lt.scale, out.scale, xp=np) \
                        if lt.kind is TypeKind.DECIMAL \
                        else ld.astype(np.int64) * (10 ** out.scale)
                    r = rescale(rd, rt.scale, out.scale, xp=np) \
                        if rt.kind is TypeKind.DECIMAL \
                        else rd.astype(np.int64) * (10 ** out.scale)
                    res = l + r if expr.op == '+' else l - r
                return res, valid
            if out.kind is TypeKind.DOUBLE:
                l = _to_comparable(expr.left, ld, out, xp=np)
                r = _to_comparable(expr.right, rd, out, xp=np)
                if expr.op == '+':
                    res = l + r
                elif expr.op == '-':
                    res = l - r
                elif expr.op == '*':
                    res = l * r
                else:
                    res = l / np.where(r == 0, np.float64(1), r)
                    valid = valid & (r != 0)
                return res, valid
            l = ld.astype(out.np_dtype)
            r = rd.astype(out.np_dtype)
            if expr.op == '+':
                res = l + r
            elif expr.op == '-':
                res = l - r
            elif expr.op == '*':
                res = l * r
            else:
                safe_r = np.where(r == 0, np.ones_like(r), r)
                q = l // safe_r
                rem = l - q * safe_r
                q = q + np.where((rem != 0) & ((l < 0) != (r < 0)), 1,
                                 0).astype(q.dtype)
                res = q
                valid = valid & (r != 0)
        return res, valid

    if isinstance(expr, ir.Negate):
        d, v = np_eval(expr.arg, rows)
        return -d, v

    if isinstance(expr, ir.Compare):
        target = ir.comparable(expr.left, expr.right)
        ld, lv = np_eval(expr.left, rows)
        rd, rv = np_eval(expr.right, rows)
        if target.kind is TypeKind.DECIMAL:
            sa = expr.left.dtype.scale \
                if expr.left.dtype.kind is TypeKind.DECIMAL else 0
            sb = expr.right.dtype.scale \
                if expr.right.dtype.kind is TypeKind.DECIMAL else 0
            res = _decimal_compare(ld.astype(np.int64), sa,
                                   rd.astype(np.int64), sb, expr.op,
                                   xp=np)
            return res, lv & rv
        l = _to_comparable(expr.left, ld, target, xp=np)
        r = _to_comparable(expr.right, rd, target, xp=np)
        return _apply_cmp(expr.op, l, r), lv & rv

    if isinstance(expr, ir.Logical):
        parts = [np_eval(a, rows) for a in expr.args]
        d, v = parts[0]
        for (d2, v2) in parts[1:]:
            if expr.op == 'and':
                out_v = (v & v2) | (v & ~d) | (v2 & ~d2)
                d = d & d2
            else:
                out_v = (v & v2) | (v & d) | (v2 & d2)
                d = d | d2
            v = out_v
        return d, v

    if isinstance(expr, ir.Not):
        d, v = np_eval(expr.arg, rows)
        return ~d, v

    if isinstance(expr, ir.IsNull):
        d, v = np_eval(expr.arg, rows)
        res = v if expr.negated else ~v
        return res, np.ones_like(v)

    if isinstance(expr, ir.InList):
        d, v = np_eval(expr.arg, rows)
        res = np.zeros(n, dtype=np.bool_)
        for lit in expr.values:
            res = res | (d == np.asarray(lit.value, dtype=d.dtype))
        return res, v

    if isinstance(expr, ir.Between):
        lowered = ir.Logical('and', (
            ir.Compare('>=', expr.arg, expr.low),
            ir.Compare('<=', expr.arg, expr.high)))
        return np_eval(lowered, rows)

    if isinstance(expr, ir.Case):
        if expr.default is not None:
            acc_d, acc_v = np_eval(expr.default, rows)
            acc_d = acc_d.astype(expr.dtype.np_dtype)
        else:
            acc_d = np.zeros(n, dtype=expr.dtype.np_dtype)
            acc_v = np.zeros(n, dtype=np.bool_)
        for cond, val in reversed(expr.whens):
            cd, cv = np_eval(cond, rows)
            vd, vv = np_eval(val, rows)
            take = cd & cv
            acc_d = np.where(take, vd.astype(expr.dtype.np_dtype), acc_d)
            acc_v = np.where(take, vv, acc_v)
        return acc_d, acc_v

    if isinstance(expr, ir.Cast):
        d, v = np_eval(expr.arg, rows)
        src, dst = expr.arg.dtype, expr.dtype
        if src == dst:
            return d, v
        with np.errstate(**_NP_ERR):
            if dst.kind is TypeKind.DECIMAL:
                if src.kind is TypeKind.DECIMAL:
                    return rescale(d, src.scale, dst.scale, xp=np), v
                if src.kind is TypeKind.DOUBLE:
                    xs = d.astype(np.float64) * (10 ** dst.scale)
                    half_up = np.where(xs >= 0, np.floor(xs + 0.5),
                                       np.ceil(xs - 0.5))
                    return half_up.astype(np.int64), v
                return d.astype(np.int64) * (10 ** dst.scale), v
            if dst.kind is TypeKind.DOUBLE:
                if src.kind is TypeKind.DECIMAL:
                    return d.astype(np.float64) / (10 ** src.scale), v
                return d.astype(np.float64), v
            if dst.kind in (TypeKind.BIGINT, TypeKind.INTEGER):
                if src.kind is TypeKind.DECIMAL:
                    return rescale(d, src.scale, 0,
                                   xp=np).astype(dst.np_dtype), v
                return d.astype(dst.np_dtype), v
            if dst.kind is TypeKind.DATE:
                if src.kind is TypeKind.TIMESTAMP:
                    return (d // 86_400_000_000).astype(np.int32), v
                return d.astype(np.int32), v
            if dst.kind is TypeKind.TIMESTAMP:
                if src.kind is TypeKind.DATE:
                    return d.astype(np.int64) * 86_400_000_000, v
                return d.astype(np.int64), v
        raise HostUnsupported(f"cast {src} -> {dst}")

    if isinstance(expr, ir.ArrayConst):
        return np.zeros(n, dtype=np.int32), np.ones(n, dtype=np.bool_)

    if isinstance(expr, ir.DictPredicate):
        d, v = np_eval(expr.arg, rows)
        if len(expr.lut) == 0:
            return np.zeros(n, dtype=np.bool_), v
        lut = np.asarray(expr.lut, dtype=np.bool_)
        codes = np.clip(d.astype(np.int32), 0, len(expr.lut) - 1)
        return lut[codes], v

    if isinstance(expr, ir.DictValueMap):
        d, v = np_eval(expr.arg, rows)
        vals = np.asarray(expr.values)
        codes = np.clip(d.astype(np.int32), 0, len(expr.values) - 1)
        return vals[codes].astype(expr.dtype.np_dtype), v

    if isinstance(expr, ir.DerivedDict):
        d, v = np_eval(expr.arg, rows)
        lut = np.asarray(expr.lut, dtype=np.int32)
        codes = np.clip(d.astype(np.int32), 0, len(expr.lut) - 1)
        out = lut[codes]
        if expr.null_code is not None:
            out = np.where(v, out, np.int32(expr.null_code))
            v = np.ones_like(v)
        return out, v

    if isinstance(expr, ir.DecimalAvg):
        from ..ops.aggregate import avg_decimal_finalize
        sd, sv = np_eval(expr.sum, rows)
        cd, cv = np_eval(expr.count, rows)
        res = avg_decimal_finalize(sd.astype(np.int64),
                                   cd.astype(np.int64), xp=np)
        return res, sv & cv & (cd != 0)

    if isinstance(expr, ir.ScalarFunc):
        # two-limb decimal accumulation (SUM over DECIMAL — the mirror
        # of ops/project.py's $limb_* scalars; >> on int64 is arithmetic
        # in numpy, matching lax.shift_right_arithmetic)
        if expr.name == "$limb_hi":
            d, v = np_eval(expr.args[0], rows)
            return d.astype(np.int64) >> 32, v
        if expr.name == "$limb_lo":
            d, v = np_eval(expr.args[0], rows)
            return d.astype(np.int64) & np.int64(0xFFFFFFFF), v
        if expr.name == "$limb_combine":
            hd, hv = np_eval(expr.args[0], rows)
            ld, lv = np_eval(expr.args[1], rows)
            with np.errstate(**_NP_ERR):
                out = (hd.astype(np.int64) << 32) + ld.astype(np.int64)
            return out, hv & lv
        raise HostUnsupported(f"scalar function {expr.name}")

    raise HostUnsupported(type(expr).__name__)


def _np_global_aggregate(node: L.AggregateNode, rows: _HostRows
                         ) -> _HostRows:
    """Mirror of ops/aggregate.py global_aggregate: one always-live
    output row; sums accumulate int64 for integer inputs; empty/all-NULL
    inputs yield NULL (zero counts stay valid)."""
    arrays: List[np.ndarray] = []
    valids: List[np.ndarray] = []
    one = np.ones(1, dtype=np.bool_)
    for spec in node.aggs:
        if spec.func == "count_star":
            arrays.append(np.asarray([rows.n], dtype=np.int64))
            valids.append(one)
            continue
        idx = spec.arg.index
        data, valid = rows.arrays[idx], rows.valids[idx]
        cnt = int(valid.sum())
        if spec.func == "count":
            arrays.append(np.asarray([cnt], dtype=np.int64))
            valids.append(one)
            continue
        if spec.func == "sum":
            acc = np.int64 if np.issubdtype(data.dtype, np.integer) \
                else data.dtype
            with np.errstate(**_NP_ERR):
                s = np.where(valid, data.astype(acc), 0).sum()
            arrays.append(np.asarray([s], dtype=acc))
        else:                              # min / max
            from ..ops.aggregate import _identity
            ident = _identity(spec.func, data.dtype)
            red = np.min if spec.func == "min" else np.max
            masked = np.where(valid, data,
                              np.asarray(ident, dtype=data.dtype)) \
                if rows.n else np.asarray([ident], dtype=data.dtype)
            arrays.append(np.asarray([red(masked)], dtype=data.dtype))
        valids.append(np.asarray([cnt > 0]))
    return _HostRows(arrays, valids, 1)


def _np_sort(node: L.SortNode, rows: _HostRows) -> _HostRows:
    """Mirror of ops/sort.py sort_batch's key encoding (direction + null
    placement; NULL slots normalized so they compare equal), realized
    with a stable np.lexsort."""
    if rows.n == 0:
        return rows
    operands = []
    for spec in node.keys:
        data = rows.arrays[spec.index]
        valid = rows.valids[spec.index]
        null_rank = np.where(valid, 1, 0) if spec.nulls_first \
            else np.where(valid, 0, 1)
        d = np.where(valid, data, np.zeros((), data.dtype))
        if not spec.ascending:
            if d.dtype == np.bool_:
                d = ~d
            elif np.issubdtype(d.dtype, np.floating):
                d = -d
            else:
                d = np.invert(d)
        operands.append(null_rank.astype(np.int8))
        operands.append(d)
    # np.lexsort: LAST key is primary -> reverse the operand order
    perm = np.lexsort(tuple(reversed(operands)))
    out = _HostRows([a[perm] for a in rows.arrays],
                    [v[perm] for v in rows.valids], rows.n)
    if node.limit is not None:
        k = int(node.limit)
        out = _HostRows([a[:k] for a in out.arrays],
                        [v[:k] for v in out.valids], min(rows.n, k))
    return out


class HostRunner:
    """Executes a host-eligible plan on numpy — read-only over connector
    TableData, thread-safe, lock-free. `query_max_memory_mb` governs
    host executions too: every operator output charges the query's
    budget (cumulative, so the bound is conservative) and exceeding it
    raises the same user-facing QUERY_EXCEEDED_MEMORY the device path
    surfaces — routing to the host must not be a way around the
    operator's memory governance."""

    def __init__(self, catalog, limit_bytes: Optional[int] = None):
        self.catalog = catalog
        self.limit_bytes = limit_bytes
        self._charged = 0

    def _charge(self, rows: _HostRows) -> _HostRows:
        if self.limit_bytes is not None:
            self._charged += sum(a.nbytes for a in rows.arrays) + \
                sum(v.nbytes for v in rows.valids)
            if self._charged > self.limit_bytes:
                from .memory import ExceededMemoryLimitError
                raise ExceededMemoryLimitError(
                    "host", self._charged, self.limit_bytes)
        return rows

    def run(self, node: L.PlanNode) -> _HostRows:
        return self._charge(self._run(node))

    def _run(self, node: L.PlanNode) -> _HostRows:
        if isinstance(node, L.OutputNode):
            return self.run(node.child)
        if isinstance(node, L.ScanNode):
            data = self.catalog.get_table(node.catalog, node.schema_name,
                                          node.table)
            arrays, valids = [], []
            for i in node.column_indices:
                a = np.asarray(data.columns[i])
                arrays.append(a)
                v = None if data.valids is None else data.valids[i]
                valids.append(np.ones(len(a), dtype=np.bool_)
                              if v is None else np.asarray(v))
            from ..metrics import OPERATOR_ROWS
            OPERATOR_ROWS.inc(data.num_rows, operator="scan")
            return _HostRows(arrays, valids, data.num_rows)
        if isinstance(node, L.ValuesNode):
            arrays = [np.asarray(a) for a in node.arrays]
            valids = [np.ones(node.num_rows, dtype=np.bool_)
                      if v is None else np.asarray(v)
                      for v in node.valids]
            return _HostRows(arrays, valids, node.num_rows)
        if isinstance(node, L.FilterNode):
            child = self.run(node.child)
            d, v = np_eval(node.predicate, child)
            return child.take(np.asarray(d & v, dtype=np.bool_))
        if isinstance(node, L.ProjectNode):
            child = self.run(node.child)
            arrays, valids = [], []
            for e in node.exprs:
                d, v = np_eval(e, child)
                arrays.append(np.asarray(d))
                valids.append(np.asarray(v, dtype=np.bool_))
            return _HostRows(arrays, valids, child.n)
        if isinstance(node, L.AggregateNode):
            return _np_global_aggregate(node, self.run(node.child))
        if isinstance(node, L.SortNode):
            return _np_sort(node, self.run(node.child))
        if isinstance(node, L.LimitNode):
            child = self.run(node.child)
            k = int(node.count)
            return _HostRows([a[:k] for a in child.arrays],
                             [v[:k] for v in child.valids],
                             min(child.n, k))
        raise HostUnsupported(type(node).__name__)


def run_host(session, rel, root: L.OutputNode, t0: float):
    """Execute a pre-planned host-eligible query on numpy and decode it
    with the SAME scope/dictionary machinery as the device path — rows
    are produced by session.decode_rows either way, so formatting cannot
    diverge between routes."""
    import time
    limit = session.properties.get("query_max_memory_mb")
    runner = HostRunner(session.catalog,
                        limit_bytes=(int(limit) << 20)
                        if limit else None)
    out = runner.run(root)
    names = list(root.names)
    rows = session.decode_rows(rel, out.arrays, out.valids)
    from .session import QueryResult
    return QueryResult(names, rows, time.monotonic() - t0)
