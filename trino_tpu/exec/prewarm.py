"""Cold-start elimination: AOT plan pre-warming + compile-aware routing.

The bench trajectory's cold-start wall (cold q5 SF100 ~297s vs ~12s
steady) is almost entirely XLA compilation. The reference engine never
pays an analogous penalty because its long-lived JVM keeps generated
PageProcessor bytecode warm across queries; the XLA analog needs three
composable pieces, and this module is the conductor for all of them:

1. **AOT pre-warming** — at coordinator start, rank the top-N
   historical plan fingerprints (`server/history.py
   top_fingerprints`), re-plan their SQL, and execute each once in a
   background thread under `CompileRecorder.prewarm_context()`. Every
   jit site along the path compiles off the query path; the first
   query-path hit on a prewarmed program claims its compile wall as
   `compile_seconds_saved_total`. Bounded by TRINO_TPU_PREWARM_BUDGET_S.
2. **Shape canonicalization** — data-dependent capacities land on the
   `bucket_capacity` lattice ({2^k, 1.5*2^k}, min 1024), so the
   canonical shape set is enumerable: `canonical_lattice()` is what the
   warm-manifest ships to joining workers and what `warm_shapes`
   compiles against.
3. **Compile-aware routing** — while a fingerprint's device program is
   cold (no warm completed, or a prewarm in flight), `decide_route`
   sends host-eligible queries to the bit-exact numpy interpreter and
   the serving layer kicks a background device warm; once warm, the
   same fingerprint routes to device. No user-facing query blocks on a
   multi-second compile.

The engine is OFF unless TRINO_TPU_PREWARM is set truthy (or a caller
enables it explicitly); disabled, every surface returns the pre-prewarm
behavior exactly — `decide_route` never sees a cold signal and no
background threads start.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional

log = logging.getLogger("trino_tpu.prewarm")

DEFAULT_TOP_N = 8
DEFAULT_BUDGET_S = 60.0
# canonical-shape warm ceiling: lattice points above this are rare
# enough (and expensive enough to compile) that only a real plan warm
# should pay for them
DEFAULT_MAX_SHAPE = 1 << 20


def _env_truthy(name: str, default: str = "0") -> bool:
    return os.environ.get(name, default).strip().lower() in (
        "1", "true", "on", "yes")


def prewarm_enabled_by_env() -> bool:
    return _env_truthy("TRINO_TPU_PREWARM")


def canonical_lattice(max_cap: int = DEFAULT_MAX_SHAPE) -> List[int]:
    """Every bucket_capacity lattice point in [1024, max_cap] — the
    enumerable canonical shape set that shape canonicalization buys.
    Two points per octave: 2^k and 1.5*2^k."""
    out = []
    cap = 1024
    while cap <= max_cap:
        out.append(cap)
        half = (cap * 3) // 2
        if half <= max_cap:
            out.append(half)
        cap <<= 1
    return out


def compile_cache_stats() -> dict:
    """Persistent compile-cache stats for the /v1/status heartbeat:
    whether the JAX persistent cache is active, where, and how much it
    holds. File counting is best-effort and bounded."""
    from .. import COMPILE_CACHE_DIR
    out = {"active": COMPILE_CACHE_DIR is not None,
           "dir": COMPILE_CACHE_DIR, "files": 0, "bytes": 0}
    if COMPILE_CACHE_DIR and os.path.isdir(COMPILE_CACHE_DIR):
        try:
            with os.scandir(COMPILE_CACHE_DIR) as it:
                for i, ent in enumerate(it):
                    if i >= 10000:
                        break
                    if ent.is_file():
                        out["files"] += 1
                        try:
                            out["bytes"] += ent.stat().st_size
                        except OSError:
                            pass
        except OSError:
            pass
    return out


# representative canonical-shape program: a masked reduction over one
# padded column — what every operator's epilogue looks like to XLA at a
# given capacity. A joining worker compiles this per lattice point so
# the device allocator, the dialect pipelines, and (when shared) the
# persistent cache are warm at every canonical shape before the first
# fragment lands.
def _make_warm_kernel():
    from .profiler import recorded_jit

    @recorded_jit(site="prewarm.shape")
    def _warm_kernel(data, valid, live):
        import jax.numpy as jnp
        ok = valid & live
        return (jnp.sum(jnp.where(ok, data, 0)),
                jnp.sum(ok.astype(jnp.int32)))

    return _warm_kernel


_WARM_KERNEL = None
_WARM_KERNEL_LOCK = threading.Lock()


def _warm_kernel():
    global _WARM_KERNEL
    with _WARM_KERNEL_LOCK:
        if _WARM_KERNEL is None:
            _WARM_KERNEL = _make_warm_kernel()
        return _WARM_KERNEL


class PrewarmEngine:
    """Coordinator/worker-side prewarm conductor.

    Coordinator wiring (server/coordinator.py CoordinatorState): the
    engine gets the session, the history store, and the dispatcher's
    exec lock; `maybe_start()` launches the AOT warm thread when the
    engine is enabled; `ServingLayer.run_routed` consults
    `device_cold()` through `decide_route` and calls `ensure_warming` /
    `mark_warm` around device runs. Worker wiring (server/worker.py):
    a joining worker builds a detached engine, pulls the coordinator's
    `manifest()` over GET /v1/prewarm, and runs `warm_shapes` before
    its first ACTIVE announce."""

    def __init__(self, session=None, history=None,
                 exec_lock: Optional[threading.Lock] = None,
                 enabled: Optional[bool] = None,
                 top_n: Optional[int] = None,
                 budget_s: Optional[float] = None,
                 run_sql: Optional[Callable[[str], object]] = None):
        self.session = session
        self.history = history
        self.exec_lock = exec_lock
        self.enabled = prewarm_enabled_by_env() if enabled is None \
            else bool(enabled)
        self.top_n = int(os.environ.get("TRINO_TPU_PREWARM_TOP_N",
                                        DEFAULT_TOP_N)) \
            if top_n is None else int(top_n)
        self.budget_s = float(os.environ.get("TRINO_TPU_PREWARM_BUDGET_S",
                                             DEFAULT_BUDGET_S)) \
            if budget_s is None else float(budget_s)
        self._run_sql = run_sql
        self._lock = threading.Lock()
        self._warmed: set = set()          # fingerprints with a warm program
        self._inflight: set = set()        # fingerprints compiling right now
        self._sql_by_fp: Dict[str, str] = {}
        self._threads: List[threading.Thread] = []
        self._deadline: Optional[float] = None
        self.warm_rounds = 0               # completed warm_all passes
        self.shape_warms = 0               # canonical shapes compiled
        self.started_at: Optional[float] = None
        if self.enabled and self.session is not None:
            # the chunked-driver fused-compile warm (exec/chunked.py)
            # rides the same opt-in as the engine itself
            self.session.properties["prewarm_chunks"] = True

    # -- cold/warm state (the router's signal) ------------------------------

    def device_cold(self, fingerprint: Optional[str]) -> bool:
        """True while this fingerprint's device program has not been
        warmed (by prewarm OR by a completed device run). The router
        sends host-eligible queries to the host interpreter for exactly
        this window; `mark_warm` closes it."""
        if not self.enabled or not fingerprint:
            return False
        with self._lock:
            return fingerprint not in self._warmed

    def is_warm(self, fingerprint: str) -> bool:
        with self._lock:
            return fingerprint in self._warmed

    def is_inflight(self, fingerprint: str) -> bool:
        with self._lock:
            return fingerprint in self._inflight

    def mark_warm(self, fingerprint: Optional[str]) -> None:
        """A device program for this fingerprint exists now — either a
        prewarm finished or a query-path device run completed (which
        compiled it on-path)."""
        if not fingerprint:
            return
        with self._lock:
            self._warmed.add(fingerprint)
            self._inflight.discard(fingerprint)

    # -- warming ------------------------------------------------------------

    def _budget_left(self) -> float:
        if self._deadline is None:
            return self.budget_s
        return self._deadline - time.monotonic()

    def warm_fingerprint(self, fingerprint: str, sql: str,
                         context: str = "") -> bool:
        """Compile this statement's device programs off the query path:
        execute it once under prewarm_context (every jit site along the
        plan records an off-path prewarm compile), then mark the
        fingerprint warm. Returns False when the warm failed or was
        skipped (already warm / in flight / no runner). `context` is the
        triggering query's `query=... trace=...` log prefix, so a warm
        kicked by a served query greps back to it."""
        if not sql:
            return False
        with self._lock:
            if fingerprint in self._warmed or \
                    fingerprint in self._inflight:
                return False
            self._inflight.add(fingerprint)
            self._sql_by_fp.setdefault(fingerprint, sql)
        from .profiler import RECORDER
        ok = False
        try:
            runner = self._run_sql
            if runner is None and self.session is not None:
                runner = self.session.execute
            if runner is None:
                return False
            with RECORDER.prewarm_context():
                if self.exec_lock is not None:
                    with self.exec_lock:
                        runner(sql)
                else:
                    runner(sql)
            ok = True
        except Exception as e:    # noqa: BLE001 — warming is best-effort
            log.warning("%sprewarm of %s failed: %s", context,
                        fingerprint, e)
        finally:
            with self._lock:
                self._inflight.discard(fingerprint)
                if ok:
                    self._warmed.add(fingerprint)
        return ok

    def ensure_warming(self, fingerprint: str, sql: str,
                       context: str = "") -> None:
        """Kick a background warm for a cold fingerprint the serving
        layer just routed to host. Dedup'd: one warm per fingerprint.
        When the warm completes the fingerprint routes to device."""
        if not self.enabled or not fingerprint or not sql:
            return
        with self._lock:
            if fingerprint in self._warmed or \
                    fingerprint in self._inflight:
                return
        t = threading.Thread(
            target=self.warm_fingerprint,
            args=(fingerprint, sql, context),
            name=f"prewarm-{fingerprint[:8]}", daemon=True)
        t.start()
        self._threads.append(t)

    def warm_all(self) -> int:
        """One AOT pass over the top-N historical fingerprints, bounded
        by the budget. Returns how many statements warmed."""
        if self.history is None:
            return 0
        self._deadline = time.monotonic() + self.budget_s
        warmed = 0
        for ent in self.history.top_fingerprints(self.top_n):
            if self._budget_left() <= 0:
                log.info("prewarm budget exhausted after %d statements",
                         warmed)
                break
            if self.warm_fingerprint(ent["fingerprint"], ent["sql"]):
                warmed += 1
        self.warm_rounds += 1
        return warmed

    def warm_shapes(self, capacities: Optional[List[int]] = None,
                    max_cap: int = DEFAULT_MAX_SHAPE) -> int:
        """Compile the representative canonical-shape kernel at each
        lattice capacity (joining-worker handshake path). Bounded by
        the budget; returns how many shapes compiled."""
        import numpy as np
        caps = capacities if capacities is not None \
            else canonical_lattice(max_cap)
        if self._deadline is None:
            self._deadline = time.monotonic() + self.budget_s
        kern = _warm_kernel()
        from .profiler import RECORDER
        done = 0
        for cap in caps:
            if self._budget_left() <= 0:
                break
            try:
                import jax
                import jax.numpy as jnp
                data = jnp.zeros(int(cap), dtype=jnp.int64)
                mask = jnp.zeros(int(cap), dtype=bool)
                with RECORDER.prewarm_context():
                    jax.block_until_ready(kern(data, mask, mask))
                done += 1
            except Exception as e:  # noqa: BLE001 — best-effort
                log.warning("shape warm at %d failed: %s", cap, e)
                break
        self.shape_warms += done
        return done

    def maybe_start(self) -> bool:
        """Launch the startup AOT warm in the background when enabled.
        Returns whether a warm thread started."""
        if not self.enabled or self.history is None:
            return False
        self.started_at = time.time()
        t = threading.Thread(target=self.warm_all, name="prewarm-aot",
                             daemon=True)
        t.start()
        self._threads.append(t)
        return True

    def wait(self, timeout_s: float = 30.0) -> None:
        """Join outstanding warm threads (tests + the worker handshake)."""
        deadline = time.monotonic() + timeout_s
        for t in list(self._threads):
            t.join(timeout=max(0.0, deadline - time.monotonic()))

    # -- read surface -------------------------------------------------------

    def manifest(self) -> dict:
        """The warm-manifest a joining worker pulls before announcing
        ACTIVE: top historical fingerprints (with the SQL to re-plan
        and rank scores) plus the canonical shape lattice."""
        fps = self.history.top_fingerprints(self.top_n) \
            if self.history is not None else []
        return {"enabled": self.enabled,
                "fingerprints": fps,
                "shapes": canonical_lattice(),
                "budget_s": self.budget_s}

    def stats(self) -> dict:
        from .profiler import RECORDER
        with self._lock:
            warmed = len(self._warmed)
            inflight = len(self._inflight)
        t = RECORDER.totals()
        return {"enabled": self.enabled,
                "warmedFingerprints": warmed,
                "inflight": inflight,
                "warmRounds": self.warm_rounds,
                "shapeWarms": self.shape_warms,
                "prewarmedPrograms": t["prewarmedPrograms"],
                "prewarmHits": t["prewarmHits"],
                "compileSecondsSaved": t["compileSecondsSaved"],
                "compileCache": compile_cache_stats()}
