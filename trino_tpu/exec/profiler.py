"""JIT-compile observability: the central compile-event recorder.

Reference: the reference engine keeps ExpressionCompiler/PageProcessor
codegen warm in long-lived caches and exposes their hit rates over JMX
(sql/gen/ExpressionCompiler.java:38 with its CacheStatsMBean); operator
wall times come from OperatorStats with explicit scheduled/blocked
splits. The XLA analog of codegen is jit tracing + compilation, and
under async dispatch its cost lands wherever the first blocking fetch
happens — invisible to host wall clocks unless measured at the jit
boundary itself.

Here: every jit site routes through `recorded_jit`/`instrument`, which
detect a fresh XLA compile by watching the jitted callable's cache size
across the call. Each compile (and each cache hit) is recorded with its
site, an argument-shape fingerprint (the jaxpr-identity proxy: same
tree of shapes/dtypes + statics => same trace => same program), and the
compile duration, into:

- the process-global `RECORDER` ring (served raw at `GET /v1/jit` and
  as `system.runtime.jit_cache`),
- Prometheus families (trino_tpu_jit_compiles_total{site},
  trino_tpu_jit_cache_hits_total{site}, trino_tpu_jit_compile_seconds),
- the thread-bound ExecStats (`jit_compiles` — the executor binds its
  stats object per dispatch thread, so per-executor counts attribute
  compiles to the executor whose dispatch triggered them),
- a per-thread compile-seconds accumulator the profiled dispatch path
  reads to split operator wall into device/host/compile components.

Design constraints: recording must never change execution (a wrapper
failure falls through to the raw call), must cost ~a cache-size probe
per call on the hot path, and must stay silent inside an outer trace
(a jitted kernel calling another jitted kernel records nothing — the
outer program owns the compile).
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict, deque
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional


@dataclass(frozen=True)
class CompileEvent:
    site: str
    fingerprint: str
    duration_s: float       # trace+compile wall for misses, 0.0 for hits
    hit: bool               # True = the program cache already had it
    when: float             # time.time() at record


def _arg_fingerprint(args, kwargs) -> str:
    """Cheap jaxpr-identity proxy: the tree of array (shape, dtype)
    leaves plus static leaves, hashed. Two calls with the same
    fingerprint hit the same compiled program for a given jit site.
    Built on Python's tuple hash (not a cryptographic digest) because
    this runs on EVERY instrumented dispatch — the fingerprint is an
    in-process cache key, not a cross-process identity."""
    import jax
    parts = []
    for leaf in jax.tree_util.tree_leaves((args, kwargs)):
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            parts.append((shape, str(getattr(leaf, "dtype", "?"))))
        else:
            try:
                parts.append(hash(leaf))
            except TypeError:
                parts.append(repr(leaf)[:48])
    return f"{hash(tuple(parts)) & 0xFFFFFFFFFFFFFFFF:016x}"


class CompileRecorder:
    """Thread-safe compile-event ring + per-(site, fingerprint) cache
    aggregates. One per process (module-level RECORDER): jitted programs
    are process-global, so their compile ledger is too."""

    MAX_EVENTS = 512
    MAX_ENTRIES = 2048

    def __init__(self):
        self._lock = threading.Lock()
        self.events: "deque[CompileEvent]" = deque(maxlen=self.MAX_EVENTS)
        # (site, fingerprint) -> mutable aggregate dict
        self._entries: "OrderedDict[tuple, dict]" = OrderedDict()
        self.total_compiles = 0
        self.total_hits = 0
        self.total_compile_s = 0.0
        # shape-canonicalization signal: every fingerprint ever seen per
        # site (survives the entry LRU — the lint cares about distinct
        # shapes produced, not about what is still cached)
        self._site_shapes: Dict[str, set] = {}
        # (site, fingerprint) -> off-path compile seconds, pending the
        # first query-path hit that claims the saving
        self._prewarm_pending: Dict[tuple, float] = {}
        self.total_prewarmed = 0
        self.total_prewarm_hits = 0
        self.total_saved_s = 0.0
        self._tl = threading.local()

    # -- per-thread attribution --------------------------------------------

    def bind_stats(self, stats) -> None:
        """Attribute compiles recorded on THIS thread to `stats`
        (ExecStats.jit_compiles). The executor binds its stats object at
        dispatch entry; worker task threads each bind their own."""
        self._tl.stats = stats

    def thread_compile_seconds(self) -> float:
        """Cumulative compile seconds recorded on this thread — the
        profiled dispatch path diffs this around a dispatch to isolate
        the compile component of an operator's wall."""
        return getattr(self._tl, "compile_s", 0.0)

    @contextmanager
    def site_context(self, prefix: str):
        """Prefix every site recorded on this thread inside the block —
        the spill tier wraps its partition-wise re-runs so their kernel
        compiles attribute to the spill path, not the resident one."""
        prev = getattr(self._tl, "site_prefix", None)
        self._tl.site_prefix = prefix
        try:
            yield
        finally:
            self._tl.site_prefix = prev

    @contextmanager
    def prewarm_context(self):
        """Mark every compile recorded on this thread inside the block
        as an OFF-PATH prewarm compile (exec/prewarm.py): it counts as
        prewarm_compiles_total instead of charging the thread-bound
        ExecStats, and the first later query-path hit on the same
        (site, fingerprint) claims its wall as compile seconds saved."""
        prev = getattr(self._tl, "prewarm", False)
        self._tl.prewarm = True
        try:
            yield
        finally:
            self._tl.prewarm = prev

    # -- recording ---------------------------------------------------------

    def record(self, site: str, fingerprint: str, duration_s: float,
               hit: bool) -> None:
        prefix = getattr(self._tl, "site_prefix", None)
        if prefix:
            site = f"{prefix}:{site}"
        from ..metrics import (COMPILE_SECONDS_SAVED, JIT_CACHE_HITS,
                               JIT_COMPILES, JIT_COMPILE_SECONDS,
                               JIT_DISTINCT_SHAPES, PREWARM_COMPILES,
                               PREWARM_HITS)
        prewarming = getattr(self._tl, "prewarm", False)
        ev = CompileEvent(site, fingerprint, duration_s if not hit
                          else 0.0, hit, time.time())
        shape_count = None
        saved_s = None
        prewarm_hit = False
        with self._lock:
            self.events.append(ev)
            key = (site, fingerprint)
            e = self._entries.get(key)
            if e is None:
                if len(self._entries) >= self.MAX_ENTRIES:
                    self._entries.popitem(last=False)
                e = self._entries[key] = {
                    "site": site, "fingerprint": fingerprint,
                    "compiles": 0, "hits": 0, "compile_ms": 0.0,
                    "last_compile_ms": 0.0, "last_used": 0.0,
                    "prewarmed": False, "prewarm_hits": 0}
            shapes = self._site_shapes.setdefault(site, set())
            if fingerprint not in shapes:
                shapes.add(fingerprint)
                shape_count = len(shapes)
            e["last_used"] = ev.when
            if hit:
                e["hits"] += 1
                self.total_hits += 1
                if e.get("prewarmed") and not prewarming:
                    prewarm_hit = True
                    e["prewarm_hits"] += 1
                    self.total_prewarm_hits += 1
                    # the first query-path hit claims the avoided
                    # compile wall; later hits were free anyway
                    saved_s = self._prewarm_pending.pop(key, None)
                    if saved_s is not None:
                        self.total_saved_s += saved_s
            else:
                e["compiles"] += 1
                e["compile_ms"] += duration_s * 1000
                e["last_compile_ms"] = duration_s * 1000
                self.total_compiles += 1
                self.total_compile_s += duration_s
                if prewarming:
                    e["prewarmed"] = True
                    self._prewarm_pending[key] = duration_s
                    self.total_prewarmed += 1
        if shape_count is not None:
            JIT_DISTINCT_SHAPES.set(shape_count, site=site)
        if hit:
            JIT_CACHE_HITS.inc(site=site)
            if prewarm_hit:
                PREWARM_HITS.inc()
            if saved_s is not None:
                COMPILE_SECONDS_SAVED.inc(saved_s)
        else:
            JIT_COMPILES.inc(site=site)
            JIT_COMPILE_SECONDS.observe(duration_s)
            if prewarming:
                PREWARM_COMPILES.inc()
            # per-thread attribution: the executor whose dispatch thread
            # triggered the compile owns it
            self._tl.compile_s = getattr(self._tl, "compile_s", 0.0) \
                + duration_s
            stats = getattr(self._tl, "stats", None)
            if stats is not None:
                stats.jit_compiles += 1

    # -- read surface ------------------------------------------------------

    def snapshot(self) -> List[dict]:
        """Per-(site, fingerprint) aggregates, most-recently-used last —
        the /v1/jit and system.runtime.jit_cache payload."""
        with self._lock:
            return [dict(e) for e in self._entries.values()]

    def totals(self) -> dict:
        with self._lock:
            return {"compiles": self.total_compiles,
                    "hits": self.total_hits,
                    "compileSeconds": round(self.total_compile_s, 6),
                    "entries": len(self._entries),
                    "prewarmedPrograms": self.total_prewarmed,
                    "prewarmHits": self.total_prewarm_hits,
                    "compileSecondsSaved": round(self.total_saved_s, 6)}

    def site_shape_counts(self) -> Dict[str, int]:
        """Distinct fingerprints ever recorded per site — what the
        shape-canonicalization lint asserts ceilings over."""
        with self._lock:
            return {s: len(fps) for s, fps in self._site_shapes.items()}

    def clear(self) -> None:
        from ..metrics import JIT_DISTINCT_SHAPES
        with self._lock:
            self.events.clear()
            self._entries.clear()
            self.total_compiles = 0
            self.total_hits = 0
            self.total_compile_s = 0.0
            sites = list(self._site_shapes)
            self._site_shapes.clear()
            self._prewarm_pending.clear()
            self.total_prewarmed = 0
            self.total_prewarm_hits = 0
            self.total_saved_s = 0.0
        for s in sites:
            JIT_DISTINCT_SHAPES.set(0, site=s)


RECORDER = CompileRecorder()


def _trace_clean() -> bool:
    try:
        import jax.core
        return jax.core.trace_state_clean()
    except Exception:        # noqa: BLE001 — recording is best-effort
        return True


def instrument(jitted: Callable, site: str,
               fingerprint: Optional[str] = None,
               recorder: Optional[CompileRecorder] = None) -> Callable:
    """Wrap an already-jitted callable with compile-event recording.
    Detection is a cache-size probe around the call; a fixed
    `fingerprint` (e.g. the fused pipeline's plan hash) skips the
    arg-shape hash. Calls made inside an outer trace bypass recording
    entirely (the outer program owns the compile), as does any probe
    failure — the wrapper can never change execution."""
    rec = recorder or RECORDER
    probe = getattr(jitted, "_cache_size", None)

    def wrapped(*args, **kwargs):
        if probe is None or not _trace_clean():
            return jitted(*args, **kwargs)
        try:
            before = probe()
        except Exception:        # noqa: BLE001 — probe is best-effort
            return jitted(*args, **kwargs)
        t0 = time.monotonic()
        out = jitted(*args, **kwargs)
        dt = time.monotonic() - t0
        try:
            hit = probe() == before
            fp = fingerprint if fingerprint is not None else \
                _arg_fingerprint(args, kwargs)
            rec.record(site, fp, dt, hit)
        except Exception:        # noqa: BLE001 — never break the call
            pass
        return out

    wrapped.__name__ = f"recorded[{site}]"
    wrapped.__wrapped__ = jitted
    return wrapped


def recorded_jit(site: Optional[str] = None, static_argnums=None,
                 static_argnames=None, **jit_kwargs) -> Callable:
    """Decorator: jax.jit + compile recording in one step — the drop-in
    replacement for `@functools.partial(jax.jit, static_argnums=...)`
    at every module-level jit site."""
    def deco(fn):
        import jax
        kw = dict(jit_kwargs)
        if static_argnums is not None:
            kw["static_argnums"] = static_argnums
        if static_argnames is not None:
            kw["static_argnames"] = static_argnames
        s = site or f"{fn.__module__.rsplit('.', 1)[-1]}.{fn.__name__}"
        return instrument(jax.jit(fn, **kw), s)
    return deco


def device_memory_stats() -> dict:
    """Live device/HBM stats of this process's first accelerator, in the
    /v1/status heartbeat shape. TPU/GPU backends report allocator stats;
    CPU returns platform-only (the fields read 0)."""
    try:
        import jax
        d = jax.local_devices()[0]
        stats = None
        if hasattr(d, "memory_stats"):
            try:
                stats = d.memory_stats()
            except Exception:    # noqa: BLE001 — backend-dependent
                stats = None
        out = {"platform": d.platform, "deviceCount": jax.local_device_count()}
        if stats:
            out["bytesInUse"] = int(stats.get("bytes_in_use", 0))
            out["bytesLimit"] = int(stats.get("bytes_limit", 0))
            out["peakBytesInUse"] = int(stats.get("peak_bytes_in_use", 0))
        else:
            out["bytesInUse"] = 0
            out["bytesLimit"] = 0
            out["peakBytesInUse"] = 0
        return out
    except Exception:            # noqa: BLE001 — stats are best-effort
        return {}
