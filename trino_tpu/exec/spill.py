"""Host-spill execution: joins and aggregations that exceed the pool.

Reference: the spilling operators — HashBuilderOperator's spill-to-disk
partitions (operator/join/PartitionedConsumption.java), the spillable
aggregation builder (operator/aggregation/builder/
SpillableHashAggregationBuilder.java), and GenericPartitioningSpiller's
radix partitioning by hash (spiller/GenericPartitioningSpiller.java:66).
"Design Trade-offs for a Robust Dynamic Hybrid Hash Join"
(arXiv:2112.02480) is the blueprint: graceful partition-and-spill, not a
bigger budget, is what keeps joins correct under constrained memory.

TPU shape: HBM is the scarce tier (16-32 GB/chip), host RAM + local disk
are the spill tiers. When an operator's reservation cannot fit the pool
even after revocation, the executor retries it here:

- both sides move to host and radix-partition by the SAME splitmix64 key
  hash the partitioned exchange uses (server/tasks.partition_assignment),
  so co-partitioned rows always land together;
- partitions persist through HostSpiller — host RAM for small partitions,
  disk containers with the exchange-spool framing + per-page CRC32C for
  large ones (a corrupt or failed write degrades to the RAM copy, never
  to wrong answers);
- each partition then joins/aggregates alone, bounded by partition size,
  and the outputs concatenate. Equality classes never straddle a hash
  partition, and stable partitioning preserves within-group row order,
  so results are bit-exact vs the resident kernels (modulo row order,
  which no operator here guarantees anyway).
"""

from __future__ import annotations

import os
import tempfile
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..batch import Batch, batch_from_numpy, batch_to_numpy, bucket_capacity
from ..planner import logical as L


class SpillReadError(RuntimeError):
    """A spilled partition could not be read back (both the disk
    container and the RAM fallback are gone) — retryable at query level."""


class HostSpiller:
    """Two-tier partition store: host RAM first, disk (exchange-spool
    framing, CRC32C-verified) for partitions past `disk_min_bytes`.

    Disk writes are verified by immediate read-back: a failed or corrupt
    write (chaos SPOOL_WRITE faults, disk full) keeps the RAM copy and
    counts trino_tpu_spill_retries_total — the spill tier can lose
    durability, never correctness."""

    def __init__(self, root: Optional[str] = None, injector=None,
                 disk_min_bytes: int = 4 << 20, force_disk: bool = False):
        from ..server.exchange_spool import ExchangeSpool
        self.root = root or os.environ.get("TRINO_TPU_SPILL_DIR") or \
            tempfile.mkdtemp(prefix="trino_tpu_spill_")
        self.spool = ExchangeSpool(root=self.root, injector=injector)
        self.disk_min_bytes = disk_min_bytes
        self.force_disk = force_disk
        self._ram: Dict[str, bytes] = {}
        self.bytes_spilled = 0
        self.disk_writes = 0
        self.write_recoveries = 0
        self._seq = 0

    @property
    def injector(self):
        return self.spool.injector

    @injector.setter
    def injector(self, inj) -> None:
        self.spool.injector = inj

    def next_key(self, hint: str) -> str:
        self._seq += 1
        return f"spill-{hint}-{self._seq}"

    def put(self, key: str, arrays: List[np.ndarray],
            valids: List[np.ndarray]) -> None:
        from ..metrics import SPILL_BYTES, SPILL_PARTITIONS, SPILL_RETRIES
        from ..server.pageserde import encode_page
        page = encode_page(arrays, valids)
        self.bytes_spilled += len(page)
        SPILL_BYTES.inc(len(page))
        SPILL_PARTITIONS.inc()
        if not self.force_disk and len(page) < self.disk_min_bytes:
            self._ram[key] = page
            return
        self.spool.put(key, [page])
        self.disk_writes += 1
        back = self.spool.get(key)        # read-back verify (CRC32C)
        if back is None or back != [page]:
            # write failed or the container came back corrupt: the RAM
            # copy stays authoritative — retryable, no wrong answer
            self.write_recoveries += 1
            SPILL_RETRIES.inc()
            self.spool.delete(key)
            self._ram[key] = page

    def get(self, key: str) -> Tuple[List[np.ndarray], List[np.ndarray]]:
        """Read one partition back, verified; the entry is consumed."""
        from ..server.pageserde import decode_page
        page = self._ram.pop(key, None)
        if page is None:
            pages = self.spool.get(key)
            self.spool.delete(key)
            if not pages:
                raise SpillReadError(f"spilled partition {key} lost")
            page = pages[0]
        return decode_page(page)

    def discard(self, keys) -> None:
        for k in keys:
            self._ram.pop(k, None)
            self.spool.delete(k)

    def clear(self) -> None:
        self._ram.clear()
        self.spool.clear()


def get_spiller(executor) -> HostSpiller:
    if executor.spiller is None:
        executor.spiller = HostSpiller(
            force_disk=getattr(executor, "spill_force_disk", False))
    return executor.spiller


# --------------------------------------------------------------------------
# host-side helpers
# --------------------------------------------------------------------------

def _side_to_host(executor, child: L.PlanNode) -> tuple:
    """Run a child subtree and move its LIVE rows to host, releasing the
    device reservations. The transient device batch runs under the
    pool's grace window (its bytes are revocable in spirit: the next
    statement revokes them to host)."""
    with executor.pool.grace():
        batch = executor.run(child)
        arrs, vals = batch_to_numpy(batch)
    executor.release_path_reservations(child, keep=executor._subst)
    return arrs, vals


def _host_bytes(arrays, valids) -> int:
    return int(sum(a.nbytes for a in arrays) +
               sum(v.nbytes for v in valids))


def _integer_keys(output, idxs) -> bool:
    for k in idxs:
        dt = np.dtype(output[k][1].np_dtype)
        if not (np.issubdtype(dt, np.integer) or dt == np.bool_):
            return False
    return True


def _pick_partitions(executor, total_bytes: int) -> int:
    """Enough partitions that one partition's working set fits a third
    of the pool's headroom, clamped to [2, 64] and the configured
    default as the floor."""
    base = max(2, int(getattr(executor, "spill_partitions", 8)))
    avail = max(1 << 20, executor.pool.available())
    need = -(-total_bytes // max(1, avail // 3))      # ceil div
    p = base
    while p < need and p < 64:
        p *= 2
    return p


def _partition_ids(arrays, valids, key_idxs, count: int) -> np.ndarray:
    from ..server.tasks import partition_assignment
    return partition_assignment(arrays, valids, key_idxs, count)


def _spill_partitions(executor, hint: str, arrays, valids, key_idxs,
                      count: int) -> List[str]:
    """Radix-partition a host column set and spill each partition; the
    source arrays can be dropped by the caller afterwards. np boolean
    take keeps within-partition row order (stable), which is what makes
    per-group float sums bit-exact on read-back."""
    spiller = get_spiller(executor)
    part = _partition_ids(arrays, valids, key_idxs, count)
    keys = []
    for p in range(count):
        m = part == p
        keys.append(spiller.next_key(f"{hint}-p{p}"))
        spiller.put(keys[-1], [a[m] for a in arrays],
                    [v[m] for v in valids])
    return keys


# --------------------------------------------------------------------------
# partition-local equi-join on host
# --------------------------------------------------------------------------

def _packed_key(parrs, pvalids, barrs, bvalids, pkeys, bkeys):
    """One int64 key per row for each side (range-compressed multi-key
    packing, shared mins so equality is preserved), plus validity masks.
    Returns (pk, pok, bk, bok) or None when the packed key would overflow
    62 bits (caller takes the dict fallback)."""
    def cols(arrs, vals, idxs):
        n = len(arrs[0]) if arrs else 0
        ok = np.ones(n, np.bool_)
        cs = []
        for i in idxs:
            cs.append(np.asarray(arrs[i]).astype(np.int64))
            ok &= np.asarray(vals[i], np.bool_)
        return cs, ok

    pc, pok = cols(parrs, pvalids, pkeys)
    bc, bok = cols(barrs, bvalids, bkeys)
    if len(pc) == 1:
        return pc[0], pok, bc[0], bok
    lims = []
    for j in range(len(pc)):
        vals = []
        for c, ok in ((pc[j], pok), (bc[j], bok)):
            if ok.any():
                vals.append((int(c[ok].min()), int(c[ok].max())))
        lo = min((v[0] for v in vals), default=0)
        hi = max((v[1] for v in vals), default=0)
        lims.append((lo, max(1, int(hi - lo + 1).bit_length())))
    if sum(b for _, b in lims) > 62:
        return None
    def pack(cs):
        out = np.zeros(len(cs[0]) if cs else 0, np.int64)
        for c, (lo, bits) in zip(cs, lims):
            out = (out << bits) | (c - lo)
        return out
    return pack(pc), pok, pack(bc), bok


def _dict_join_counts(pk_rows, bk_rows):
    """Python-dict fallback for unpackable multi-column keys: returns
    (counts, lo, bidx_sorted-equivalent) compatible with the vectorized
    expansion below by synthesizing a sorted build order."""
    order = sorted(range(len(bk_rows)), key=lambda i: bk_rows[i])
    bsorted = [bk_rows[i] for i in order]
    import bisect
    lo = np.fromiter((bisect.bisect_left(bsorted, k) for k in pk_rows),
                     np.int64, len(pk_rows))
    hi = np.fromiter((bisect.bisect_right(bsorted, k) for k in pk_rows),
                     np.int64, len(pk_rows))
    return lo, hi, np.asarray(order, np.int64)


def _host_equi_join(parrs, pvalids, barrs, bvalids, pkeys, bkeys,
                    kind: str):
    """Partition-local join: sort the build keys once, range-probe with
    searchsorted, expand with repeats (the numpy rendition of the sorted
    probe the device kernels run). Handles duplicate build keys; NULL
    keys never match. Returns (arrays, valids) in probe+build column
    order (inner/left), probe order (semi/anti), or probe+mark (mark)."""
    n = len(parrs[0]) if parrs else 0
    packed = _packed_key(parrs, pvalids, barrs, bvalids, pkeys, bkeys)
    if packed is not None:
        pk, pok, bk, bok = packed
        bidx = np.nonzero(bok)[0]
        order = np.argsort(bk[bidx], kind="stable")
        bidx = bidx[order]
        bsorted = bk[bidx]
        lo = np.searchsorted(bsorted, pk, side="left")
        hi = np.searchsorted(bsorted, pk, side="right")
    else:
        pok = np.ones(n, np.bool_)
        bokn = len(barrs[0]) if barrs else 0
        bok = np.ones(bokn, np.bool_)
        for i in pkeys:
            pok &= np.asarray(pvalids[i], np.bool_)
        for i in bkeys:
            bok &= np.asarray(bvalids[i], np.bool_)
        pk_rows = [tuple(int(parrs[i][r]) for i in pkeys) if pok[r]
                   else None for r in range(n)]
        valid_b = np.nonzero(bok)[0]
        bk_rows = [tuple(int(barrs[i][r]) for i in bkeys)
                   for r in valid_b]
        pk_safe = [k if k is not None else ((1 << 62),) for k in pk_rows]
        lo, hi, order = _dict_join_counts(pk_safe, bk_rows)
        bidx = valid_b[order]
    counts = np.where(pok, hi - lo, 0)

    if kind in ("semi", "anti", "mark"):
        matched = counts > 0
        if kind == "mark":
            return (list(parrs) + [matched],
                    list(pvalids) + [np.ones(n, np.bool_)])
        keep = matched if kind == "semi" else ~matched
        return ([a[keep] for a in parrs], [v[keep] for v in pvalids])

    out_counts = counts if kind == "inner" else np.maximum(counts, 1)
    prow = np.repeat(np.arange(n), out_counts)
    within = np.arange(len(prow)) - np.repeat(
        np.cumsum(out_counts) - out_counts, out_counts)
    has_match = counts[prow] > 0
    bpos = lo[prow] + within
    if len(bidx):
        brow = bidx[np.clip(bpos, 0, len(bidx) - 1)]
    else:
        brow = np.zeros(len(prow), np.int64)
    arrays = [a[prow] for a in parrs]
    valids = [v[prow] for v in pvalids]
    for a, v in zip(barrs, bvalids):
        data = a[brow] if len(a) else np.zeros(len(prow), a.dtype)
        arrays.append(np.where(has_match, data,
                               np.zeros(1, a.dtype)[0]))
        vv = v[brow] if len(v) else np.zeros(len(prow), np.bool_)
        valids.append(np.asarray(vv & has_match, np.bool_))
    return arrays, valids


# --------------------------------------------------------------------------
# operator-level spill entry points (called from Executor.run's
# ExceededMemoryLimitError fallback)
# --------------------------------------------------------------------------

def _spill_site(fn):
    """Attribute every XLA compile triggered by a spill-tier re-run to a
    `spill:`-prefixed site in the central compile recorder — the
    partition-wise shapes differ from the resident kernels', so their
    compiles are a real (and otherwise invisible) cost of spilling."""
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        from .profiler import RECORDER
        with RECORDER.site_context("spill"):
            return fn(*args, **kwargs)
    return wrapped


@_spill_site
def spill_join(executor, node: L.JoinNode) -> Optional[Batch]:
    """Radix-partitioned host join for a JoinNode whose working set blew
    the pool. None = shape unsupported (caller re-raises the original
    memory error — a clean QUERY_EXCEEDED_MEMORY, never a crash)."""
    if node.kind not in ("inner", "left", "semi", "anti", "mark") or \
            node.null_aware or node.residual is not None:
        return None
    if not _integer_keys(node.left.output, node.left_keys) or \
            not _integer_keys(node.right.output, node.right_keys):
        return None
    parrs, pvalids = _side_to_host(executor, node.left)
    barrs, bvalids = _side_to_host(executor, node.right)
    total = _host_bytes(parrs, pvalids) + _host_bytes(barrs, bvalids)
    count = _pick_partitions(executor, total)
    pkeys_files = _spill_partitions(executor, "join-probe", parrs,
                                    pvalids, node.left_keys, count)
    bkeys_files = _spill_partitions(executor, "join-build", barrs,
                                    bvalids, node.right_keys, count)
    del parrs, pvalids, barrs, bvalids
    spiller = get_spiller(executor)
    out_arrays: List[list] = []
    out_valids: List[list] = []
    for pf, bf in zip(pkeys_files, bkeys_files):
        # partition-boundary cooperative cancel (terminate()/deadline)
        executor.check_cancel()
        pa, pv = spiller.get(pf)
        ba, bv = spiller.get(bf)
        arrs, vals = _host_equi_join(pa, pv, ba, bv, node.left_keys,
                                     node.right_keys, node.kind)
        if arrs and len(arrs[0]):
            out_arrays.append(arrs)
            out_valids.append(vals)
    executor.stats.spilled_joins += 1
    if not out_arrays:
        return _empty_output(node)
    ncols = len(out_arrays[0])
    arrs = [np.concatenate([p[j] for p in out_arrays])
            for j in range(ncols)]
    vals = [np.concatenate([p[j] for p in out_valids])
            for j in range(ncols)]
    return batch_from_numpy(arrs, valids=vals)


def _empty_output(node: L.JoinNode) -> Batch:
    arrs = [np.zeros(0, dtype=np.dtype(dt.np_dtype))
            for _, dt in node.output]
    return batch_from_numpy(arrs,
                            valids=[np.zeros(0, np.bool_) for _ in arrs])


@_spill_site
def spill_aggregate(executor, node: L.AggregateNode) -> Optional[Batch]:
    """Spillable aggregation, two strategies (the hash-vs-sort group-by
    study's trade-off, arXiv:2411.13245):

    - radix partitioning by group-key hash when the largest partition
      fits the pool: every group is wholly inside one partition and
      stable partitioning preserves row order within a group, so the
      result matches the resident kernel bit for bit;
    - chunk-and-merge partial states when the keys are too low-
      cardinality to partition (a 4-group GROUP BY hashes everything
      into 4 partitions): fixed-size row chunks aggregate to partial
      states that merge with sum/min/max — exact for integer/decimal
      accumulators, same ULP caveat as the chunked driver for floats.

    None = shape unsupported (caller fails cleanly)."""
    if not node.group_keys or \
            not _integer_keys(node.child.output, node.group_keys):
        return None
    from .chunked import MERGE_FUNC
    from ..ops.aggregate import AggSpec
    aggs = tuple(AggSpec(a.func,
                         a.arg.index if a.arg is not None else None,
                         a.distinct)
                 for a in node.aggs)
    mergeable = not any(a.distinct for a in node.aggs) and \
        all(a.func in MERGE_FUNC for a in node.aggs)
    arrs, vals = _side_to_host(executor, node.child)
    total = _host_bytes(arrs, vals)
    count = _pick_partitions(executor, total)
    n = len(arrs[0]) if arrs else 0
    row_bytes = max(1, total // max(1, n))
    part = _partition_ids(arrs, vals, node.group_keys, count)
    biggest = int(np.bincount(part, minlength=count).max()) if n else 0
    if biggest * row_bytes * 2 > executor.pool.limit:
        # skewed/low-cardinality keys: partitioning cannot shrink the
        # working set — chunk-and-merge instead (or give up cleanly)
        if not mergeable:
            return None
        return _chunked_partial_aggregate(executor, node, arrs, vals)
    files = _spill_partitions(executor, "agg", arrs, vals,
                              node.group_keys, count)
    del arrs, vals
    spiller = get_spiller(executor)
    outs: List[list] = []
    outs_v: List[list] = []
    from .memory import batch_bytes
    with executor.no_decisions():
        for f in files:
            # partition-boundary cooperative cancel
            executor.check_cancel()
            pa, pv = spiller.get(f)
            part = batch_from_numpy(pa, valids=pv)
            executor.pool.reserve(batch_bytes(part))
            try:
                out = executor.aggregate_batch(node, part, aggs)
                oa, ov = batch_to_numpy(out)
            finally:
                executor.pool.free(batch_bytes(part))
            if oa and len(oa[0]):
                outs.append(oa)
                outs_v.append(ov)
    executor.stats.spilled_aggregations += 1
    if not outs:
        arrs0 = [np.zeros(0, dtype=np.dtype(dt.np_dtype))
                 for _, dt in node.output]
        return batch_from_numpy(
            arrs0, valids=[np.zeros(0, np.bool_) for _ in arrs0])
    ncols = len(outs[0])
    arrs2 = [np.concatenate([p[j] for p in outs]) for j in range(ncols)]
    vals2 = [np.concatenate([p[j] for p in outs_v]) for j in range(ncols)]
    return batch_from_numpy(arrs2, valids=vals2)


@_spill_site
def spill_sort(executor, node: L.SortNode) -> Batch:
    """Host-side ORDER BY fallback: when the device sort's batch cannot
    fit the pool, sort the live rows on host with the same key
    semantics as the scheduler's n-way run merge (rank codes below a
    null-rank level, np.lexsort's stability preserving input order on
    ties) and apply the TopN limit before anything rematerializes."""
    arrs, vals = _side_to_host(executor, node.child)
    n = len(arrs[0]) if arrs else 0
    levels = []
    for k in reversed(node.keys):
        ok = np.asarray(vals[k.index], np.bool_)
        codes = np.unique(arrs[k.index],
                          return_inverse=True)[1].astype(np.int64)
        if not k.ascending:
            codes = -codes
        codes = np.where(ok, codes, 0)
        nr = np.where(ok, 1 if k.nulls_first else 0,
                      0 if k.nulls_first else 1).astype(np.int8)
        levels.append(codes)
        levels.append(nr)
    order = np.lexsort(levels) if levels else np.arange(n)
    if node.limit is not None:
        order = order[:node.limit]
    executor.stats.spilled_sorts += 1
    return batch_from_numpy([a[order] for a in arrs],
                            valids=[v[order] for v in vals])


def _chunked_partial_aggregate(executor, node: L.AggregateNode,
                               arrs, vals) -> Batch:
    """Bounded aggregation over host rows in fixed chunks: each chunk
    runs the node's own aggregation (its output IS the partial-state
    layout: keys, then mergeable states), chunk outputs spill through
    the host spiller, and merge_partial_pages re-aggregates them."""
    from ..ops.aggregate import AggSpec
    from .memory import batch_bytes
    aggs = tuple(AggSpec(a.func,
                         a.arg.index if a.arg is not None else None)
                 for a in node.aggs)
    n = len(arrs[0]) if arrs else 0
    total = _host_bytes(arrs, vals)
    row_bytes = max(1, total // max(1, n))
    # a third of the pool per chunk (input + kernel scratch + partial
    # output share it); the floor only guards against degenerate limits
    budget = max(64 << 10, executor.pool.limit // 3)
    chunk_rows = max(1024, budget // row_bytes)
    spiller = get_spiller(executor)
    keys = []
    with executor.no_decisions():
        for start in range(0, max(n, 1), chunk_rows):
            chunk = batch_from_numpy(
                [a[start:start + chunk_rows] for a in arrs],
                valids=[v[start:start + chunk_rows] for v in vals])
            executor.pool.reserve(batch_bytes(chunk))
            try:
                out = executor.aggregate_batch(node, chunk, aggs)
                oa, ov = batch_to_numpy(out)
            finally:
                executor.pool.free(batch_bytes(chunk))
            key = spiller.next_key("aggchunk")
            spiller.put(key, oa, ov)
            keys.append(key)
    pages = [spiller.get(k) for k in keys]
    executor.stats.spilled_aggregations += 1
    return merge_partial_pages(executor, node, pages)


# --------------------------------------------------------------------------
# spillable partial-aggregation state (exec/chunked.py's accumulator)
# --------------------------------------------------------------------------

class PartialState:
    """The chunked driver's partial-aggregate accumulator, made
    spillable (SpillableHashAggregationBuilder's role): device partials
    are revocable reservations; when the pool asks (or the watermark
    trips) they move to host pages, and the merge step re-aggregates
    either resident or partition-wise."""

    def __init__(self, executor, tag: str = "agg-partials"):
        import threading
        self.executor = executor
        self.tag = tag
        self.device: List[Batch] = []
        self._device_bytes: List[int] = []
        self.host: List[tuple] = []          # (arrays, valids)
        self.spilled_rounds = 0
        # revocation may fire from the ClusterMemoryManager's thread
        # while the chunk loop is appending — the lists move together
        self._lock = threading.Lock()
        self._handle = executor.pool.register_revocation(
            self._revoke, tag=tag)

    def add(self, batch: Batch) -> None:
        from .memory import batch_bytes
        b = batch_bytes(batch)
        self.executor.pool.reserve_revocable(b, tag=self.tag)
        with self._lock:
            self.device.append(batch)
            self._device_bytes.append(b)

    def _revoke(self, target_bytes: int) -> int:
        """Revocation callback: move device partials to host until the
        target is met (oldest first — they are coldest)."""
        freed = 0
        while freed < target_bytes:
            with self._lock:
                if not self.device:
                    break
                batch = self.device.pop(0)
                b = self._device_bytes.pop(0)
            self.host.append(batch_to_numpy(batch))
            self.executor.pool.free_revocable(b, tag=self.tag)
            freed += b
        if freed:
            self.spilled_rounds += 1
            self.executor.stats.spilled_aggregations += 1
        return freed

    def spill_all(self) -> int:
        return self._revoke(1 << 62)

    def close(self) -> None:
        # free whatever is still resident; drop the callback
        while True:
            with self._lock:
                if not self.device:
                    break
                self.device.pop()
                b = self._device_bytes.pop()
            self.executor.pool.free_revocable(b, tag=self.tag)
        self.executor.pool.unregister_revocation(self._handle)

    def merge(self, node: L.AggregateNode) -> Batch:
        """FINAL step over mixed device/host partials. All-resident
        partials keep the one-concat device merge; once anything
        spilled, everything merges through host (partition-wise when the
        concat would not fit the pool)."""
        from .chunked import merge_partials
        # drop the callback first so revocation cannot race the merge
        self.executor.pool.unregister_revocation(self._handle)
        with self._lock:
            device = list(self.device)
            host = list(self.host)
        try:
            if not host:
                return merge_partials(self.executor, node, device)
            pages = host + [batch_to_numpy(b) for b in device]
            return merge_partial_pages(self.executor, node, pages)
        finally:
            self.close()


def merge_partial_pages(executor, node: L.AggregateNode,
                        pages: List[tuple]) -> Batch:
    """Merge host partial-state pages. Fits-in-pool: one device merge.
    Otherwise: radix-partition the concatenated states by group key and
    merge each partition alone (states for one group always share a
    partition, so the merge is exact)."""
    from ..ops.aggregate import AggSpec, global_aggregate
    from .chunked import MERGE_FUNC
    from .memory import batch_bytes
    nonempty = [p for p in pages if p[0] and len(p[0][0])]
    if not pages:
        from .chunked import merge_partials
        return merge_partials(executor, node, [])   # raises like before
    # all-empty partials still carry dtypes: merge one zero-row page so
    # global aggregates emit their identity row exactly as the resident
    # merge does
    pages = nonempty if nonempty else pages[:1]
    ncols = len(pages[0][0])
    arrs = [np.concatenate([p[0][j] for p in pages])
            for j in range(ncols)]
    vals = [np.concatenate([p[1][j] for p in pages])
            for j in range(ncols)]
    n_keys = len(node.group_keys)
    merge_aggs = tuple(AggSpec(MERGE_FUNC[a.func], n_keys + j)
                       for j, a in enumerate(node.aggs))
    if node.strategy == "global" or not n_keys:
        merged = batch_from_numpy(arrs, valids=vals)
        return global_aggregate(merged, merge_aggs)
    total = _host_bytes(arrs, vals)
    # 3x: input + sort scratch + output headroom for the device merge
    # (hash-strategy operators merge through the hash-partial path)
    if executor.pool.available() >= 3 * total:
        merged = batch_from_numpy(arrs, valids=vals)
        capacity = max(node.out_capacity, bucket_capacity(len(arrs[0])))
        return executor.merge_group_aggregate(node, merged, merge_aggs,
                                              capacity)
    count = _pick_partitions(executor, total)
    part = _partition_ids(arrs, vals, tuple(range(n_keys)), count)
    outs, outs_v = [], []
    for p in range(count):
        m = part == p
        if not m.any():
            continue
        pb = batch_from_numpy([a[m] for a in arrs],
                              valids=[v[m] for v in vals])
        executor.pool.reserve(batch_bytes(pb))
        try:
            out = executor.merge_group_aggregate(
                node, pb, merge_aggs, bucket_capacity(int(m.sum())))
            oa, ov = batch_to_numpy(out)
        finally:
            executor.pool.free(batch_bytes(pb))
        if oa and len(oa[0]):
            outs.append(oa)
            outs_v.append(ov)
    executor.stats.spilled_aggregations += 1
    ncols2 = len(outs[0])
    return batch_from_numpy(
        [np.concatenate([p[j] for p in outs]) for j in range(ncols2)],
        valids=[np.concatenate([p[j] for p in outs_v])
                for j in range(ncols2)])
