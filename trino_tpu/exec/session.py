"""Session: the user-facing entry — SQL text in, rows out.

Reference: the coordinator path DispatchManager.createQuery ->
SqlQueryExecution (dispatcher/DispatchManager.java:175,
execution/SqlQueryExecution.java:392) collapsed to its single-node essence:
parse -> plan -> execute -> decode. The distributed scheduler wraps this in
parallel/; the HTTP protocol front end in client/ builds on Session too.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from ..batch import decode_column, Field
from ..catalog import Catalog, default_catalog
from ..planner.logical import OutputNode, explain_text
from ..planner.optimizer import prune_plan
from ..planner.planner import Planner
from ..sql import ast_nodes as A
from ..sql.parser import parse
from ..types import TypeKind
from .executor import Executor


@dataclass
class QueryResult:
    column_names: List[str]
    rows: List[tuple]
    elapsed_s: float = 0.0
    stats: Optional[object] = None

    def __iter__(self):
        return iter(self.rows)

    def __len__(self):
        return len(self.rows)


class Session:
    def __init__(self, catalog: Optional[Catalog] = None,
                 default_cat: str = "tpch", default_schema: str = "tiny"):
        self.catalog = catalog or default_catalog()
        self.default_cat = default_cat
        self.default_schema = default_schema
        self.executor = Executor(self.catalog)

    def planner(self) -> Planner:
        return Planner(self.catalog, self.default_cat, self.default_schema)

    def plan(self, sql: str):
        stmt = parse(sql)
        if isinstance(stmt, A.Explain):
            return stmt, None
        assert isinstance(stmt, (A.Query, A.SetOp, A.Values, A.ShowTables))
        if isinstance(stmt, A.ShowTables):
            return stmt, None
        rel = self.planner().plan_query(stmt)
        return stmt, rel

    def execute(self, sql: str) -> QueryResult:
        t0 = time.monotonic()
        stmt = parse(sql)

        if isinstance(stmt, A.ShowTables):
            cat = stmt.catalog or self.default_cat
            sch = stmt.schema or self.default_schema
            names = self.catalog.connector(cat).table_names(sch)
            return QueryResult(["table"], [(n,) for n in names],
                               time.monotonic() - t0)

        if isinstance(stmt, A.Explain):
            rel = self.planner().plan_query(stmt.query)
            text = explain_text(prune_plan(rel.node))
            return QueryResult(["query plan"],
                               [(line,) for line in text.split("\n")],
                               time.monotonic() - t0)

        rel = self.planner().plan_query(stmt)
        root = rel.node
        assert isinstance(root, OutputNode)
        root = prune_plan(root)
        batch = self.executor.execute(root)
        names, arrays, valids = self.executor.result_to_host(root, batch)
        rows = self.decode_rows(rel, arrays, valids)
        return QueryResult(names, rows, time.monotonic() - t0,
                           self.executor.stats)

    def decode_rows(self, rel, arrays, valids) -> List[tuple]:
        cols = []
        for sc, arr, val in zip(rel.scope.columns, arrays, valids):
            fld = sc.field if sc.field is not None else Field(
                sc.name, sc.dtype)
            if sc.dtype.kind is TypeKind.VARCHAR and \
                    (fld.dictionary is None):
                raise RuntimeError(
                    f"varchar output {sc.name} lost its dictionary")
            cols.append(decode_column(fld, arr, val))
        return list(zip(*cols)) if cols else []
