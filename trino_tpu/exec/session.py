"""Session: the user-facing entry — SQL text in, rows out.

Reference: the coordinator path DispatchManager.createQuery ->
SqlQueryExecution (dispatcher/DispatchManager.java:175,
execution/SqlQueryExecution.java:392) collapsed to its single-node essence:
parse -> plan -> execute -> decode. The distributed scheduler wraps this in
parallel/; the HTTP protocol front end in client/ builds on Session too.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from ..batch import decode_column, Field
from ..catalog import Catalog, default_catalog
from ..planner.logical import OutputNode, explain_text
from ..planner.optimizer import prune_plan
from ..planner.planner import Planner
from ..sql import ast_nodes as A
from ..sql.parser import parse
from ..types import TypeKind
from .executor import Executor


@dataclass
class QueryResult:
    column_names: List[str]
    rows: List[tuple]
    elapsed_s: float = 0.0
    stats: Optional[object] = None

    def __iter__(self):
        return iter(self.rows)

    def __len__(self):
        return len(self.rows)


# session properties (SystemSessionProperties.java:61's role); each entry:
# name -> (default, parser)
def _bool(v):
    return str(v).lower() in ("true", "1")


def _default_query_max_memory_mb() -> int:
    """TRINO_TPU_QUERY_MAX_MEMORY (bytes, B/kB/MB/GB suffixes) overrides
    the 64 GiB per-query default for every session in the process."""
    import os
    env = os.environ.get("TRINO_TPU_QUERY_MAX_MEMORY")
    if env:
        from .memory import parse_bytes
        return max(1, parse_bytes(env) >> 20)
    return 64 << 10


SESSION_PROPERTY_DEFAULTS = {
    "distributed": (False, _bool),
    "query_max_rows": (10_000_000, int),
    # per-query memory limit (memory/MemoryPool reserve path)
    "query_max_memory_mb": (_default_query_max_memory_mb(), int),
    # bounded-memory aggregation chunk size, 0 = off (spill analog)
    "spill_chunk_rows": (0, int),
    # host-spill survival chain (exec/spill.py): joins/aggregations whose
    # working set exceeds the pool retry partition-wise through host
    # RAM/disk instead of failing
    "spill_enabled": (True, _bool),
    "spill_partitions": (8, int),
    # Pallas MXU one-pass aggregation kernel (ops/pallas_agg.py): auto
    # picks it in its measured win region (direct aggregates with
    # G >= Executor.MXU_AGG_MIN_GROUPS on TPU); true/false force
    "mxu_agg": ("auto", lambda v: str(v).lower()),
    # Pallas tiled-gather probe kernel (ops/pallas_gather.py): auto =
    # on for TPU backends; true forces it (interpret mode on CPU, the
    # tier-1 test path); false = jnp.take everywhere
    "enable_pallas_gather": ("auto", lambda v: str(v).lower()),
    # Pallas VMEM hash-table kernel (ops/pallas_hash.py): hash
    # aggregation + hybrid hash join; same auto/true/false contract as
    # the tiled gather (true = interpret mode on CPU, the tier-1 path)
    "enable_pallas_hash": ("auto", lambda v: str(v).lower()),
    # hash-agg table size in slots (0 = size from the group estimate;
    # tests pin it small to exercise the overflow->partition escape)
    "hash_table_slots": (0, int),
    # fused multiway star join (ops/pallas_hash.multiway_probe): the
    # planner's star detector emits MultiJoinNode and the executor
    # probes every dimension table in one Pallas pass; same
    # auto/true/false contract as the other Pallas kernels (true =
    # interpret mode on CPU, the tier-1 path)
    "enable_multiway_join": ("auto", lambda v: str(v).lower()),
    # star-detector cap on fused dimensions per MultiJoinNode
    "multiway_max_dims": (5, int),
    # resident-table VMEM budget for the fused pass, in KiB; dims are
    # shed largest-first to the pairwise ladder until the stack fits
    # (tests pin it tiny to prove the overflow degrade bit-exact)
    "multiway_vmem_kb": (8192, int),
    # planner hash-vs-sort gate: auto applies the rows-per-group rule,
    # force always picks hash for grouped aggregates, off never does
    "hash_agg_mode": ("auto", lambda v: str(v).lower()),
    # auto mode thresholds: hash needs at least this many estimated
    # groups AND at most this many estimated rows per group
    "hash_agg_min_groups": (8192, int),
    "hash_agg_max_rows_per_group": (64, int),
    # dense 'direct' aggregation bound (GroupByHash strategy choice);
    # capped by the kernel's compile-bound MAX_DIRECT_GROUPS
    "direct_agg_max_groups": (64, int),
    # join distribution (SystemSessionProperties JOIN_DISTRIBUTION_TYPE):
    # AUTO picks by estimated build bytes against the threshold
    "join_distribution_type": ("auto", lambda v: str(v).lower()),
    "broadcast_join_threshold_mb": (32, int),
    # wall-clock budget; exceeded -> QueryDeadlineError (QUERY_MAX_RUN_TIME)
    "query_max_run_time_s": (0.0, float),
    # admission-queue budget (query.max-queued-time's role): a query
    # still QUEUED past this is rejected with a retryable
    # QUERY_EXCEEDED_QUEUED_TIME instead of waiting forever (0 = off)
    "query_max_queued_time_s": (0.0, float),
    # build-side min/max pruning of probe scans (ENABLE_DYNAMIC_FILTERING)
    "dynamic_filtering": (True, _bool),
    # escape hatch for the batched mesh filter collectives; the old
    # mid-execution rendezvous deadlock (q77) is gone by construction,
    # this only exists to isolate regressions
    "mesh_dynamic_filtering": (True, _bool),
    # gather-free sort-merge unique join at small shapes (compile-cost
    # gated regardless; this disables it outright)
    "merge_join": (True, _bool),
    # device bytes the scan cache may pin before LRU eviction
    "scan_cache_max_mb": (24 << 10, int),
    # zone-map scan pruning (exec/zonemap.py): skip decoding row ranges
    # the pushed-down predicate provably cannot match. Conservative-only;
    # the residual filter always re-runs, so off is bit-exact with on
    "enable_zone_map_pruning": (True, _bool),
    # zone granularity in rows (split-level pruning quantum)
    "zone_map_rows": (65536, int),
    # chunked-driver prefetch pipeline: how many decoded+staged chunks
    # may run ahead of the device (0 = today's serial loop, exactly)
    "prefetch_depth": (2, int),
    # chunked-driver compile warm: overlap the fused program's XLA compile
    # with chunk-0 decode via a discarded zero-row call (exec/prewarm.py
    # turns this on cluster-wide when TRINO_TPU_PREWARM is set)
    "prewarm_chunks": (False, _bool),
    # distributed runtime knobs (execution/scheduler tier)
    "split_rows": (250_000, int),
    "task_retries": (2, int),
    # distributed write fan-out (0 = one write task per active worker)
    "write_partitions": (0, int),
    # straggler hedging: a task past max(hedge_min_s, hedge_multiplier *
    # median drain time of its round) is speculatively re-dispatched to
    # a survivor; first success wins. multiplier <= 0 disables.
    "hedge_multiplier": (4.0, float),
    "hedge_min_s": (2.0, float),
    # per-query retry/hedge amplification cap: extra task attempts past
    # this fail the query (retries) or are declined (hedges) instead of
    # multiplying load on a struggling cluster
    "task_amplification_budget": (16, int),
    # control-plane retry backoff (server/retrypolicy.py: exponential +
    # decorrelated jitter) between task-retry rounds
    "retry_backoff_base_s": (0.05, float),
    "retry_backoff_max_s": (2.0, float),
    # error instead of silent local fallback when the cluster declines a
    # query (the round-4 verdict's "silently local" complaint)
    "require_distributed": (False, _bool),
    # build sides estimated above this stream chunk-wise through the
    # dense LUT with host-side payload gathers (spill tier v2; 0 = off)
    "stream_build_min_kb": (0, int),
    # distributed tracing (utils/tracing.py): when on, every query runs
    # under a propagating tracer — coordinator + worker spans stitch into
    # one trace served at GET /v1/query/{id}/trace
    "enable_tracing": (False, _bool),
    # device-time profiling (exec/profiler.py): fence every operator
    # dispatch with block_until_ready, splitting per-operator wall into
    # device/host/compile components in ExecStats / operator metrics /
    # EXPLAIN ANALYZE. Costs a device sync per plan node — forced
    # automatically during (distributed) EXPLAIN ANALYZE
    "enable_profiling": (False, _bool),
    # --- high-concurrency serving layer (server/serving.py) ---
    # logical-plan cache keyed by the normalized-SQL plan fingerprint:
    # repeated statements skip parse/plan entirely
    "enable_plan_cache": (True, _bool),
    # coordinator result cache for FINISHED pages (catalog-version
    # invalidated; volatile/system scans never cache). Opt-in: cached
    # pages skip execution, which fault-injection/chaos runs must see
    "enable_result_cache": (False, _bool),
    # micro-batching: concurrent same-shape point queries coalesce into
    # one dispatch behind a short gather window
    "enable_microbatch": (False, _bool),
    "microbatch_window_ms": (4.0, float),
    # cost-based CPU/TPU co-routing (exec/router.py): auto routes by
    # history baseline + scan-row estimates; host/device force a target
    "routing_mode": ("auto", lambda v: str(v).lower()),
    # auto mode: plans scanning at most this many estimated rows run on
    # the host numpy path (no device dispatch, no exec lock)
    "router_host_max_rows": (200_000, int),
    # auto mode: fingerprints whose history median latency is under this
    # run on the host regardless of the row estimate
    "router_host_latency_ms": (30.0, float),
}


class Session:
    def __init__(self, catalog: Optional[Catalog] = None,
                 default_cat: str = "tpch", default_schema: str = "tiny"):
        self.catalog = catalog or default_catalog()
        self.default_cat = default_cat
        self.default_schema = default_schema
        self.executor = Executor(self.catalog)
        self.properties = {k: v for k, (v, _) in
                           SESSION_PROPERTY_DEFAULTS.items()}
        from ..utils.tracing import NOOP
        self.tracer = NOOP          # swap for utils.tracing.Tracer()

    def planner(self) -> Planner:
        return Planner(self.catalog, self.default_cat, self.default_schema,
                       properties=self.properties)

    def plan(self, sql: str):
        stmt = parse(sql)
        if isinstance(stmt, (A.Query, A.SetOp, A.Values)):
            return stmt, self.planner().plan_query(stmt)
        return stmt, None

    def execute(self, sql: str) -> QueryResult:
        t0 = time.monotonic()
        stmt = parse(sql)

        if isinstance(stmt, (A.Query, A.SetOp, A.Values)):
            return self.execute_query(stmt, t0)
        if isinstance(stmt, A.Explain):
            return self.execute_explain(stmt, t0)
        if isinstance(stmt, (A.ShowTables, A.ShowCatalogs, A.ShowSchemas,
                             A.ShowSession, A.ShowColumns)):
            return self.execute_show(stmt, t0)
        if isinstance(stmt, A.SetSession):
            return self.execute_set_session(stmt, t0)
        if isinstance(stmt, (A.Update, A.Delete, A.MergeInto)):
            return self.execute_dml(stmt, t0)
        if isinstance(stmt, (A.CreateTable, A.DropTable, A.InsertInto)):
            return self.execute_ddl(stmt, t0)
        raise NotImplementedError(type(stmt).__name__)

    def _apply_executor_properties(self, t0: float) -> None:
        """Push session properties into the executor for this query
        (SystemSessionProperties -> TaskContext wiring, collapsed)."""
        ex = self.executor
        ex.pool.set_limit(self.properties["query_max_memory_mb"] << 20)
        ex.enable_spill = self.properties["spill_enabled"]
        ex.spill_partitions = self.properties["spill_partitions"]
        ex.enable_dynamic_filtering = self.properties["dynamic_filtering"]
        ex.mesh_dynamic_filtering = \
            self.properties["mesh_dynamic_filtering"]
        ex.enable_merge_join = self.properties["merge_join"]
        ex.scan_cache_max_bytes = \
            self.properties["scan_cache_max_mb"] << 20
        ex.enable_zone_map_pruning = \
            self.properties["enable_zone_map_pruning"]
        ex.zone_map_rows = max(1, self.properties["zone_map_rows"])
        ex.prefetch_depth = max(0, self.properties["prefetch_depth"])
        ex.prewarm_chunks = self.properties["prewarm_chunks"]
        max_s = self.properties["query_max_run_time_s"]
        ex.deadline = (t0 + max_s) if max_s else None
        kb = self.properties["stream_build_min_kb"]
        ex.stream_build_bytes = (kb << 10) if kb else None
        ex.enable_pallas_gather = self.properties["enable_pallas_gather"]
        ex.enable_pallas_hash = self.properties["enable_pallas_hash"]
        ex.hash_table_slots = self.properties["hash_table_slots"]
        ex.enable_multiway_join = self.properties["enable_multiway_join"]
        ex.multiway_max_dims = max(2, self.properties["multiway_max_dims"])
        ex.multiway_vmem_kb = max(1, self.properties["multiway_vmem_kb"])
        ex.enable_mxu_agg = self.properties["mxu_agg"]
        ex.profile = self.properties["enable_profiling"]
        if ex.profile:
            ex.node_stats = {}       # per-query attribution

    def execute_query(self, stmt, t0) -> QueryResult:
        # spans mirror the reference's: planner / fragment-plan / execute
        # (SqlQueryExecution.java:473,501)
        with self.tracer.span("plan"):
            rel = self.planner().plan_query(stmt)
        root = rel.node
        assert isinstance(root, OutputNode)
        with self.tracer.span("optimize"):
            root = prune_plan(root)
        return self.execute_planned(rel, root, t0)

    def execute_planned(self, rel, root, t0) -> QueryResult:
        """Execute an already planned + pruned query — the plan-cache
        re-entry point (server/serving.py): cached statements skip
        parse/plan and land here directly."""
        self._apply_executor_properties(t0)
        with self.tracer.span("execute") as sp:
            batch = self.executor.execute(root)
            names, arrays, valids = self.executor.result_to_host(root,
                                                                 batch)
            if sp is not None and self.executor.profile:
                ns = [v for v in self.executor.node_stats.values()
                      if len(v) >= 5]
                sp.attributes["profiled"] = True
                sp.attributes["deviceMs"] = round(
                    sum(v[2] for v in ns) * 1000, 3)
                sp.attributes["hostMs"] = round(
                    sum(v[3] for v in ns) * 1000, 3)
                sp.attributes["compileMs"] = round(
                    sum(v[4] for v in ns) * 1000, 3)
        with self.tracer.span("decode", rows=len(arrays[0])
                              if arrays else 0):
            rows = self.decode_rows(rel, arrays, valids)
        self.executor.flush_metrics()
        return QueryResult(names, rows, time.monotonic() - t0,
                           self.executor.stats)

    def execute_explain(self, stmt: A.Explain, t0) -> QueryResult:
        planner = self.planner()
        # EXPLAIN over a write statement plans its source query and
        # renders it under TableCommit/TableWriter wrapper nodes (the
        # reference's TableFinishNode over TableWriterNode)
        wstmt = None
        query = stmt.query
        if isinstance(query, (A.InsertInto, A.CreateTable)):
            if getattr(query, "query", None) is None:
                raise ValueError("EXPLAIN of CREATE TABLE without AS "
                                 "SELECT is not supported")
            wstmt = query
            query = query.query
        rel = planner.plan_query(query)
        root = prune_plan(rel.node)

        def estimate(node) -> str:
            """Cost-model annotations (EXPLAIN shows estimates —
            cost/PlanNodeStatsEstimate rendering)."""
            try:
                est = planner.estimate_rows(node)
            except Exception:
                return ""
            extra = ""
            from ..planner.logical import JoinNode
            if isinstance(node, JoinNode) and \
                    node.distribution != "auto":
                extra = f", {node.distribution.upper()}"
            return f"{{rows: {est:,.0f}{extra}}}"

        annotate = estimate
        # apply session properties the same way execute_query would:
        # ANALYZE really executes, and even the plain-EXPLAIN strategy
        # predictions below read executor knobs that must reflect
        # SET SESSION (zone_map_rows, enable_multiway_join, ...)
        self._apply_executor_properties(t0)
        if stmt.analyze and wstmt is not None:
            # ANALYZE of a write really writes (local staged path); the
            # plan stays estimate-annotated — the single commit is the
            # interesting line, not per-operator device times
            wres = self.execute_ddl(wstmt, t0)
            written = wres.rows[0][0] if wres.rows else 0
            text = explain_text(root, annotate=annotate)
            cat, sch, tbl = self.resolve_table(wstmt.table)
            rows = [(f"TableCommit[{cat}.{sch}.{tbl}]",),
                    (f"  TableWriter[{cat}.{sch}.{tbl}]",)]
            rows += [(f"    {line}",) for line in text.split("\n")]
            rows.append((f"write: 1 partitions, 1 staged, 0 deduped, "
                         f"{written} rows",))
            return QueryResult(["query plan"], rows,
                               time.monotonic() - t0)
        if stmt.analyze:
            saved = self.executor.profile
            self.executor.profile = True
            self.executor.node_stats = {}
            try:
                self.executor.execute(root)
            finally:
                self.executor.profile = saved
            stats = self.executor.node_stats

            def annotate(node):
                s = stats.get(id(node))
                est = estimate(node)
                if s is None:
                    return est
                if len(s) >= 5:
                    # fenced profiling splits the wall into components
                    # (device + host + compile sum to wall exactly)
                    return (f"[{s[0] * 1000:.2f}ms (device "
                            f"{s[2] * 1000:.2f} + host {s[3] * 1000:.2f}"
                            f" + compile {s[4] * 1000:.2f}), "
                            f"{s[1]} rows] {est}")
                return f"[{s[0] * 1000:.2f}ms, {s[1]} rows] {est}"
        text = explain_text(root, annotate=annotate)
        rows = [(line,) for line in text.split("\n")]
        if wstmt is not None:
            cat, sch, tbl = self.resolve_table(wstmt.table)
            rows = [(f"TableCommit[{cat}.{sch}.{tbl}]",),
                    (f"  TableWriter[{cat}.{sch}.{tbl}]",)] + \
                [(f"    {r[0]}",) for r in rows]
        # per-operator strategy verdicts (the aggregation/join gate's
        # choice; after ANALYZE the executed strategy is authoritative)
        try:
            from .executor import explain_strategy_lines
            # walk the pre-prune plan: column pruning interleaves
            # ProjectNodes into join spines, which would hide the
            # multiway-star verdict; every field the predictions read
            # (strategy, build_unique, key domains) survives pruning
            for line in explain_strategy_lines(rel.node, self.executor):
                rows.append((line,))
        except Exception:    # noqa: BLE001 — EXPLAIN must never fail
            pass             # on a strategy estimate
        # scan-path verdicts after ANALYZE: how many zones/chunks each
        # table scan pruned against its pushed-down predicate
        if stmt.analyze:
            for op, dec in sorted(self.executor.strategy_decisions.items()):
                if not op.startswith("TableScan["):
                    continue
                kind, _, frac = dec.partition(":")
                pruned, _, total = frac.partition("/")
                unit = "zones" if kind == "zone-pruned" else "chunks"
                rows.append((f"scan {op[10:-1]}: {total} {unit}, "
                             f"{pruned} pruned by zone maps",))
        # CPU/TPU co-routing verdict (exec/router.py): what the serving
        # layer would do with this plan, and why
        try:
            from .router import decide_route
            dec = decide_route(planner, root, self.properties,
                               history=getattr(self, "history_store",
                                               None))
            rows.append((f"routing: {dec.target} ({dec.reason})",))
        except Exception:    # noqa: BLE001 — EXPLAIN must never fail on
            pass             # a router estimate
        return QueryResult(["query plan"], rows,
                           time.monotonic() - t0)

    def execute_show(self, stmt, t0) -> QueryResult:
        el = time.monotonic() - t0
        if isinstance(stmt, A.ShowTables):
            cat = stmt.catalog or self.default_cat
            sch = stmt.schema or self.default_schema
            names = self.catalog.connector(cat).table_names(sch)
            return QueryResult(["table"], [(n,) for n in names], el)
        if isinstance(stmt, A.ShowCatalogs):
            return QueryResult(
                ["catalog"],
                [(n,) for n in sorted(self.catalog._connectors)], el)
        if isinstance(stmt, A.ShowSchemas):
            cat = stmt.catalog or self.default_cat
            names = self.catalog.connector(cat).schema_names()
            return QueryResult(["schema"], [(n,) for n in names], el)
        if isinstance(stmt, A.ShowSession):
            rows = [(k, str(self.properties[k]),
                     str(SESSION_PROPERTY_DEFAULTS[k][0]))
                    for k in sorted(self.properties)]
            return QueryResult(["name", "value", "default"], rows, el)
        # SHOW COLUMNS / DESCRIBE
        cat, sch, tbl = self.resolve_table(stmt.table)
        data = self.catalog.get_table(cat, sch, tbl)
        rows = [(f.name, str(f.dtype)) for f in data.schema]
        return QueryResult(["column", "type"], rows, el)

    def execute_set_session(self, stmt: A.SetSession, t0) -> QueryResult:
        if stmt.name not in SESSION_PROPERTY_DEFAULTS:
            raise KeyError(f"unknown session property {stmt.name!r}")
        _, parser = SESSION_PROPERTY_DEFAULTS[stmt.name]
        raw = getattr(stmt.value, "value", getattr(stmt.value, "text",
                                                   None))
        if raw is None and hasattr(stmt.value, "parts"):
            # bare-identifier value (SET SESSION routing_mode = device):
            # same spelling as the quoted form
            raw = ".".join(stmt.value.parts)
        self.properties[stmt.name] = parser(raw)
        if stmt.name == "distributed":
            self.set_distributed(self.properties["distributed"])
        elif stmt.name == "query_max_memory_mb":
            # in-place limit change: replacing the pool object would leak
            # the cached builds' revocable ledger
            self.executor.pool.set_limit(self.properties[stmt.name] << 20)
        elif stmt.name == "spill_chunk_rows":
            self.executor.spill_chunk_rows = \
                self.properties[stmt.name] or None
        elif stmt.name == "mxu_agg":
            self.executor.enable_mxu_agg = self.properties[stmt.name]
        elif stmt.name == "enable_pallas_gather":
            self.executor.enable_pallas_gather = \
                self.properties[stmt.name]
        elif stmt.name == "enable_tracing":
            from ..utils.tracing import NOOP, Tracer
            self.tracer = Tracer() if self.properties[stmt.name] else NOOP
        return QueryResult(["result"], [("SET SESSION",)],
                           time.monotonic() - t0)

    def set_distributed(self, on: bool) -> None:
        """Swap the executor (single-device vs mesh GSPMD)."""
        if on:
            from ..parallel.dist_executor import MeshExecutor
            if not isinstance(self.executor, MeshExecutor):
                self.executor = MeshExecutor(self.catalog)
        elif type(self.executor) is not Executor:
            self.executor = Executor(self.catalog)

    def resolve_table(self, parts):
        parts = tuple(p.lower() for p in parts)
        if len(parts) == 3:
            return parts
        if len(parts) == 2:
            return self.default_cat, parts[0], parts[1]
        return self.default_cat, self.default_schema, parts[0]

    def execute_ddl(self, stmt, t0) -> QueryResult:
        from ..connectors.tpch.datagen import TableData
        import numpy as np
        from ..batch import Field, Schema
        from ..planner.analyzer import parse_type

        if isinstance(stmt, A.DropTable):
            cat, sch, tbl = self.resolve_table(stmt.table)
            self.catalog.connector(cat).drop_table(sch, tbl,
                                                   stmt.if_exists)
            self.catalog.bump_version()
            self.executor = type(self.executor)(self.catalog)
            return QueryResult(["result"], [("DROP TABLE",)],
                               time.monotonic() - t0)

        if isinstance(stmt, A.CreateTable):
            cat, sch, tbl = self.resolve_table(stmt.table)
            conn = self.catalog.connector(cat)
            if stmt.query is not None:     # CTAS
                fields, arrays, valids = self.query_to_columns(stmt.query)
                data = TableData(tbl, Schema(tuple(fields)), arrays,
                                 valids=valids)
                conn.create_table(sch, tbl, data, stmt.if_not_exists)
                self.catalog.bump_version()
                n = data.num_rows
                return QueryResult(["rows"], [(n,)],
                                   time.monotonic() - t0)
            fields = [Field(name, parse_type(tn))
                      for name, tn in stmt.columns]
            arrays = [np.zeros(0, dtype=f.dtype.np_dtype) for f in fields]
            fields = [Field(f.name, f.dtype, dictionary=()
                            if f.dtype.kind is TypeKind.VARCHAR else None)
                      for f in fields]
            conn.create_table(sch, tbl,
                              TableData(tbl, Schema(tuple(fields)),
                                        arrays),
                              stmt.if_not_exists)
            self.catalog.bump_version()
            return QueryResult(["result"], [("CREATE TABLE",)],
                               time.monotonic() - t0)

        # INSERT INTO
        cat, sch, tbl = self.resolve_table(stmt.table)
        fields, arrays, valids = self.query_to_columns(stmt.query)
        n = self.catalog.connector(cat).insert(sch, tbl, arrays, valids,
                                               fields)
        # stored table changed: refresh any cached scans
        self.catalog.bump_version()
        self.executor.invalidate_scan_cache()
        return QueryResult(["rows"], [(n,)], time.monotonic() - t0)

    # ---- UPDATE / DELETE / MERGE (row-id + delete-mask scheme) ----------

    def _register_shadow(self, conn, sch: str, tbl: str) -> str:
        """Copy of the target with a hidden $rowid column, registered
        under a reserved name — mutations are planned as ordinary queries
        over it (reference: the merge row-change paradigm routes rows by
        target row id, MergeWriterOperator.java)."""
        import numpy as np
        from ..batch import Field, Schema
        from ..connectors.tpch.datagen import TableData
        from ..types import BIGINT
        t = conn.get_table(sch, tbl)
        cols = list(t.columns) + [np.arange(t.num_rows, dtype=np.int64)]
        valids = None if t.valids is None else list(t.valids) + [None]
        fields = tuple(t.schema.fields) + (Field("$rowid", BIGINT),)
        shadow = f"{tbl}$dml"
        conn.drop_table(sch, shadow, if_exists=True)
        conn.create_table(sch, shadow,
                          TableData(tbl, Schema(fields), cols,
                                    valids=valids))
        return shadow

    def _dml_conn(self, cat: str):
        conn = self.catalog.connector(cat)
        if not hasattr(conn, "delete_rows"):
            from ..planner.analyzer import AnalysisError
            raise AnalysisError(
                f"connector {cat!r} does not support row-level DML")
        return conn

    @staticmethod
    def _sql_type_name(dt) -> str:
        if dt.kind is TypeKind.DECIMAL:
            return f"decimal({dt.precision},{dt.scale})"
        return dt.kind.value

    def _coerced_assignments(self, conn, sch, tbl, assignments):
        """Validate assignment targets and wrap each value in a cast to
        the column's declared type — the stored representation must be
        the target column's, not the expression's (e.g. a scale-1
        decimal literal written to a decimal(10,2) column)."""
        from ..planner.analyzer import AnalysisError
        schema = conn.get_table(sch, tbl).schema
        names = {f.name for f in schema.fields}
        out = []
        for col, expr in assignments:
            if col not in names:
                raise AnalysisError(
                    f"UPDATE target column {col!r} does not exist")
            dt = schema.field(col).dtype
            if dt.kind is not TypeKind.VARCHAR:
                expr = A.CastExpr(expr, self._sql_type_name(dt))
            out.append((col, expr))
        return out

    def execute_dml(self, stmt, t0) -> QueryResult:
        import numpy as np
        from ..planner.analyzer import AnalysisError
        if isinstance(stmt, A.MergeInto):
            return self.execute_merge(stmt, t0)
        cat, sch, tbl = self.resolve_table(stmt.table)
        conn = self._dml_conn(cat)
        assignments = self._coerced_assignments(
            conn, sch, tbl, stmt.assignments) \
            if isinstance(stmt, A.Update) else ()
        shadow = self._register_shadow(conn, sch, tbl)
        try:
            items = [A.SelectItem(A.Identifier(("$rowid",)), "$rowid")]
            if isinstance(stmt, A.Update):
                for j, (_, expr) in enumerate(assignments):
                    items.append(A.SelectItem(expr, f"$v{j}"))
            q = A.Query(select=tuple(items), distinct=False,
                        relation=A.TableRef((cat, sch, shadow),
                                            alias=tbl),
                        where=stmt.where, group_by=(), having=None,
                        order_by=(), limit=None)
            fields, arrays, valids = self.query_to_columns(q)
            ids = np.asarray(arrays[0], dtype=np.int64)
            if isinstance(stmt, A.Delete):
                n = conn.delete_rows(sch, tbl, ids)
            else:
                updates = {col: (arrays[1 + j], valids[1 + j],
                                 fields[1 + j])
                           for j, (col, _) in enumerate(assignments)}
                n = conn.update_rows(sch, tbl, ids, updates)
        finally:
            conn.drop_table(sch, shadow, if_exists=True)
        self.catalog.bump_version()
        self.executor.invalidate_scan_cache()
        return QueryResult(["rows"], [(n,)], time.monotonic() - t0)

    def execute_merge(self, stmt: "A.MergeInto", t0) -> QueryResult:
        """MERGE: matched rows route to UPDATE/DELETE, unmatched source
        rows to INSERT — both decided against the pre-merge table state
        (the reference's RowChangeProcessor semantics). Supported shape:
        at most one WHEN MATCHED and one WHEN NOT MATCHED clause."""
        import numpy as np
        from ..planner.analyzer import AnalysisError
        cat, sch, tbl = self.resolve_table(stmt.target)
        conn = self._dml_conn(cat)
        alias = stmt.target_alias or tbl
        matched = [c for c in stmt.clauses if c.matched]
        unmatched = [c for c in stmt.clauses if not c.matched]
        if len(matched) > 1 or len(unmatched) > 1:
            raise AnalysisError(
                "MERGE supports one WHEN MATCHED and one "
                "WHEN NOT MATCHED clause")
        if unmatched and unmatched[0].action != "insert":
            raise AnalysisError("WHEN NOT MATCHED requires INSERT")
        shadow = self._register_shadow(conn, sch, tbl)
        n = 0
        try:
            tref = A.TableRef((cat, sch, shadow), alias=alias)
            if matched:
                mc = matched[0]
                massign = self._coerced_assignments(
                    conn, sch, tbl, mc.assignments)
                items = [A.SelectItem(A.Identifier((alias, "$rowid")),
                                      "$rowid")]
                for j, (_, expr) in enumerate(massign):
                    items.append(A.SelectItem(expr, f"$v{j}"))
                q = A.Query(select=tuple(items), distinct=False,
                            relation=A.Join("inner", stmt.source, tref,
                                            stmt.on),
                            where=mc.condition, group_by=(),
                            having=None, order_by=(), limit=None)
                fields, arrays, valids = self.query_to_columns(q)
                ids = np.asarray(arrays[0], dtype=np.int64)
                if len(np.unique(ids)) != len(ids):
                    raise RuntimeError(
                        "MERGE: one target row matched more than one "
                        "source row")
                if mc.action == "delete":
                    n += conn.delete_rows(sch, tbl, ids)
                elif mc.action == "update":
                    updates = {col: (arrays[1 + j], valids[1 + j],
                                     fields[1 + j])
                               for j, (col, _) in enumerate(massign)}
                    n += conn.update_rows(sch, tbl, ids, updates)
                else:
                    raise AnalysisError(
                        "WHEN MATCHED requires UPDATE or DELETE")
            if unmatched:
                ic = unmatched[0]
                sub = A.Query(select=(A.SelectItem(A.NumberLit("1"),
                                                   "x"),),
                              distinct=False, relation=tref,
                              where=stmt.on, group_by=(), having=None,
                              order_by=(), limit=None)
                where: A.Node = A.ExistsPredicate(sub, negated=True)
                if ic.condition is not None:
                    where = A.BinaryOp("and", where, ic.condition)
                # coerce each inserted value to its target column type
                tschema = conn.get_table(sch, tbl).schema
                inames = [c.lower() for c in ic.insert_columns] or \
                    [f.name for f in tschema.fields]
                if len(inames) != len(ic.insert_values):
                    raise AnalysisError(
                        "MERGE INSERT column/value count mismatch")
                ivalues = []
                for cname, e in zip(inames, ic.insert_values):
                    if cname not in {f.name for f in tschema.fields}:
                        raise AnalysisError(
                            f"MERGE INSERT column {cname!r} does not "
                            f"exist")
                    dt = tschema.field(cname).dtype
                    if dt.kind is not TypeKind.VARCHAR:
                        e = A.CastExpr(e, self._sql_type_name(dt))
                    ivalues.append(e)
                items = tuple(A.SelectItem(e, f"$c{j}") for j, e in
                              enumerate(ivalues))
                q2 = A.Query(select=items, distinct=False,
                             relation=stmt.source, where=where,
                             group_by=(), having=None, order_by=(),
                             limit=None)
                fields, arrays, valids = self.query_to_columns(q2)
                target = conn.get_table(sch, tbl)
                by_name = dict(zip(inames, range(len(inames))))
                n_ins = len(arrays[0]) if arrays else 0
                full_arrays, full_valids, full_fields = [], [], []
                for f in target.schema.fields:
                    j = by_name.get(f.name)
                    if j is None:     # unmentioned column: NULL
                        full_arrays.append(
                            np.zeros(n_ins, dtype=f.dtype.np_dtype))
                        full_valids.append(
                            np.zeros(n_ins, dtype=np.bool_))
                        full_fields.append(f)
                    else:
                        full_arrays.append(np.asarray(arrays[j]))
                        full_valids.append(valids[j])
                        full_fields.append(fields[j])
                n += conn.insert(sch, tbl, full_arrays, full_valids,
                                 full_fields)
        finally:
            conn.drop_table(sch, shadow, if_exists=True)
        self.catalog.bump_version()
        self.executor.invalidate_scan_cache()
        return QueryResult(["rows"], [(n,)], time.monotonic() - t0)

    def query_to_columns(self, query):
        """Run a query and return (fields, host arrays, valids) — the
        TableWriterOperator boundary (raw codes, not decoded strings)."""
        rel = self.planner().plan_query(query)
        root = prune_plan(rel.node)
        batch = self.executor.execute(root)
        names, arrays, valids = self.executor.result_to_host(root, batch)
        fields = []
        for sc, name in zip(rel.scope.columns, names):
            fld = sc.field if sc.field is not None else Field(name,
                                                              sc.dtype)
            fields.append(Field(name, sc.dtype,
                                dictionary=fld.dictionary))
        return fields, list(arrays), list(valids)

    def decode_rows(self, rel, arrays, valids) -> List[tuple]:
        cols = []
        for sc, arr, val in zip(rel.scope.columns, arrays, valids):
            fld = sc.field if sc.field is not None else Field(
                sc.name, sc.dtype)
            if sc.dtype.kind is TypeKind.VARCHAR and \
                    (fld.dictionary is None):
                raise RuntimeError(
                    f"varchar output {sc.name} lost its dictionary")
            cols.append(decode_column(fld, arr, val))
        return list(zip(*cols)) if cols else []
