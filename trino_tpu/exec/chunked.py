"""Bounded-memory plan execution: the big table streams in chunks.

Reference: Trino's spill tier — SpillableHashAggregationBuilder merges
partial aggregation states spilled to disk, and the spilling join processes
partitions one at a time (operator/aggregation/builder/
SpillableHashAggregationBuilder.java, operator/join/PartitionedConsumption.java,
spiller/FileSingleStreamSpiller.java:59), triggered by memory watermarks
(execution/MemoryRevokingScheduler.java:47).

TPU redesign: host RAM is the spill tier and the *scan* is the spill
boundary. The plan's largest table (the fact table: every TPC-H/DS query has
one) never materializes on device; it streams through the compiled pipeline
in fixed-size chunks:

    for chunk in fact_table:            # host -> device, bounded HBM
        partial = run(plan_path(chunk)) # filter/project/joins/partial agg,
                                        # one jitted pipeline, reused trace
    merged = re_aggregate(concat(partials))   # MERGE step
    result = run(rest_of_plan, merged)

Join build sides (dimension tables) are computed once and pinned for the
whole loop — the analog of Trino's build-side LookupSource living across
probe pages. Chunks all share one padded capacity, so the whole loop hits
one XLA compilation.

Shapes handled: any Filter/Project/Join(probe-side)/Aggregate path above
the driver scan. The merge point is the first aggregate above the scan
(partial states merge by re-aggregation, Trino's PARTIAL->FINAL split) or
the plan root (outputs concatenate on host). Paths containing Sort/Window/
SetOp below the merge point, distinct aggregates, or the driver on a join
BUILD side fall back to single-shot execution.
"""

from __future__ import annotations

import functools
import queue
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..batch import (Batch, Column, batch_from_numpy, batch_to_numpy,
                     bucket_capacity)
from ..planner import logical as L
from .profiler import instrument, recorded_jit


@recorded_jit(static_argnums=(0, 1), site="exec.slice_widen")
def _slice_widen(cap: int, wide_names: tuple, datas, valids,
                 start, end, num_rows):
    """Slice one chunk straight from device-resident narrowed columns
    (exec/device_cache.py): dynamic_slice + widen to the engine's lane
    dtype + live mask. The slice offset clamps so the last (short) chunk
    re-reads the tail of the previous one, with the live mask excluding
    the overlap — every chunk shares ONE trace and never touches the
    host link."""
    idx = jnp.arange(cap, dtype=jnp.int64)
    s0 = jnp.clip(start, 0, jnp.maximum(num_rows - cap, 0))
    cols = []
    for a, v, wn in zip(datas, valids, wide_names):
        sl = jax.lax.dynamic_slice(a, (s0,), (cap,))
        data = sl if str(sl.dtype) == wn else sl.astype(jnp.dtype(wn))
        valid = jnp.ones(cap, jnp.bool_) if v is None else \
            jax.lax.dynamic_slice(v, (s0,), (cap,))
        cols.append(Column(data, valid))
    live = ((s0 + idx) >= start) & ((s0 + idx) < end)
    return Batch(tuple(cols), live)

# partial-state merge functions (HashAggregationOperator's
# intermediate-state combine): min/max idempotent, sums/counts add
MERGE_FUNC = {"sum": "sum", "count": "sum", "count_star": "sum",
              "min": "min", "max": "max"}


def _fused_join_ok(node: L.JoinNode) -> bool:
    return (node.kind in ("inner", "left", "semi", "anti") and
            node.build_key_domain is not None and node.build_unique and
            node.residual is None and not node.null_aware and
            len(node.left_keys) == 1)


def _spine_joins(target: L.PlanNode, driver: L.ScanNode) \
        -> Optional[List[L.JoinNode]]:
    """JoinNodes on the driver's probe spine, bottom-up (the order
    compile_fused_chunk's emit() appends them). None when any spine
    join can't run in the fused pipeline."""
    joins: List[L.JoinNode] = []

    def walk(node) -> bool:
        if node is driver:
            return True
        if isinstance(node, (L.FilterNode, L.ProjectNode,
                             L.AggregateNode)):
            return walk(node.child)
        if isinstance(node, L.JoinNode):
            if _fused_join_ok(node) and walk(node.left):
                joins.append(node)
                return True
            return False
        return False

    return joins if walk(target) else None


# value-packing caps: at most this many payload columns, packed word
# must fit int64 with the sign bit untouched
_PACK_MAX_COLS = 4
_PACK_MAX_BITS = 62


def _plan_packing(build: Batch, node: L.JoinNode, mins, maxs):
    """Static packing meta for a build whose payload values fit one
    word: ((col_idx, lo, width, val_off, valid_off), ...), word dtype
    name. None when not packable (caller keeps the row-id LUT)."""
    bkey = node.right_keys[0] if len(node.right_keys) == 1 else None
    payload = [i for i in range(len(build.columns)) if i != bkey]
    if len(payload) > _PACK_MAX_COLS:
        return None
    meta = []
    off = 1                                   # bit0 = presence
    for j, i in enumerate(payload):
        if not jnp.issubdtype(build.columns[i].data.dtype, jnp.integer):
            return None
        lo, hi = int(mins[j]), int(maxs[j])
        if hi < lo:
            lo, hi = 0, 0
        width = max(1, int(hi - lo + 1).bit_length())
        meta.append((i, lo, width, off, off + width))
        off += width + 1
    if off > _PACK_MAX_BITS:
        return None
    word_dtype = "int8" if off <= 7 else "int16" if off <= 15 else \
        "int32" if off <= 31 else "int64"
    return tuple(meta), word_dtype


def compile_fused_chunk(executor, target: L.PlanNode,
                        driver: L.ScanNode, lut_specs=None, adapt=None,
                        gather_mode: str = "off"):
    """Compose the whole per-chunk path (joins with prebuilt LUTs,
    filters, projections, the partial aggregate) into ONE traced
    function so every chunk is a single device dispatch with zero host
    syncs and no per-operator intermediate materialization — XLA fuses
    across what the per-node executor would run as 6-8 separate
    programs. Supported shape: Filter/Project chains, single-key
    unique-build dense joins (driver on the probe side), and a
    direct/global partial aggregate on top.

    `lut_specs` maps id(join node) -> spec from _fused_luts: ("rows",)
    joins gather per payload column off a row-id LUT; ("packed", meta,
    word_dtype, bkey, out_dtypes) joins decode everything from ONE
    value-packed gather.

    `adapt` applies a previous run's measurements (AdaptivePlanner.java:87's
    role, replayed through the cross-run decision cache): {"windows":
    {join_idx: W}} probes a packed join through a W-sized LUT window
    (near-sorted keys); {"compact": (join_idx, cap)} compacts live rows
    to `cap` after that join so later operators run at the real
    selectivity. Both are guesses that may be invalidated by new data,
    so the program reports per-join (escaped, span, live) + compaction
    overflow in a stats vector the DRIVER must verify (nonzero escaped/
    overflow => rerun the plain program).

    `gather_mode` routes windowed packed probes through the Pallas
    tiled-gather kernel (ops/pallas_gather.py): the driver prepares
    per-LUT int32 planes ONCE and passes them as the program's fourth
    argument; kernel window escapes fold into the same escaped flag the
    verifier already checks, so a violated near-sorted guess reruns
    plain exactly as before.

    Returns (fn, join_nodes) where fn(chunk, builds, luts, gplanes) ->
    (partial Batch, stats int64[2 + 3*n_joins]); stats layout:
    [escaped_total, compact_overflow, span_0, live_0, 0, span_1, ...].
    None when the shape doesn't apply (caller uses the per-node loop)."""
    from ..ops.aggregate import (AggSpec, direct_group_aggregate,
                                 global_aggregate)
    from ..ops.join import (compact_live, dense_join_packed,
                            dense_join_packed_windowed,
                            dense_join_with_lut)
    from ..ops.project import apply_filter, filter_project

    joins: List[L.JoinNode] = []
    windows = (adapt or {}).get("windows", {})
    compact_at = (adapt or {}).get("compact")

    def emit(node):
        """Returns f(chunk, builds, luts, gplanes) -> (Batch, stats
        dict) or None. stats: {"escaped": scalar, "overflow": scalar,
        "joins": [(span, live), ...]}."""
        if node is driver:
            return lambda chunk, builds, luts, gp: (chunk, {
                "escaped": jnp.int64(0), "overflow": jnp.int64(0),
                "joins": []})
        if isinstance(node, L.FilterNode):
            child = emit(node.child)
            if child is None:
                return None
            pred = executor.fold_scalars(node.predicate)

            def run_filter(chunk, b, l, g, _child=child, _pred=pred):
                bt, st = _child(chunk, b, l, g)
                return apply_filter(bt, _pred), st
            return run_filter
        if isinstance(node, L.ProjectNode):
            child = emit(node.child)
            if child is None:
                return None
            exprs = executor.fold_scalars_tuple(node.exprs)

            def run_project(chunk, b, l, g, _child=child, _exprs=exprs):
                bt, st = _child(chunk, b, l, g)
                return filter_project(bt, None, _exprs), st
            return run_project
        if isinstance(node, L.JoinNode):
            if not _fused_join_ok(node):
                return None
            child = emit(node.left)
            if child is None:
                return None
            idx = len(joins)
            joins.append(node)
            lk, rk, kind = node.left_keys, node.right_keys, node.kind
            spec = lut_specs.get(id(node)) if lut_specs else None
            window = windows.get(idx)
            cap = compact_at[1] if compact_at is not None and \
                compact_at[0] == idx else None

            def run_join(chunk, b, l, g, _child=child, _idx=idx,
                         _lk=lk, _rk=rk, _kind=kind, _spec=spec,
                         _win=window, _cap=cap):
                bt, st = _child(chunk, b, l, g)
                esc = jnp.int64(0)
                if _spec is not None and _spec[0] == "packed":
                    _, meta, _wd, bkey, out_dtypes = _spec
                    if _win is not None:
                        gp = g[_idx] if _idx < len(g) else None
                        out, esc, span = dense_join_packed_windowed(
                            bt, l[_idx], _lk, meta, bkey, out_dtypes,
                            _kind, _win, word_dtype=_wd,
                            gather_mode=gather_mode, lut_planes=gp)
                    else:
                        out = dense_join_packed(
                            bt, l[_idx], _lk, meta, bkey, out_dtypes,
                            _kind, gather_mode)
                        span = _key_span(bt, _lk)
                else:
                    out = dense_join_with_lut(bt, b[_idx], l[_idx], _lk,
                                              _rk, _kind, gather_mode)
                    span = _key_span(bt, _lk)
                live = jnp.sum(out.live, dtype=jnp.int64)
                if _cap is not None:
                    out, over = compact_live(out, _cap)
                    st = dict(st, overflow=st["overflow"] + over)
                return out, dict(
                    st, escaped=st["escaped"] + esc,
                    joins=st["joins"] + [(span, live)])
            return run_join
        if isinstance(node, L.AggregateNode):
            child = emit(node.child)
            if child is None:
                return None
            if any(a.distinct for a in node.aggs):
                return None
            aggs = tuple(AggSpec(a.func, a.arg.index
                                 if a.arg is not None else None)
                         for a in node.aggs)
            if node.strategy == "global":
                def run_gagg(chunk, b, l, g, _child=child, _aggs=aggs):
                    bt, st = _child(chunk, b, l, g)
                    return global_aggregate(bt, _aggs), st
                return run_gagg
            if node.strategy == "direct":
                keys, domains = node.group_keys, node.key_domains

                def run_dagg(chunk, b, l, g, _child=child, _aggs=aggs,
                             _keys=keys, _domains=domains):
                    bt, st = _child(chunk, b, l, g)
                    return direct_group_aggregate(
                        bt, _keys, _domains, _aggs), st
                return run_dagg
            return None
        return None

    inner = emit(target)
    if inner is None:
        return None

    def fn(chunk, builds, luts, gplanes=()):
        out, st = inner(chunk, builds, luts, gplanes)
        parts = [st["escaped"], st["overflow"]]
        for span, live in st["joins"]:
            parts.extend((span, live, jnp.int64(0)))
        return out, jnp.stack(parts) if parts else \
            jnp.zeros(2, jnp.int64)
    return fn, joins


def _key_span(batch: Batch, keys: tuple):
    """Probe-key extent of live rows (windowing measurement).

    Measured over the COMBINED packed key — the same key the windowed
    probe (dense_join_packed_windowed) slices by. Measuring keys[0]
    alone underestimated multi-key packed joins by ~2^32 per trailing
    column, so the adapted window always escaped: every run compiled
    the adapted program, failed verification, dropped the record, reran
    plain, and re-recorded the same bad span — a permanent ~2x
    device-work cycle (ADVICE round-5 low)."""
    from ..ops.join import _combined_key
    key, valid = _combined_key(batch, keys)
    ok = batch.live & valid
    big = jnp.int64(1) << 62
    lo = jnp.min(jnp.where(ok, key, big))
    hi = jnp.max(jnp.where(ok, key, -big))
    return jnp.maximum(hi - lo + 1, 0)


def _fused_luts(executor, joins) -> Optional[tuple]:
    """Build + validate the dense LUT for every fused join, choosing
    value-packed LUTs whenever the payload fits one word (probe = ONE
    gather) and falling back to row-id LUTs otherwise. LUT+spec pairs
    reuse the cross-run cache for deterministic builds; their stats and
    dup/oob validations ride the persistent decision cache (sync-free on
    replay). Uncacheable builds fuse all stats into one device fetch and
    all validations into a second. Any violation aborts the fused path
    (the per-node loop has the graceful fallbacks)."""
    from ..ops.join import dense_build_lut, dense_build_packed_lut
    n = len(joins)
    builds = [executor.run(j.right) for j in joins]
    luts: List[object] = [None] * n
    specs: List[object] = [None] * n
    fresh: List[int] = []
    keys: List[object] = [None] * n
    for k, node in enumerate(joins):
        keys[k] = executor.build_structure_key(node.right)
        hit = executor._lut_cache.get((keys[k], node.build_key_domain)) \
            if keys[k] is not None else None
        if hit is not None:
            luts[k], specs[k] = hit
        else:
            fresh.append(k)
    if fresh:
        # min/max of integer payload columns (packing layouts are
        # host-side statics). Cacheable builds (deterministic catalogs)
        # fetch per build through the cross-run decision cache — the tag
        # carries right_keys/kind/domain because the SAME build subtree
        # joined on a different key has different stats layout and
        # validation semantics; the structure hash alone covers only
        # j.right. A FRESH process then replays with zero device syncs.
        # Uncacheable builds keep the old behavior: ALL their stats fuse
        # into one fetch and all their validations into a second.
        big = 1 << 62

        def minmax_parts(k):
            b, j = builds[k], joins[k]
            bkey = j.right_keys[0]
            parts = []
            for i in range(len(b.columns)):
                if i == bkey:
                    continue
                col = b.columns[i]
                if jnp.issubdtype(col.data.dtype, jnp.integer):
                    m = b.live & col.valid
                    d = col.data.astype(jnp.int64)
                    parts.append(jnp.min(jnp.where(m, d, big)))
                    parts.append(jnp.max(jnp.where(m, d, -big)))
                else:
                    parts.append(jnp.full((), big, jnp.int64))
                    parts.append(jnp.full((), -big, jnp.int64))
            return parts

        def build_one(k, mins, maxs):
            """Build LUT k; returns (dup_signal, oob) device scalars."""
            b, j = builds[k], joins[k]
            if j.kind in ("semi", "anti"):
                pk = ((), "int8")         # presence bit only
            else:
                pk = _plan_packing(b, j, mins, maxs)
            if pk is not None:
                meta, wd = pk
                lut, exp, oob, occ = dense_build_packed_lut(
                    b, j.right_keys, j.build_key_domain, meta, wd)
                specs[k] = ("packed", meta, wd, j.right_keys[0],
                            tuple(str(c.data.dtype) for c in b.columns))
                dup_sig = exp - occ           # >0 = duplicate keys
            else:
                lut, dup, oob = dense_build_lut(b, j.right_keys,
                                                j.build_key_domain)
                specs[k] = ("rows",)
                dup_sig = dup.astype(jnp.int64)
            luts[k] = lut
            return dup_sig, oob

        def join_tag(base, j):
            return (f"{base}:{tuple(j.right_keys)}:{j.kind}:"
                    f"{j.build_key_domain}")

        cacheable = [k for k in fresh if keys[k] is not None]
        fused_rest = [k for k in fresh if keys[k] is None]
        for k in cacheable:
            j = joins[k]
            parts = minmax_parts(k)
            vals = np.asarray(executor.fetch_ints(
                j.right, join_tag("fusedminmax", j), *parts),
                dtype=np.int64) if parts else np.zeros(0, np.int64)
            dup_sig, oob = build_one(k, vals[0::2], vals[1::2])
            check = executor.fetch_ints(
                j.right, join_tag("fusedlutcheck", j), dup_sig, oob)
            if check[0] != 0 or check[1] != 0:
                return None
        if fused_rest:
            all_parts = [minmax_parts(k) for k in fused_rest]
            flat = [p for ps in all_parts for p in ps]
            vals = np.asarray(jnp.stack(flat)) if flat else \
                np.zeros(0, np.int64)
            pos, checks = 0, []
            for k, ps in zip(fused_rest, all_parts):
                vk = vals[pos:pos + len(ps)]
                pos += len(ps)
                checks.extend(build_one(k, vk[0::2], vk[1::2]))
            if int(np.asarray(jnp.stack(checks)).sum()) != 0:
                return None
        for k in fresh:
            if keys[k] is not None:
                if len(executor._lut_cache) >= 4:
                    executor._lut_cache.pop(
                        next(iter(executor._lut_cache)))
                executor._lut_cache[(keys[k], joins[k].build_key_domain)] \
                    = (luts[k], specs[k])
    return tuple(builds), tuple(luts), tuple(specs)


def _windowed_planes(gmode: str, adapt, specs, luts, k):
    """int32 gather planes for join k's LUT, or None when the Pallas
    windowed probe won't run for it (mode off, not adapted to a window,
    not value-packed, or domain too wide for 32-bit kernel indices)."""
    from ..ops import pallas_gather
    windows = (adapt or {}).get("windows", {})
    if gmode == "off" or k not in windows or specs[k] is None or \
            specs[k][0] != "packed" or \
            luts[k].shape[0] > pallas_gather.MAX_WINDOWED_ELEMS:
        return None
    return pallas_gather.prepare_word_planes(luts[k])


# adaptive re-optimization safety margins: windows/capacities pad the
# measured maxima so ordinary chunk-to-chunk variance doesn't trip the
# rerun path; real data changes still do (and then re-measure)
_ADAPT_MARGIN = 1.25


def _fused_adaptation(executor, skey, spine, specs, chunk_cap):
    """Build the `adapt` argument for compile_fused_chunk from a
    previous run's recorded measurements (cross-run decision cache):
    window sizes for packed joins with near-sorted probe keys, and one
    compaction point where measured selectivity is low. None on the
    first-ever run (the plain program measures)."""
    from ..batch import bucket_capacity
    if skey is None:
        return None
    if not executor._decision_loaded:
        executor._load_decisions()
    rec = executor._decision_cache.get(
        ("fusedadapt", skey, executor._decision_salt()))
    if rec is None or len(rec) != 2 * len(spine):
        return None
    allow_windows = getattr(executor, "enable_adapt_windows", True)
    allow_compact = getattr(executor, "enable_adapt_compact", False)
    windows = {}
    compact = None
    for i, j in enumerate(spine):
        span, live = rec[2 * i], rec[2 * i + 1]
        domain = j.build_key_domain
        if allow_windows and specs[i] is not None and \
                specs[i][0] == "packed" and span > 0 and domain:
            w = bucket_capacity(int(span * _ADAPT_MARGIN))
            if w * 2 <= domain:      # window must actually shrink reads
                windows[i] = w
        if allow_compact and compact is None and live >= 0:
            # NOTE measured on v5e: jnp.nonzero's lowering scatters, and
            # TPU scatter costs ~80ns/row — in-program compaction LOSES
            # unless later stages are very wide. Off by default.
            c = max(1024, bucket_capacity(int(live * _ADAPT_MARGIN)))
            if c * 4 <= chunk_cap:   # only pay the compact gather when
                compact = (i, c)     # later operators shrink >=4x
    if not windows and compact is None:
        return None
    return {"windows": windows, "compact": compact}


def _verify_record_adaptation(executor, skey, adapt, chunk_stats) -> bool:
    """ONE fetch over the run's stacked per-chunk stats: correctness
    flags (escaped window rows, compaction overflow) plus span/live
    maxima. Plain runs record measurements for the next run's
    adaptation; adapted runs verify their guesses — False means results
    are unusable and the caller must rerun plain (the stale record is
    removed so the rerun re-measures)."""
    key = ("fusedadapt", skey, executor._decision_salt()) \
        if skey is not None else None
    if adapt is None and (key is None or key in executor._decision_cache):
        return True      # nothing to verify or record: skip the sync
    stk = jnp.stack(chunk_stats)
    esc = jnp.sum(stk[:, 0])
    over = jnp.sum(stk[:, 1])
    spans = jnp.max(stk[:, 2::3], axis=0)
    lives = jnp.max(stk[:, 3::3], axis=0)
    vals = np.asarray(jnp.concatenate(
        [jnp.stack([esc, over]), spans, lives]))
    n_joins = len(spans)
    esc_h, over_h = int(vals[0]), int(vals[1])
    measured = []
    for i in range(n_joins):
        measured.extend((int(vals[2 + i]), int(vals[2 + n_joins + i])))
    if esc_h > 0 or over_h > 0:
        if over_h > 0:
            executor.stats.compaction_overflows += 1
        # stale guesses: drop the record so the rerun runs PLAIN and
        # re-measures (an adapted rerun from these numbers could loop —
        # escaped rows depress the live measurement)
        if key is not None:
            executor._decision_cache.pop(key, None)
            executor._decision_dirty = True
        return False
    if adapt is None and key is not None:
        executor._decision_cache[key] = tuple(measured)
        executor._decision_dirty = True
    return True


class ChunkAnalysis:
    """Where to cut the plan for chunked execution."""

    def __init__(self, driver: L.ScanNode, merge_agg: Optional[L.AggregateNode],
                 build_roots: List[L.PlanNode], driver_rows: int,
                 merge_sort: Optional["L.SortNode"] = None):
        self.driver = driver
        self.merge_agg = merge_agg          # None = concat at root
        self.build_roots = build_roots      # pinned once, reused per chunk
        self.driver_rows = driver_rows
        # distributed ORDER BY: the fragment's top Sort — per-split
        # outputs are sorted RUNS the consumer merges order-preservingly
        # (MergeOperator.java's role); only the scheduler opts in
        self.merge_sort = merge_sort


def _scan_rows(catalog, node: L.ScanNode) -> int:
    return catalog.get_table(node.catalog, node.schema_name,
                             node.table).num_rows


def analyze(root: L.OutputNode, catalog, chunk_rows: int,
            allow_sort_merge: bool = False) -> Optional[ChunkAnalysis]:
    """Pick the driver scan and validate the path up to the merge point.
    With allow_sort_merge, a Sort directly below the output becomes the
    fragment top: per-split outputs are sorted runs for an
    order-preserving merge (the distributed scheduler's MergeOperator
    path; the local chunked driver keeps its re-sort semantics)."""
    parents: Dict[int, L.PlanNode] = {}

    def walk(node):
        for c in L.children(node):
            parents[id(c)] = node
            walk(c)
    walk(root)

    scans = [n for n in _all_nodes(root) if isinstance(n, L.ScanNode)]
    if not scans:
        return None
    driver = max(scans, key=lambda s: _scan_rows(catalog, s))
    driver_rows = _scan_rows(catalog, driver)
    if driver_rows <= chunk_rows:
        return None

    build_roots: List[L.PlanNode] = []
    merge_agg: Optional[L.AggregateNode] = None
    merge_sort: Optional[L.SortNode] = None
    node: L.PlanNode = driver
    while True:
        parent = parents.get(id(node))
        if parent is None:
            break
        if isinstance(parent, (L.FilterNode, L.ProjectNode)):
            pass
        elif isinstance(parent, L.JoinNode):
            if parent.left is not node:
                return None       # driver on the build side: can't stream
            build_roots.append(parent.right)
        elif isinstance(parent, L.MultiJoinNode):
            # fused star: the driver must BE the fact side; every
            # dimension pins like a pairwise build side, so the fused
            # tables build once and each chunk probes them sync-free
            if parent.fact is not node:
                return None
            build_roots.extend(parent.dims)
        elif isinstance(parent, L.AggregateNode):
            if any(a.distinct for a in parent.aggs):
                return None       # distinct needs global dedup
            if any(a.func not in MERGE_FUNC for a in parent.aggs):
                return None
            merge_agg = parent
            break
        elif isinstance(parent, L.OutputNode):
            break                 # concat mode
        elif allow_sort_merge and isinstance(parent, L.SortNode) and \
                isinstance(parents.get(id(parent)), L.OutputNode):
            merge_sort = parent
            break
        else:
            return None           # Sort/Window/SetOp/Limit below merge point
        node = parent
    return ChunkAnalysis(driver, merge_agg, build_roots, driver_rows,
                         merge_sort=merge_sort)


def _all_nodes(node):
    yield node
    for c in L.children(node):
        yield from _all_nodes(c)


# TRINO_TPU_CHUNK_PROFILE=1 (shared helper in device_cache): per-phase
# walls to stderr, with a blocking sync per chunk so device time
# attributes to its dispatch (diagnostic only — the sync costs a tunnel
# RTT per chunk on this rig)
from .device_cache import prof as _prof
from .device_cache import profile_enabled as _profile_enabled


class _PrefetchPipeline:
    """Bounded double-buffered decode->stage pipeline for the chunked
    driver ("Revisiting Co-Processing..." overlap, PAPERS.md): a worker
    thread decodes chunk k+1 from host columns and stages its device
    transfer (batch_from_numpy -> jnp.asarray, the same
    device_cache-warmed path) while the device computes chunk k.

    Every staged chunk holds a REVOCABLE reservation in the memory pool,
    so arbitration/backpressure see the prefetch buffer and can reclaim
    it under pressure: a revoked chunk is simply re-decoded inline by the
    consumer — correctness never depends on staging. Faults injected at
    the SCAN_PREFETCH chaos point raise out of next() on the consumer
    thread, surfacing as an ordinary retryable query/task failure.
    `depth` bounds how many chunks may sit decoded-but-unconsumed."""

    def __init__(self, executor, starts, decode, depth: int):
        self.executor = executor
        self.pool = executor.pool
        self.decode = decode
        self.decode_s = 0.0
        self.served = 0                     # chunks consumed from staging
        self._staged: Dict[int, tuple] = {}
        self._lock = threading.Lock()
        self._slots = threading.Semaphore(depth)
        self._queue: "queue.Queue[tuple]" = queue.Queue()
        self._stop = False
        self._revocation = self.pool.register_revocation(
            self._revoke, tag="scan-prefetch")
        self._thread = threading.Thread(
            target=self._run, args=(list(starts),),
            name="scan-prefetch", daemon=True)
        self._thread.start()

    def _gauge(self) -> None:
        from ..metrics import SCAN_PREFETCH_BUFFERS
        SCAN_PREFETCH_BUFFERS.set(len(self._staged))

    def _revoke(self, target_bytes: int) -> int:
        """Memory-pool revocation callback: drop staged chunks (newest
        kept longest would not matter — the consumer re-decodes any
        missing chunk inline)."""
        freed = 0
        with self._lock:
            for s in list(self._staged):
                if freed >= target_bytes:
                    break
                _, b = self._staged.pop(s)
                self.pool.free_revocable(b, tag="scan-prefetch")
                freed += b
            self._gauge()
        return freed

    def _run(self, starts) -> None:
        from .memory import batch_bytes
        try:
            for s in starts:
                self._slots.acquire()
                if self._stop:
                    return
                inj = self.executor.failure_injector
                if inj is not None:
                    from ..server.failureinjector import SCAN_PREFETCH
                    inj.maybe_fail(SCAN_PREFETCH, f"chunk@{s}")
                t0 = time.monotonic()
                batch = self.decode(s)
                self.decode_s += time.monotonic() - t0
                b = batch_bytes(batch)
                self.pool.reserve_revocable(b, tag="scan-prefetch")
                with self._lock:
                    self._staged[s] = (batch, b)
                    self._gauge()
                _prof(f"prefetch: chunk@{s} staged")
                self._queue.put(("chunk", s))
            self._queue.put(("done", None))
        except BaseException as e:          # surfaces in next()
            self._queue.put(("error", e))

    def next(self, expected_start: int) -> Batch:
        from ..metrics import SCAN_PREFETCH_STALL_SECONDS
        import queue as _q
        t0 = time.monotonic()
        while True:
            # bounded waits so a stuck prefetch worker (chaos HANG, dead
            # source) can't pin a canceled query on the exec lock — the
            # cooperative check raises and close() reaps the thread
            try:
                kind, val = self._queue.get(timeout=0.25)
                break
            except _q.Empty:
                self.executor.check_cancel()
        wait = time.monotonic() - t0
        if wait > 1e-4:
            self.executor.stats.scan_prefetch_stalls += 1
            SCAN_PREFETCH_STALL_SECONDS.inc(wait)
        if kind == "error":
            raise val
        assert kind == "chunk" and val == expected_start, \
            f"prefetch out of order: {kind} {val} != {expected_start}"
        with self._lock:
            hit = self._staged.pop(expected_start, None)
            self._gauge()
        self._slots.release()
        if hit is None:                     # revoked under pressure
            t0 = time.monotonic()
            batch = self.decode(expected_start)
            self.decode_s += time.monotonic() - t0
            return batch
        batch, b = hit
        self.pool.free_revocable(b, tag="scan-prefetch")
        self.executor.stats.scan_prefetched_chunks += 1
        self.served += 1
        return batch

    def close(self) -> None:
        self._stop = True
        self._slots.release()               # unblock a waiting worker
        self._thread.join(timeout=10)
        with self._lock:
            for s in list(self._staged):
                _, b = self._staged.pop(s)
                self.pool.free_revocable(b, tag="scan-prefetch")
            self._gauge()
        self.pool.unregister_revocation(self._revocation)


def execute_chunked(executor, root: L.OutputNode) -> Optional[Batch]:
    """Run `root` with the driver scan streamed in chunks. Returns None if
    the plan shape doesn't support chunking (caller falls back)."""
    chunk_rows = executor.spill_chunk_rows
    plan = analyze(root, executor.catalog, chunk_rows)
    if plan is None:
        return None

    # pin join build sides once (HashBuilderOperator builds once, probes
    # stream); scalar subqueries are folded+cached by the executor anyway.
    # Builds of DETERMINISTIC sources additionally persist across runs in
    # a structural-hash cache (the scan cache's policy extended to build
    # subtrees): a repeated chunked query skips minutes of build joins.
    _prof("pin builds: start")
    for b in plan.build_roots:
        if id(b) not in executor._subst:
            executor._subst[id(b)] = executor.run_cached_build(b)
    _prof("pin builds: done")

    data = executor.catalog.get_table(plan.driver.catalog,
                                      plan.driver.schema_name,
                                      plan.driver.table)
    per_chunk_target = plan.merge_agg if plan.merge_agg is not None \
        else root.child

    # spillable partial-aggregation state: device partials hold REVOCABLE
    # reservations; under memory pressure the pool's revocation request
    # moves them to host pages and the merge step re-aggregates
    # partition-wise (exec/spill.PartialState)
    from .spill import PartialState
    partial_state = PartialState(executor) \
        if plan.merge_agg is not None else None
    concat_arrays: List[list] = []
    concat_valids: List[list] = []
    # one shared padded capacity => one jit trace for every chunk
    cap = bucket_capacity(min(chunk_rows, plan.driver_rows))

    # device-resident narrowed fact columns: when the driver scan fits
    # the HBM budget in its narrowest dtypes, chunks slice straight from
    # device memory (steady state never touches the ~30 MB/s host link)
    fact = None
    if executor.enable_fact_cache and cap <= plan.driver_rows:
        key = (plan.driver.catalog, plan.driver.schema_name,
               plan.driver.table, tuple(plan.driver.column_indices))
        if executor.fact_cache.estimate_bytes(
                data, plan.driver.column_indices) <= \
                executor.fact_cache.max_bytes:
            if executor.fact_cache.get(key) is None:
                # about to claim several GB of HBM: raw cached scans are
                # dead weight now (the pinned builds already consumed
                # them) — drop them first, NOT the fact cache itself
                executor._scan_cache.clear()
                executor._scan_cache_bytes.clear()
            fact = executor.fact_cache.load(
                key, data, plan.driver.column_indices,
                persist_ok=plan.driver.catalog in ("tpch", "tpcds",
                                                   "bench"))
    if fact is not None:
        fact_datas = tuple(c.data for c in fact)
        fact_valids = tuple(c.valid for c in fact)
        fact_wide = tuple(str(c.wide_dtype) for c in fact)

    # fused pipeline: the whole per-chunk path as ONE program per chunk
    # (zero host syncs in the loop; LUTs prebuilt + validated once)
    fused = None
    if plan.merge_agg is not None and not executor.profile and \
            plan.merge_agg.strategy in ("global", "direct") and \
            not any(a.distinct for a in plan.merge_agg.aggs):
        # the strategy gate mirrors compile_fused_chunk's emit() support
        # so LUTs are never built (device work + a blocking validation
        # fetch) for a plan the fused compiler would then reject
        spine = _spine_joins(per_chunk_target, plan.driver)
        bl = _fused_luts(executor, spine) if spine is not None else None
        if bl is not None:
            builds, luts, specs = bl
            # one jitted wrapper per (plan structure, packing layout,
            # adaptation, gather mode), reused across runs so
            # re-executions hit the in-memory trace cache (a replan
            # produces new node objects but identical static values)
            gmode = executor.gather_mode()
            skey = executor.build_structure_key(per_chunk_target)
            adapt = _fused_adaptation(executor, skey, spine, specs, cap)
            # Pallas windowed probes gather off int32 planes prepared
            # ONCE per pinned LUT (per-chunk re-splitting would re-read
            # the whole domain-sized table every chunk)
            gplanes = tuple(
                _windowed_planes(gmode, adapt, specs, luts, k)
                for k in range(len(spine)))
            ckey = (skey, specs, repr(adapt), gmode) \
                if skey is not None else None
            jitted = executor._fused_cache.get(ckey) \
                if ckey is not None else None
            if jitted is None:
                mine = compile_fused_chunk(
                    executor, per_chunk_target, plan.driver,
                    {id(j): s for j, s in zip(spine, specs)}, adapt,
                    gather_mode=gmode)
                if mine is not None:
                    # routed through the compile recorder: the first
                    # chunk call records the actual XLA compile (site
                    # exec.fused_chunk, fingerprint = plan-structure
                    # hash), bumping ExecStats.jit_compiles via the
                    # thread binding — re-used traces count as hits
                    jitted = instrument(jax.jit(mine[0]),
                                        site="exec.fused_chunk",
                                        fingerprint=skey or "adhoc")
                    if ckey is not None:
                        if len(executor._fused_cache) >= 8:
                            executor._fused_cache.pop(
                                next(iter(executor._fused_cache)))
                        executor._fused_cache[ckey] = jitted
            if jitted is not None:
                fused = (jitted, builds, luts, skey, adapt, gplanes)
                executor.stats.fused_chunk_pipelines += 1
                if gmode != "off":
                    executor.stats.pallas_gather_calls += 1
    _prof(f"luts+fused ready (fused={fused is not None}, "
          f"adapt={fused[4] if fused else None}, "
          f"fact={fact is not None})")

    # ---- chunk schedule: zone-map pruning skips whole chunks -------------
    # per_chunk_target contains the residual Filter above the driver scan,
    # so a skipped chunk (provably zero matching rows) contributes nothing
    # in BOTH merge-agg and concat modes — bit-exact with skipping off.
    starts_all = list(range(0, plan.driver_rows, chunk_rows))
    starts_list = starts_all
    if plan.driver.predicate is not None and \
            executor.enable_zone_map_pruning:
        from . import zonemap
        zm = zonemap.zone_map_for(data, executor.zone_map_rows)
        starts_list = [
            s for s in starts_all
            if zonemap.range_may_match(
                zm, plan.driver.predicate, plan.driver.column_indices,
                s, min(chunk_rows, plan.driver_rows - s))]
        if not starts_list:
            # keep one chunk so downstream shapes/merges stay on the
            # ordinary path; its rows die at the residual filter
            starts_list = starts_all[:1]
        skipped = len(starts_all) - len(starts_list)
        if skipped:
            executor.stats.scan_chunks_skipped += skipped
            from ..metrics import SCAN_ZONES_PRUNED
            SCAN_ZONES_PRUNED.inc(skipped)
            executor.strategy_decisions[
                f"TableScan[{plan.driver.table}]"] = \
                f"chunks-skipped:{skipped}/{len(starts_all)}"
            _prof(f"zone maps: {skipped}/{len(starts_all)} chunks skipped")

    def _decode_chunk(start: int) -> Batch:
        arrays = [np.asarray(data.columns[i])
                  [start:start + chunk_rows]
                  for i in plan.driver.column_indices]
        valids = None
        if data.valids is not None:
            valids = [None if data.valids[i] is None else
                      np.asarray(data.valids[i])
                      [start:start + chunk_rows]
                      for i in plan.driver.column_indices]
        return batch_from_numpy(arrays, valids=valids, capacity=cap)

    # ---- prefetch pipeline: overlap host decode+stage with compute -------
    # depth 0 (or a device-resident fact table, which decodes nothing)
    # keeps the serial loop exactly
    depth = int(executor.prefetch_depth or 0)
    pipeline = None
    if fact is None and depth > 0 and len(starts_list) > 1:
        pipeline = _PrefetchPipeline(executor, starts_list, _decode_chunk,
                                     depth)

    # ---- compile warm: overlap the fused XLA compile with chunk-0 decode -
    # a zero-row dummy at the shared capacity has the identical trace
    # signature (Batch is an all-array pytree; dtypes come from the real
    # columns), so this warms the very program chunk 0 will call. The
    # output is discarded — bit-exactness is untouched — and the recorder
    # books the compile to the prewarm context so the loop's first call
    # counts as a prewarm hit.
    if fused is not None and pipeline is not None and \
            getattr(executor, "prewarm_chunks", False):
        from .profiler import RECORDER

        def _warm_fused():
            try:
                dummy = batch_from_numpy(
                    [np.asarray(data.columns[i])[:0]
                     for i in plan.driver.column_indices],
                    capacity=cap)
                with RECORDER.prewarm_context():
                    jax.block_until_ready(
                        fused[0](dummy, fused[1], fused[2], fused[5]))
            except Exception:
                pass    # warm is best-effort; the loop compiles anyway

        threading.Thread(target=_warm_fused, name="chunk-warm",
                         daemon=True).start()

    chunk_stats: List[object] = []
    decode_s = 0.0
    compute_s = 0.0
    t_loop = time.monotonic()
    executor.enter_chunk_mode()
    try:
        for start in starts_list:
            # chunk-boundary cooperative cancel: a terminate()/deadline
            # on a long chunked scan frees the exec lock between chunks
            executor.check_cancel()
            if fact is not None:
                chunk = _slice_widen(
                    cap, fact_wide, fact_datas, fact_valids, start,
                    min(start + chunk_rows, plan.driver_rows),
                    plan.driver_rows)
            elif pipeline is not None:
                chunk = pipeline.next(start)
            else:
                t0 = time.monotonic()
                chunk = _decode_chunk(start)
                decode_s += time.monotonic() - t0
            t0 = time.monotonic()
            if fused is not None:
                out, stats_vec = fused[0](chunk, fused[1], fused[2],
                                          fused[5])
                chunk_stats.append(stats_vec)
                if _profile_enabled():
                    jax.block_until_ready(out)
                    _prof(f"chunk@{start} done")
            else:
                executor._subst[id(plan.driver)] = chunk
                executor._subst_opaque.add(id(plan.driver))
                try:
                    out = executor.run(per_chunk_target)
                finally:
                    executor._subst.pop(id(plan.driver), None)
                    executor._subst_opaque.discard(id(plan.driver))
                    # the per-chunk path recomputes these nodes next
                    # iteration; release their reservations now so the
                    # pool reflects only pinned builds + partials
                    executor.release_path_reservations(
                        per_chunk_target, keep=executor._subst)
            executor.stats.agg_spill_chunks += 1
            if fact is not None:
                executor.stats.fact_cache_chunks += 1
            if partial_state is not None:
                partial_state.add(out)
            else:
                arrs, vals = batch_to_numpy(out)
                concat_arrays.append(arrs)
                concat_valids.append(vals)
            compute_s += time.monotonic() - t0
    except BaseException:
        if partial_state is not None:
            partial_state.close()       # drop revocable reservations
        raise
    finally:
        if pipeline is not None:
            decode_s += pipeline.decode_s
            pipeline.close()
        executor.exit_chunk_mode()
        # per-run span attribution for the overlap proof (bench.py
        # --scan-micro compares pipelined wall against the serial run's
        # decode+compute span sum)
        executor.chunk_spans = {
            "chunks": len(starts_list),
            "decode_s": decode_s,
            "compute_s": compute_s,
            "wall_s": time.monotonic() - t_loop,
            "prefetched": pipeline.served if pipeline is not None else 0,
        }

    if plan.merge_agg is None:
        ncols = len(concat_arrays[0])
        arrs = [np.concatenate([c[j] for c in concat_arrays])
                for j in range(ncols)]
        vals = [np.concatenate([c[j] for c in concat_valids])
                for j in range(ncols)]
        merged = batch_from_numpy(arrs, valids=vals)
        # structure-faithful: the concat of all chunks IS root.child's
        # deterministic value, so decisions above it stay cacheable
        executor._subst[id(root.child)] = merged
        try:
            return executor.run(root)
        finally:
            executor._subst.clear()
            executor._subst_opaque.clear()

    if fused is not None and chunk_stats:
        ok = _verify_record_adaptation(executor, fused[3], fused[4],
                                       chunk_stats)
        if not ok:
            # the adaptation's window/capacity guesses were violated by
            # this run's data: results would be wrong — rerun with the
            # plain program (the stale measurement was just invalidated,
            # so the retry does not re-adapt)
            executor.stats.escaped_window_reruns += 1
            partial_state.close()
            _prof("adaptation violated; plain rerun")
            return execute_chunked(executor, root)
    _prof("chunk loop dispatched; merging")
    merged = partial_state.merge(plan.merge_agg)
    # structure-faithful (see concat mode above): decisions above the
    # merge point replay from the cross-run cache
    executor._subst[id(plan.merge_agg)] = merged
    try:
        return executor.run(root)
    finally:
        executor._subst.clear()
        executor._subst_opaque.clear()


# --------------------------------------------------------------------------
# streaming-build join: build sides bigger than device memory
# --------------------------------------------------------------------------

def streaming_build_join(executor, node: L.JoinNode,
                         probe: Batch) -> Optional[Batch]:
    """Inner/semi/anti unique-build join whose BUILD side streams from
    host in chunks (PartitionedConsumption.java's partition-at-a-time
    idea, reshaped for the dense-LUT kernel).

    TPU shape: the LUT is DOMAIN-sized no matter how many build rows
    exist, so the build only ever occupies one chunk of HBM at a time —
    each chunk scatters its global row ids into a persistent LUT. Probe
    lookups then yield global row ids; matched rows compact, and build
    payload columns are gathered HOST-side (numpy fancy-indexing over the
    mmap'd table) at the compacted size, so the full build never
    materializes on device. Requires: single int key with known domain,
    build = Scan or Filter(Scan) (the planner's pruned-scan shape), and a
    planner uniqueness proof. Returns None when the shape doesn't apply
    (caller uses the resident-build path)."""
    import jax.numpy as jnp

    if node.kind not in ("inner", "semi", "anti") or \
            node.build_key_domain is None or not node.build_unique or \
            len(node.right_keys) != 1 or node.residual is not None or \
            node.null_aware:
        return None
    build_root = node.right
    pred = None
    if isinstance(build_root, L.FilterNode):
        pred = executor.fold_scalars(build_root.predicate)
        scan = build_root.child
    else:
        scan = build_root
    if not isinstance(scan, L.ScanNode):
        return None

    data = executor.catalog.get_table(scan.catalog, scan.schema_name,
                                      scan.table)
    chunk_rows = executor.spill_chunk_rows or data.num_rows
    domain = node.build_key_domain
    key_in_scan = node.right_keys[0]

    from ..ops.join import build_lut_chunk
    lut = jnp.full(domain + 1, -1, dtype=jnp.int32)
    cap = bucket_capacity(min(chunk_rows, data.num_rows))
    expected = jnp.zeros((), dtype=jnp.int64)   # in-domain valid build rows
    oob = jnp.zeros((), dtype=jnp.int64)        # valid keys outside domain
    for start in range(0, data.num_rows, chunk_rows):
        arrays = [np.asarray(data.columns[i])[start:start + chunk_rows]
                  for i in scan.column_indices]
        valids = None
        if data.valids is not None:
            valids = [None if data.valids[i] is None else
                      np.asarray(data.valids[i])[start:start + chunk_rows]
                      for i in scan.column_indices]
        chunk = batch_from_numpy(arrays, valids=valids, capacity=cap)
        if pred is not None:
            from ..ops.project import apply_filter
            chunk = apply_filter(chunk, pred)
        lut, n_in, n_oob = build_lut_chunk(lut, chunk, key_in_scan,
                                           domain, start)
        expected = expected + n_in
        oob = oob + n_oob
        executor.stats.agg_spill_chunks += 1

    # Runtime validation of the planner's uniqueness proof: every resident
    # path checks dup/oob and degrades gracefully; mirror that here. A
    # duplicate build key would silently keep only the max row id, and an
    # out-of-domain key would be clipped into a real slot — both produce
    # wrong answers, so fall back to the resident-build path instead.
    # (occupied-slot counting avoids a second domain-sized count array:
    # dup rows exist iff scattered rows exceed occupied slots.)
    occupied = jnp.sum((lut[:domain] >= 0).astype(jnp.int64))
    expected_h, oob_h, occupied_h = (int(x) for x in
                                     np.asarray(jnp.stack(
                                         (expected, oob, occupied))))
    if oob_h > 0 or occupied_h != expected_h:
        return None

    # probe: global row ids out of the LUT
    pk = probe.columns[node.left_keys[0]]
    p_idx = jnp.where(pk.valid, jnp.clip(pk.data, 0, domain - 1), domain)
    src = lut[p_idx]
    matched = (src >= 0) & pk.valid & probe.live & \
        (pk.data >= 0) & (pk.data < domain)
    if node.kind == "semi":
        return probe.with_live(probe.live & matched)
    if node.kind == "anti":
        return probe.with_live(probe.live & ~matched)

    live = int(jnp.sum(matched))
    new_cap = bucket_capacity(live)
    from .executor import _compact_gather
    probe_plus = Batch(probe.columns + (Column(
        src, matched),), probe.live & matched)
    compacted = _compact_gather(probe_plus, new_cap)
    src_host = np.asarray(compacted.columns[-1].data)
    src_ok = np.asarray(compacted.columns[-1].valid) & \
        np.asarray(compacted.live)
    src_host = np.where(src_ok, src_host, 0)

    # host-side payload gather from the table's mmap'd columns
    out_cols = list(compacted.columns[:-1])
    for j, ti in enumerate(scan.column_indices):
        col_np = np.asarray(data.columns[ti])[src_host]
        valid_np = src_ok.copy()
        if data.valids is not None and data.valids[ti] is not None:
            valid_np &= np.asarray(data.valids[ti])[src_host]
        out_cols.append(Column(jnp.asarray(col_np),
                               jnp.asarray(valid_np)))
    return Batch(tuple(out_cols), compacted.live)


def merge_partials(executor, node: L.AggregateNode,
                   partials: List[Batch]) -> Batch:
    """FINAL step: concat partial states, re-aggregate with merge
    functions over the partial layout (keys at 0..n_keys-1, states
    after). Hash-strategy operators merge through the hash-partial
    path (executor.merge_group_aggregate) instead of the sort merge."""
    from ..ops.aggregate import AggSpec, global_aggregate
    from .executor import concat_batches

    merged = partials[0]
    for p in partials[1:]:
        merged = concat_batches(merged, p)
    n_keys = len(node.group_keys)
    merge_aggs = tuple(AggSpec(MERGE_FUNC[a.func], n_keys + j)
                       for j, a in enumerate(node.aggs))
    if node.strategy == "global":
        return global_aggregate(merged, merge_aggs)
    capacity = max(node.out_capacity, bucket_capacity(
        int(np.asarray(merged.live).sum())))
    return executor.merge_group_aggregate(node, merged, merge_aggs,
                                          capacity)
