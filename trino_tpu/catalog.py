"""Catalog registry — maps catalog names to connectors.

Reference: Trino's CatalogManager / connector loading
(metadata/CatalogManager.java, server/PluginManager.java). Connectors
implement a minimal duck-typed contract for now (schema_names/table_names/
get_table returning host TableData); the split-based scan SPI for
distributed execution layers on top in planner/physical.py.
"""

from __future__ import annotations

from typing import Dict

from .connectors.tpch.connector import TpchConnector


class Catalog:
    def __init__(self):
        self._connectors: Dict[str, object] = {}

    def register(self, name: str, connector) -> None:
        self._connectors[name] = connector

    def connector(self, name: str):
        if name not in self._connectors:
            raise KeyError(f"catalog {name!r} not found "
                           f"(have {sorted(self._connectors)})")
        return self._connectors[name]

    def get_table(self, catalog: str, schema: str, table: str):
        return self.connector(catalog).get_table(schema, table)


def default_catalog() -> Catalog:
    cat = Catalog()
    cat.register("tpch", TpchConnector())
    from .connectors.tpcds.connector import TpcdsConnector
    cat.register("tpcds", TpcdsConnector())
    from .connectors.memory import MemoryConnector
    cat.register("memory", MemoryConnector())
    return cat
