"""Catalog registry — maps catalog names to connectors.

Reference: Trino's CatalogManager / connector loading
(metadata/CatalogManager.java, server/PluginManager.java). Connectors
implement a minimal duck-typed contract for now (schema_names/table_names/
get_table returning host TableData); the split-based scan SPI for
distributed execution layers on top in planner/physical.py.
"""

from __future__ import annotations

from typing import Dict

from .connectors.tpch.connector import TpchConnector


class Catalog:
    def __init__(self):
        self._connectors: Dict[str, object] = {}
        self._stats_cache: Dict[tuple, object] = {}
        # monotonic catalog version: bumped on every DDL/write that goes
        # through the session (CREATE/DROP/INSERT/UPDATE/DELETE/MERGE).
        # The serving layer stamps every cached plan and result page with
        # the version it observed, so a write invalidates them all
        # without enumerating which tables changed.
        self.version = 0

    def bump_version(self) -> None:
        self.version += 1
        # table contents moved: cached plan-time stats are stale too
        self._stats_cache.clear()

    def register(self, name: str, connector) -> None:
        self._connectors[name] = connector

    def connector(self, name: str):
        if name not in self._connectors:
            raise KeyError(f"catalog {name!r} not found "
                           f"(have {sorted(self._connectors)})")
        return self._connectors[name]

    def get_table(self, catalog: str, schema: str, table: str):
        if schema == "information_schema":
            return self.information_schema_table(catalog, table)
        return self.connector(catalog).get_table(schema, table)

    def get_table_stats(self, catalog: str, schema: str, table: str):
        """TableStats for an already-materialized table, else None —
        plan-time stats must never trigger SF1000 generation
        (spi/statistics ConnectorTableStatistics role, cached)."""
        key = (catalog, schema, table)
        if key in self._stats_cache:
            return self._stats_cache[key]
        try:
            conn = self.connector(catalog)
            if hasattr(conn, "scale_for_schema"):
                # generator connectors: only stats for materialized scales
                scale = conn.scale_for_schema(schema)
                data = conn._cache.get(scale, {}).get(table)
            else:
                data = conn.get_table(schema, table)
        except Exception:
            data = None
        if data is None:
            return None
        from .stats import compute_table_stats
        stats = compute_table_stats(data)
        self._stats_cache[key] = stats
        return stats

    def information_schema_table(self, catalog: str, table: str):
        """Synthesize information_schema.{schemata,tables,columns} from
        connector metadata (reference: the engine-provided
        information_schema connector, connector/informationschema/)."""
        conn = self.connector(catalog)
        if table == "schemata":
            names = list(conn.schema_names())
            return _strings_table("schemata",
                                  [("catalog_name", [catalog] * len(names)),
                                   ("schema_name", names)])
        if table == "tables":
            cats, schemas, tables = [], [], []
            for s in conn.schema_names():
                for t in conn.table_names(s):
                    cats.append(catalog)
                    schemas.append(s)
                    tables.append(t)
            return _strings_table("tables",
                                  [("table_catalog", cats),
                                   ("table_schema", schemas),
                                   ("table_name", tables)])
        if table == "columns":
            get_schema = getattr(conn, "get_table_schema",
                                 lambda s, t: conn.get_table(s, t).schema)
            schemas, tables, cols, types, positions = [], [], [], [], []
            for s in conn.schema_names():
                for t in conn.table_names(s):
                    table_schema = get_schema(s, t)
                    for i, f in enumerate(table_schema):
                        schemas.append(s)
                        tables.append(t)
                        cols.append(f.name)
                        types.append(str(f.dtype))
                        positions.append(i + 1)
            out = _strings_table("columns",
                                 [("table_schema", schemas),
                                  ("table_name", tables),
                                  ("column_name", cols),
                                  ("data_type", types)])
            import numpy as np
            from .batch import Field, Schema
            from .types import BIGINT
            return type(out)(
                "columns",
                Schema(out.schema.fields + (Field("ordinal_position",
                                                  BIGINT),)),
                out.columns + [np.asarray(positions, dtype=np.int64)])
        raise KeyError(f"information_schema table {table!r} not found")


def _strings_table(name: str, cols):
    """Build a TableData of VARCHAR columns from python string lists."""
    import numpy as np
    from .batch import Field, Schema
    from .connectors.tpch.datagen import TableData
    from .types import VARCHAR
    fields = []
    arrays = []
    for col_name, values in cols:
        pool = sorted(set(values))
        index = {s: i for i, s in enumerate(pool)}
        fields.append(Field(col_name, VARCHAR, dictionary=tuple(pool)))
        arrays.append(np.array([index[v] for v in values],
                               dtype=np.int32))
    return TableData(name, Schema(tuple(fields)), arrays)


def default_catalog() -> Catalog:
    cat = Catalog()
    cat.register("tpch", TpchConnector())
    from .connectors.tpcds.connector import TpcdsConnector
    cat.register("tpcds", TpcdsConnector())
    from .connectors.memory import MemoryConnector
    cat.register("memory", MemoryConnector())
    return cat
