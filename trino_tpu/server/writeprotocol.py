"""Exactly-once staged-write commit protocol for directory connectors.

The fault-tolerant-execution write path (the role of Trino's
TableWriterOperator + TableFinishOperator under task-level retries):
worker write tasks stage output to uniquely-named attempt files under
`<table>/.staging/` and only *report* a manifest — publication is the
coordinator's job. The coordinator dedups manifests by (stage, partition)
with first-success-wins, records a CRC-framed, fsync'd commit journal in
the table directory, publishes each staged file by atomic rename, then
removes the journal. Replaying any prefix of that sequence is idempotent:

  crash before the INTENT record is durable  -> roll back (sweep staging)
  crash after INTENT, before all renames     -> roll forward (finish renames)
  crash after COMMIT record                  -> cleanup only

Published part files carry the committing query's token and row count in
their names (`part-00000-<qtok>-r123.orc`), so a whole-query retry that
finds its own parts already published returns success without re-staging —
the commit point is the INTENT record, exactly once per query id.

Single-writer-per-table is assumed (the coordinator serializes DDL/DML on
one exec lock; the session-local path is in-process); recovery additionally
runs on connector startup so an unclean shutdown can never leak staging
files, journals, or torn tables.
"""

import json
import logging
import os
import re
import struct

from ..metrics import (WRITE_COMMITS, WRITE_ORPHANS_SWEPT, WRITE_TASKS)
from ..utils.atomicio import fsync_dir
from ..utils.log import query_context
from .failureinjector import WRITE_COMMIT, WRITE_PUBLISH, WRITE_STAGE
from .pageserde import _crc32c

log = logging.getLogger("trino_tpu.write")

STAGING_DIR = ".staging"
JOURNAL_MAGIC = b"TWJ1"
_PART_RE = re.compile(r"^part-(\d+)-([0-9a-f]+)-r(\d+)\.(orc|parquet)$")


def qtoken(query_id: str) -> str:
    """Filesystem-safe token for a query id (stable across retries of
    the same query — that stability is what makes commit exactly-once)."""
    return format(_crc32c(query_id.encode()) & 0xFFFFFFFF, "08x")


def staging_dir(table_dir: str) -> str:
    return os.path.join(table_dir, STAGING_DIR)


def journal_path(table_dir: str, query_id: str) -> str:
    return os.path.join(table_dir, f".commit_{qtoken(query_id)}.journal")


def attempt_filename(query_id: str, stage: int, partition: int,
                     attempt: str, ext: str) -> str:
    return f"{qtoken(query_id)}_{stage}_{partition}_{attempt}.{ext}"


def part_filename(seq: int, qtok: str, rows: int, ext: str) -> str:
    return f"part-{seq:05d}-{qtok}-r{rows}.{ext}"


def list_parts(table_dir: str):
    """Published part files, in deterministic (sequence) order."""
    if not os.path.isdir(table_dir):
        return []
    out = []
    for f in os.listdir(table_dir):
        m = _PART_RE.match(f)
        if m:
            out.append((int(m.group(1)), f))
    return [f for _, f in sorted(out)]


def published_rows_for(table_dir: str, query_id: str):
    """If parts published by `query_id` exist, their total row count —
    the signal that a prior attempt already committed. None otherwise."""
    tok = qtoken(query_id)
    rows, seen = 0, False
    for f in list_parts(table_dir):
        m = _PART_RE.match(f)
        if m and m.group(2) == tok:
            seen = True
            rows += int(m.group(3))
    return rows if seen else None


# --------------------------------------------------------------------------
# staging (worker side)
# --------------------------------------------------------------------------

def stage_table_data(table_dir: str, data, query_id: str, stage: int,
                     partition: int, attempt: str, fmt: str,
                     injector=None) -> dict:
    """Write one attempt file under `<table>/.staging/` and return its
    manifest (path, rows, CRC, bytes, per-column zone stats). Never
    publishes — the file is invisible to scans until the coordinator
    commits it."""
    if injector is not None:
        injector.maybe_fail(WRITE_STAGE,
                            f"{query_id}:{stage}:{partition}:{attempt}")
    sdir = staging_dir(table_dir)
    os.makedirs(sdir, exist_ok=True)
    ext = "orc" if fmt == "orc" else "parquet"
    path = os.path.join(sdir, attempt_filename(query_id, stage, partition,
                                               attempt, ext))
    if fmt == "orc":
        from ..connectors.orcdir import export_table
    else:
        from ..connectors.parquetdir import export_table
    export_table(data, path)
    with open(path, "rb") as f:
        body = f.read()
    WRITE_TASKS.inc()
    return {
        "path": path,
        "rows": int(data.num_rows),
        "bytes": len(body),
        "crc": _crc32c(body) & 0xFFFFFFFF,
        "stage": stage,
        "partition": partition,
        "attempt": attempt,
        "zones": _zone_stats(data),
    }


def _zone_stats(data) -> dict:
    """min/max per numeric column — the manifest's zone-map stats (the
    file's own stripe/chunk statistics back actual scan pruning; these
    feed observability and the commit journal)."""
    import numpy as np
    out = {}
    for i, f in enumerate(data.schema):
        col = np.asarray(data.columns[i])
        if col.size == 0 or not (np.issubdtype(col.dtype, np.integer)
                                 or np.issubdtype(col.dtype, np.floating)):
            continue
        valid = None if data.valids is None else data.valids[i]
        vals = col if valid is None else col[np.asarray(valid)]
        if vals.size:
            out[f.name] = [float(vals.min()), float(vals.max())]
    return out


# --------------------------------------------------------------------------
# journal (CRC-framed, fsync'd, torn-tail tolerant)
# --------------------------------------------------------------------------

def _frame(rec: dict) -> bytes:
    body = json.dumps(rec, sort_keys=True).encode()
    return (JOURNAL_MAGIC + struct.pack("<I", _crc32c(body) & 0xFFFFFFFF)
            + struct.pack("<I", len(body)) + body)


def append_journal(path: str, rec: dict, injector=None,
                   key: str = "") -> None:
    """Append one CRC-framed record and fsync file + directory. The
    CORRUPT fault at WRITE_COMMIT truncates the frame mid-write — the
    torn-journal case replay must tolerate."""
    frame = _frame(rec)
    torn = False
    if injector is not None:
        try:
            frame2 = injector.corrupt_page(WRITE_COMMIT, key, frame)
            if frame2 is not frame and frame2 != frame:
                # model a torn append: a prefix of the record hits disk
                frame, torn = frame[:max(4, len(frame) // 2)], True
        except AttributeError:
            pass
    with open(path, "ab") as f:
        f.write(frame)
        f.flush()
        os.fsync(f.fileno())
    fsync_dir(os.path.dirname(os.path.abspath(path)))
    if torn:
        from .failureinjector import InjectedCrash
        raise InjectedCrash(f"torn journal append at {path}")


def replay_journal(path: str):
    """Decode journal records, stopping cleanly at the first torn or
    corrupt frame. Returns (records, torn_tail)."""
    try:
        with open(path, "rb") as f:
            buf = f.read()
    except OSError:
        return [], False
    recs, off = [], 0
    while off < len(buf):
        if buf[off:off + 4] != JOURNAL_MAGIC or off + 12 > len(buf):
            return recs, True
        crc, ln = struct.unpack_from("<II", buf, off + 4)
        body = buf[off + 12:off + 12 + ln]
        if len(body) != ln or (_crc32c(body) & 0xFFFFFFFF) != crc:
            return recs, True
        try:
            recs.append(json.loads(body.decode()))
        except ValueError:
            return recs, True
        off += 12 + ln
    return recs, False


# --------------------------------------------------------------------------
# commit (coordinator side)
# --------------------------------------------------------------------------

def dedup_manifests(manifests):
    """First-success-wins by (stage, partition): scheduler retries and
    straggler hedges can report duplicate attempts for one partition;
    exactly one may publish. Returns (chosen, n_deduped)."""
    chosen, deduped = {}, 0
    for m in manifests:
        key = (m["stage"], m["partition"])
        if key in chosen:
            deduped += 1
        else:
            chosen[key] = m
    ordered = [chosen[k] for k in sorted(chosen)]
    return ordered, deduped


def commit(table_dir: str, query_id: str, manifests, injector=None,
           tracer=None) -> dict:
    """Publish deduped staged files transactionally. The INTENT journal
    record (durable before any rename) is the commit point: recovery
    rolls the full rename set forward from it; without it, staged files
    are swept. Idempotent per query id. `tracer`, when given, nests
    write-publish / write-sweep child spans under the caller's
    write-commit span so the commit's phases show in the query trace."""
    from ..utils.tracing import NOOP
    tracer = tracer or NOOP
    chosen, deduped = dedup_manifests(manifests)
    tok = qtoken(query_id)
    if injector is not None:
        injector.maybe_fail(WRITE_COMMIT, query_id)
    already = published_rows_for(table_dir, query_id)
    if already is not None:          # prior attempt already committed
        sweep_query(table_dir, query_id)
        return {"published": 0, "rows": already, "deduped": deduped,
                "bytes": 0, "phase": "committed"}
    seq0 = len(list_parts(table_dir))
    files = []
    for i, m in enumerate(chosen):
        ext = os.path.splitext(m["path"])[1].lstrip(".")
        files.append({"src": m["path"],
                      "dst": os.path.join(table_dir, part_filename(
                          seq0 + i, tok, m["rows"], ext)),
                      "rows": m["rows"], "crc": m["crc"],
                      "zones": m.get("zones", {})})
    jpath = journal_path(table_dir, query_id)
    append_journal(jpath, {"rec": "intent", "query": query_id,
                           "files": [{k: f[k] for k in
                                      ("src", "dst", "rows", "crc")}
                                     for f in files]},
                   injector=injector, key=query_id)
    # ---- point of no return: roll forward from here ----
    with tracer.span("write-publish", files=len(files)):
        for f in files:
            if injector is not None:
                injector.maybe_fail(WRITE_PUBLISH, f["dst"])
            _publish_one(f["src"], f["dst"])
        fsync_dir(table_dir)
        append_journal(jpath, {"rec": "commit", "query": query_id})
    with tracer.span("write-sweep"):
        sweep_query(table_dir, query_id)
        try:
            os.unlink(jpath)
        except OSError:
            pass
        fsync_dir(table_dir)
    WRITE_COMMITS.inc(outcome="committed")
    rows = sum(f["rows"] for f in files)
    log.info("%scommitted %d parts (%d rows, %d deduped) in %s",
             query_context(query_id), len(files), rows, deduped, table_dir)
    return {"published": len(files), "deduped": deduped,
            "rows": rows,
            "bytes": sum(m["bytes"] for m in chosen),
            "phase": "committed"}


def _publish_one(src: str, dst: str) -> None:
    if os.path.exists(src):
        os.replace(src, dst)
    elif not os.path.exists(dst):
        raise IOError(f"write commit lost {src} (and {dst} absent)")


def abort(table_dir: str, query_id: str) -> None:
    """Abandon a write that never reached its INTENT record: sweep this
    query's staging attempts and any torn journal."""
    recs, _ = replay_journal(journal_path(table_dir, query_id))
    if any(r.get("rec") == "intent" for r in recs):
        # intent is durable: the write must roll forward, not abort
        recover_table_dir(table_dir)
        return
    n = sweep_query(table_dir, query_id)
    try:
        os.unlink(journal_path(table_dir, query_id))
        n += 1
    except OSError:
        pass
    if n:
        WRITE_ORPHANS_SWEPT.inc(n)
    WRITE_COMMITS.inc(outcome="aborted")
    log.info("%saborted write: swept %d staging artifacts in %s",
             query_context(query_id), n, table_dir)


def sweep_query(table_dir: str, query_id: str) -> int:
    """Remove this query's staging attempts (all of them — duplicates
    from hedged attempts included)."""
    sdir = staging_dir(table_dir)
    tok = qtoken(query_id)
    n = 0
    if os.path.isdir(sdir):
        for f in os.listdir(sdir):
            if f.startswith(f"{tok}_"):
                try:
                    os.unlink(os.path.join(sdir, f))
                    n += 1
                except OSError:
                    pass
        _rmdir_if_empty(sdir)
    return n


def _rmdir_if_empty(d: str) -> None:
    try:
        os.rmdir(d)
    except OSError:
        pass


# --------------------------------------------------------------------------
# recovery (abort path + connector startup)
# --------------------------------------------------------------------------

def recover_table_dir(table_dir: str) -> dict:
    """Replay any journals in a table directory and finish or undo the
    protocol: durable INTENT -> roll the renames forward; torn or absent
    INTENT -> roll back. Then sweep all remaining staging files and temp
    names. Idempotent — safe to run any number of times, after a crash
    at any point."""
    out = {"rolled_forward": 0, "swept": 0}
    if not os.path.isdir(table_dir):
        return out
    for jf in sorted(os.listdir(table_dir)):
        if not jf.endswith(".journal"):
            continue
        jpath = os.path.join(table_dir, jf)
        recs, _torn = replay_journal(jpath)
        intent = next((r for r in recs if r.get("rec") == "intent"), None)
        if intent is not None:
            for f in intent["files"]:
                _publish_one(f["src"], f["dst"])
                out["rolled_forward"] += 1
            fsync_dir(table_dir)
        try:
            os.unlink(jpath)
            out["swept"] += 1
        except OSError:
            pass
    sdir = staging_dir(table_dir)
    if os.path.isdir(sdir):
        for f in os.listdir(sdir):
            try:
                os.unlink(os.path.join(sdir, f))
                out["swept"] += 1
            except OSError:
                pass
        _rmdir_if_empty(sdir)
    for f in os.listdir(table_dir):
        if f.startswith(".tmp."):
            try:
                os.unlink(os.path.join(table_dir, f))
                out["swept"] += 1
            except OSError:
                pass
    if out["swept"]:
        WRITE_ORPHANS_SWEPT.inc(out["swept"])
    return out


def sweep_root(root: str) -> dict:
    """Connector-startup sweep: recover every table directory under
    `<root>/<schema>/` so no crash can leak staging state or a torn
    journal into a serving connector."""
    total = {"rolled_forward": 0, "swept": 0}
    if not os.path.isdir(root):
        return total
    for schema in os.listdir(root):
        sdir = os.path.join(root, schema)
        if not os.path.isdir(sdir):
            continue
        for entry in os.listdir(sdir):
            tdir = os.path.join(sdir, entry)
            if not os.path.isdir(tdir):
                continue
            r = recover_table_dir(tdir)
            total["rolled_forward"] += r["rolled_forward"]
            total["swept"] += r["swept"]
            # a rolled-back CTAS can leave an empty table dir behind
            _rmdir_if_empty(tdir)
    return total
