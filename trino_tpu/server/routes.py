"""Declarative HTTP route tables for the coordinator and worker servers.

Reference: the reference engine binds REST resources declaratively (JAX-RS
annotations on QueuedStatementResource / TaskResource / QueryResource), so
its route inventory is introspectable. The round-7 handlers here had grown
if/elif chains instead — invisible to metrics and impossible to lint. Every
`/v1/...` route now lives in a module-level ROUTES table:

    (METHOD, pattern, handler_method_name, needs_auth)

where `pattern` is a tuple of path segments and STAR matches any single
segment. `needs_auth` is False (open), True (end-user authentication via
the server's authenticator), or "internal" (cluster-membership routes:
the shared-secret header TRINO_TPU_INTERNAL_SECRET configures — worker
task/exchange routes and the coordinator announce route reject callers
without it with 401). `dispatch()` is the entire body of each
do_GET/do_POST/...: match, count the request in
trino_tpu_http_requests_total{server,route}, enforce auth, call the
handler. Adding a route therefore *cannot* skip the metrics surface, and
tier-1 lints exactly that (tests/test_metrics_lint.py: handlers may not
contain inline path literals; every table entry must have a
pre-initialized counter sample).
"""

from __future__ import annotations

from typing import Tuple

STAR = "*"


def route_label(method: str, pattern: Tuple[str, ...]) -> str:
    """Stable metrics label, e.g. 'GET /v1/task/*/results/*'."""
    return method + " /" + "/".join(pattern)


def match(pattern: Tuple[str, ...], parts: Tuple[str, ...]) -> bool:
    return len(pattern) == len(parts) and all(
        p == STAR or p == s for p, s in zip(pattern, parts))


def register_routes(server_name: str, routes) -> None:
    """Pre-initialize every route's request counter so a cold server's
    /v1/metrics already lists its full route inventory at 0."""
    from ..metrics import HTTP_REQUESTS
    for method, pattern, *_ in routes:
        HTTP_REQUESTS.init_labels(server=server_name,
                                  route=route_label(method, pattern))


def dispatch(handler, method: str, routes, server_name: str) -> None:
    """Generic request dispatcher (the whole body of a do_* method)."""
    from urllib.parse import urlparse

    from ..metrics import HTTP_REQUESTS
    path = urlparse(handler.path).path
    parts = tuple(p for p in path.split("/") if p)
    for m, pattern, fn_name, needs_auth in routes:
        if m != method or not match(pattern, parts):
            continue
        HTTP_REQUESTS.inc(server=server_name,
                          route=route_label(m, pattern))
        user = None
        if needs_auth == "internal":
            from .security import check_internal_request
            if not check_internal_request(handler.headers):
                handler._send(401, {"error": {
                    "message": "cluster-internal route: missing or "
                               "invalid internal secret",
                    "errorName": "AUTHENTICATION_FAILED"}})
                return
            user = "internal"
        elif needs_auth:
            user = handler._authenticate()
            if user is None:
                return           # 401 already sent
        getattr(handler, fn_name)(parts, user)
        return
    HTTP_REQUESTS.inc(server=server_name, route=f"{method} unmatched")
    handler._not_found(path)
