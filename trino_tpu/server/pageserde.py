"""Binary page serde for the data plane.

Reference: Trino ships exchange pages as length-prefixed binary frames
with optional LZ4/ZSTD compression
(core/trino-main/.../execution/buffer/CompressingEncryptingPageSerializer.java:60,
PagesSerdeUtil). Round-3 shipped base64-in-JSON — fine for correctness,
hopeless for SF100 shuffles — this module is the binary replacement.

Frame layout (little-endian):

    magic  b"TPG1"
    flags  u8      bit0: body zstd-compressed, bit1: zlib-compressed
    rawlen u64     uncompressed body length
    body   bytes   (compressed per flags)

Body:

    ncols  u16
    rows   u64
    per column:
        dlen   u8   dtype string length
        dtype  ascii
        nbytes u64  data byte length
        data   bytes
        vbytes u64  validity byte length (bool_, rows entries)
        valid  bytes

Decoding attacker-controlled bytes can at worst produce malformed numpy
arrays — no object deserialization (same data-only property as
server/serde.py).
"""

from __future__ import annotations

import struct
import zlib
from typing import List, Tuple

import numpy as np

MAGIC = b"TPG1"
_F_ZSTD = 1
_F_ZLIB = 2

try:
    import zstandard as _zstd
except Exception:                        # pragma: no cover — zstd absent
    _zstd = None

# zstd (de)compression CONTEXTS are not thread-safe, and pages flow on
# many threads at once (worker task threads, exchange-consumer pulls,
# coordinator drains) — sharing one context corrupts frames under
# concurrency (observed: intermittent ZstdError in the partitioned
# exchange). Keep one context per thread.
import threading as _threading

_tls = _threading.local()


def _zc():
    if _zstd is None:
        return None
    c = getattr(_tls, "zc", None)
    if c is None:
        c = _tls.zc = _zstd.ZstdCompressor(level=3)
    return c


def _zd():
    if _zstd is None:
        return None
    d = getattr(_tls, "zd", None)
    if d is None:
        d = _tls.zd = _zstd.ZstdDecompressor()
    return d

# frames smaller than this ship uncompressed (header cost dominates)
MIN_COMPRESS = 512


def encode_page(arrays: List[np.ndarray],
                valids: List[np.ndarray]) -> bytes:
    rows = len(arrays[0]) if arrays else 0
    parts = [struct.pack("<HQ", len(arrays), rows)]
    for a, v in zip(arrays, valids):
        a = np.ascontiguousarray(a)
        v = np.ascontiguousarray(np.asarray(v, dtype=np.bool_))
        dt = str(a.dtype).encode("ascii")
        parts.append(struct.pack("<B", len(dt)))
        parts.append(dt)
        ab = a.tobytes()
        parts.append(struct.pack("<Q", len(ab)))
        parts.append(ab)
        vb = v.tobytes()
        parts.append(struct.pack("<Q", len(vb)))
        parts.append(vb)
    body = b"".join(parts)
    flags = 0
    if len(body) >= MIN_COMPRESS:
        zc = _zc()
        if zc is not None:
            comp = zc.compress(body)
            if len(comp) < len(body):
                body, flags = comp, _F_ZSTD
        else:
            comp = zlib.compress(body, 1)
            if len(comp) < len(body):
                body, flags = comp, _F_ZLIB
    return MAGIC + struct.pack("<BQ", flags, len(body)) + body


def decode_page(buf: bytes) -> Tuple[List[np.ndarray], List[np.ndarray]]:
    if buf[:4] != MAGIC:
        raise ValueError("bad page frame magic")
    flags, rawlen = struct.unpack_from("<BQ", buf, 4)
    body = buf[13:13 + rawlen]
    if flags & _F_ZSTD:
        zd = _zd()
        if zd is None:
            raise ValueError("zstd page but zstandard unavailable")
        body = zd.decompress(body)
    elif flags & _F_ZLIB:
        body = zlib.decompress(body)
    off = 0
    ncols, rows = struct.unpack_from("<HQ", body, off)
    off += 10
    arrays, valids = [], []
    for _ in range(ncols):
        (dlen,) = struct.unpack_from("<B", body, off)
        off += 1
        dt = np.dtype(body[off:off + dlen].decode("ascii"))
        off += dlen
        (nbytes,) = struct.unpack_from("<Q", body, off)
        off += 8
        arrays.append(np.frombuffer(body, dtype=dt,
                                    count=nbytes // dt.itemsize,
                                    offset=off) if nbytes else
                      np.empty(0, dt))
        off += nbytes
        (vbytes,) = struct.unpack_from("<Q", body, off)
        off += 8
        valids.append(np.frombuffer(body, dtype=np.bool_, count=vbytes,
                                    offset=off) if vbytes else
                      np.empty(0, np.bool_))
        off += vbytes
    return arrays, valids
