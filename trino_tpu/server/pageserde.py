"""Binary page serde for the data plane.

Reference: Trino ships exchange pages as length-prefixed binary frames
with optional LZ4/ZSTD compression
(core/trino-main/.../execution/buffer/CompressingEncryptingPageSerializer.java:60,
PagesSerdeUtil). Round-3 shipped base64-in-JSON — fine for correctness,
hopeless for SF100 shuffles — this module is the binary replacement.

Frame layout (little-endian):

    magic  b"TPG2"
    crc    u32     CRC32C (Castagnoli) of everything after this field
    flags  u8      bit0: body zstd-compressed, bit1: zlib-compressed
    rawlen u64     uncompressed body length
    body   bytes   (compressed per flags)

The checksum covers flags + rawlen + body, so a bit flip anywhere past
the magic — in transit, in a spool file, in a worker's output buffer —
is detected at decode/verify time and surfaces as PageChecksumError,
which the exchange layers convert into a retryable task failure instead
of silently wrong results (the reference's
CompressingEncryptingPageSerializer checksum word plays the same role).
Legacy b"TPG1" frames (round-5, no checksum) still decode — rolling
upgrade, same policy as the base64-dict fallback in tasks.py.

Body:

    ncols  u16
    rows   u64
    per column:
        dlen   u8   dtype string length
        dtype  ascii
        nbytes u64  data byte length
        data   bytes
        vbytes u64  validity byte length (bool_, rows entries)
        valid  bytes

Decoding attacker-controlled bytes can at worst produce malformed numpy
arrays — no object deserialization (same data-only property as
server/serde.py).
"""

from __future__ import annotations

import struct
import zlib
from typing import List, Tuple

import numpy as np

MAGIC = b"TPG2"
MAGIC_V1 = b"TPG1"        # legacy checksum-free frames (round 5)
_F_ZSTD = 1
_F_ZLIB = 2


class PageChecksumError(ValueError):
    """Frame failed its CRC32C integrity check (or is truncated/garbled).
    Retryable: the holder of the frame re-fetches or re-runs the work.

    Every raise is counted in the process metrics registry
    (trino_tpu_pageserde_crc_failures_total) so corruption seen at any
    verify site — coordinator drain, spool read, worker<->worker pull —
    is visible on /v1/metrics without log spelunking."""

    def __init__(self, *args):
        super().__init__(*args)
        from ..metrics import PAGE_CRC_FAILURES
        PAGE_CRC_FAILURES.inc()


try:
    import google_crc32c as _gcrc

    def _crc32c(*chunks) -> int:
        c = 0
        for ch in chunks:
            c = _gcrc.extend(c, bytes(ch))
        return c
except Exception:                    # pragma: no cover — lib absent
    # zlib's CRC-32 (0x04C11DB7) as a stand-in: same 32-bit guarantees
    # (all 1-2 bit errors, bursts <= 32), just not the Castagnoli
    # polynomial. Frames never cross processes with mismatched builds
    # (one container image), so the choice only needs to be consistent.
    def _crc32c(*chunks) -> int:
        c = 0
        for ch in chunks:
            c = zlib.crc32(ch, c)
        return c & 0xFFFFFFFF

try:
    import zstandard as _zstd
except Exception:                        # pragma: no cover — zstd absent
    _zstd = None

# zstd (de)compression CONTEXTS are not thread-safe, and pages flow on
# many threads at once (worker task threads, exchange-consumer pulls,
# coordinator drains) — sharing one context corrupts frames under
# concurrency (observed: intermittent ZstdError in the partitioned
# exchange). Keep one context per thread.
import threading as _threading

_tls = _threading.local()


def _zc():
    if _zstd is None:
        return None
    c = getattr(_tls, "zc", None)
    if c is None:
        c = _tls.zc = _zstd.ZstdCompressor(level=3)
    return c


def _zd():
    if _zstd is None:
        return None
    d = getattr(_tls, "zd", None)
    if d is None:
        d = _tls.zd = _zstd.ZstdDecompressor()
    return d

# frames smaller than this ship uncompressed (header cost dominates)
MIN_COMPRESS = 512


def encode_page(arrays: List[np.ndarray],
                valids: List[np.ndarray]) -> bytes:
    rows = len(arrays[0]) if arrays else 0
    parts = [struct.pack("<HQ", len(arrays), rows)]
    for a, v in zip(arrays, valids):
        a = np.ascontiguousarray(a)
        v = np.ascontiguousarray(np.asarray(v, dtype=np.bool_))
        dt = str(a.dtype).encode("ascii")
        parts.append(struct.pack("<B", len(dt)))
        parts.append(dt)
        ab = a.tobytes()
        parts.append(struct.pack("<Q", len(ab)))
        parts.append(ab)
        vb = v.tobytes()
        parts.append(struct.pack("<Q", len(vb)))
        parts.append(vb)
    body = b"".join(parts)
    flags = 0
    if len(body) >= MIN_COMPRESS:
        zc = _zc()
        if zc is not None:
            comp = zc.compress(body)
            if len(comp) < len(body):
                body, flags = comp, _F_ZSTD
        else:
            comp = zlib.compress(body, 1)
            if len(comp) < len(body):
                body, flags = comp, _F_ZLIB
    meta = struct.pack("<BQ", flags, len(body))
    return MAGIC + struct.pack("<I", _crc32c(meta, body)) + meta + body


def verify_page(buf: bytes) -> None:
    """Integrity-check a frame without decompressing or decoding it.

    Raises PageChecksumError on CRC mismatch, truncation, or an
    unrecognizable magic (a flipped magic byte is corruption too).
    Legacy TPG1 frames carry no checksum and pass unverified."""
    if buf[:4] == MAGIC_V1:
        return
    if buf[:4] != MAGIC:
        raise PageChecksumError("bad page frame magic")
    if len(buf) < 17:
        raise PageChecksumError("truncated page frame header")
    (crc,) = struct.unpack_from("<I", buf, 4)
    (_, blen) = struct.unpack_from("<BQ", buf, 8)
    if len(buf) < 17 + blen:
        raise PageChecksumError("truncated page frame body")
    if _crc32c(buf[8:17 + blen]) != crc:
        raise PageChecksumError("page frame CRC32C mismatch")


def decode_page(buf: bytes) -> Tuple[List[np.ndarray], List[np.ndarray]]:
    if buf[:4] == MAGIC:
        verify_page(buf)
        flags, rawlen = struct.unpack_from("<BQ", buf, 8)
        body = buf[17:17 + rawlen]
    elif buf[:4] == MAGIC_V1:
        flags, rawlen = struct.unpack_from("<BQ", buf, 4)
        body = buf[13:13 + rawlen]
    else:
        raise ValueError("bad page frame magic")
    if flags & _F_ZSTD:
        zd = _zd()
        if zd is None:
            raise ValueError("zstd page but zstandard unavailable")
        body = zd.decompress(body)
    elif flags & _F_ZLIB:
        body = zlib.decompress(body)
    off = 0
    ncols, rows = struct.unpack_from("<HQ", body, off)
    off += 10
    arrays, valids = [], []
    for _ in range(ncols):
        (dlen,) = struct.unpack_from("<B", body, off)
        off += 1
        dt = np.dtype(body[off:off + dlen].decode("ascii"))
        off += dlen
        (nbytes,) = struct.unpack_from("<Q", body, off)
        off += 8
        arrays.append(np.frombuffer(body, dtype=dt,
                                    count=nbytes // dt.itemsize,
                                    offset=off) if nbytes else
                      np.empty(0, dt))
        off += nbytes
        (vbytes,) = struct.unpack_from("<Q", body, off)
        off += 8
        valids.append(np.frombuffer(body, dtype=np.bool_, count=vbytes,
                                    offset=off) if vbytes else
                      np.empty(0, np.bool_))
        off += vbytes
    return arrays, valids
