"""High-concurrency serving layer: plan cache, result cache,
cost-based CPU/TPU routing, micro-batched point-query dispatch.

Reference: the reference engine's prepared-statement machinery and the
co-processing literature (PAPERS.md "Revisiting Co-Processing for Hash
Joins on the Coupled CPU-GPU Architecture"). "Millions of users" means
thousands of small concurrent statements, and the bench shows the
device is the wrong place for them (q6 SF1: ~10 ms of device compute
behind one 100-260 ms tunnel RTT). Four cooperating parts:

1. **Plan cache** — LRU + byte-capped map from the normalized-SQL plan
   fingerprint (server/history.py plan_fingerprint) to the planned +
   pruned logical tree, so repeated statements skip parse/plan
   entirely. Keyed additionally by the session-property digest and the
   catalog version (DDL invalidates). Served as
   ``system.runtime.plan_cache``.

2. **Result cache** — FINISHED query pages keyed the same way, stamped
   with the catalog version observed at execution start; any DDL/write
   bumps the monotonic counter (catalog.py) and stale entries count as
   invalidations. Opt-in via ``enable_result_cache``; plans that scan
   volatile catalogs (system / information_schema) or embed
   non-deterministic subplans are never cached.

3. **Cost router** (exec/router.py) — small/point queries execute on
   the host numpy path WITHOUT the coordinator's device exec lock;
   scan-heavy plans keep the device. Per-route counters + an EXPLAIN
   annotation.

4. **Micro-batcher** — concurrent point queries that share a plan shape
   and differ only in one equality literal gather behind a short window
   and execute as ONE dispatch (``k = ?`` -> ``k IN (...)`` with the
   key column appended), then demultiplex to their clients.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..exec.router import (HostUnsupported, TenantFairShare, decide_route,
                           host_supported, run_host)
from ..planner import logical as L
from ..planner.optimizer import prune_plan
from ..sql import ast_nodes as A
from ..sql.parser import parse
from ..utils.log import tq_context
from .history import plan_fingerprint

log = logging.getLogger("trino_tpu.serving")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _subtree_nodes(node):
    yield node
    for c in L.children(node):
        yield from _subtree_nodes(c)


def _plan_scans(root):
    """Every ScanNode reachable from the plan, INCLUDING subplans
    embedded in expressions (scalar/IN subqueries) — the result cache's
    volatility check must see through them."""
    from .. import ir
    todo = [root]
    while todo:
        node = todo.pop()
        for n in _subtree_nodes(node):
            if isinstance(n, L.ScanNode):
                yield n
            for e in _node_exprs(n):
                for sub in ir.walk(e):
                    plan = getattr(sub, "plan", None)
                    if isinstance(plan, L.PlanNode):
                        todo.append(plan)


def _node_exprs(node):
    if isinstance(node, L.FilterNode):
        return (node.predicate,)
    if isinstance(node, L.ProjectNode):
        return node.exprs
    if isinstance(node, L.AggregateNode):
        return tuple(a.arg for a in node.aggs if a.arg is not None)
    return ()


def _plan_weight(root, sql: str) -> int:
    """Rough retained-bytes estimate for the byte cap (node count drives
    the tree size; the SQL text rides along for the system table)."""
    return sum(1 for _ in _subtree_nodes(root)) * 512 + 2 * len(sql)


def _result_weight(rows) -> int:
    if not rows:
        return 64
    sample = rows[:64]
    per = sum(sum(len(v) if isinstance(v, str) else 16 for v in r) + 48
              for r in sample) / len(sample)
    return int(per * len(rows)) + 64


# --------------------------------------------------------------------------
# plan cache
# --------------------------------------------------------------------------

@dataclasses.dataclass
class PlanEntry:
    sql: str
    fingerprint: str
    stmt: object                       # parsed AST (Query/SetOp/Values)
    rel: object                        # PlannedRelation (decode scope)
    root: object                       # pruned L.OutputNode
    cacheable: bool                    # result-cache eligible
    point_shape: Optional[tuple]       # (shape_key, key_ident, lit_text)
    catalog_version: int = 0           # version the plan was built at
    weight: int = 0
    hits: int = 0
    created_at: float = 0.0


class PlanCache:
    """LRU + byte-capped logical-plan cache keyed by (fingerprint,
    session-property digest, catalog version)."""

    def __init__(self, max_entries: Optional[int] = None,
                 max_bytes: Optional[int] = None):
        self.max_entries = max_entries if max_entries is not None else \
            _env_int("TRINO_TPU_PLAN_CACHE_ENTRIES", 512)
        self.max_bytes = max_bytes if max_bytes is not None else \
            _env_int("TRINO_TPU_PLAN_CACHE_BYTES", 64 << 20)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, PlanEntry]" = OrderedDict()
        self._bytes = 0

    def get(self, key: tuple) -> Optional[PlanEntry]:
        from ..metrics import PLAN_CACHE_HITS, PLAN_CACHE_MISSES
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                PLAN_CACHE_MISSES.inc()
                return None
            self._entries.move_to_end(key)
            entry.hits += 1
        PLAN_CACHE_HITS.inc()
        return entry

    def put(self, key: tuple, entry: PlanEntry) -> None:
        from ..metrics import PLAN_CACHE_EVICTIONS
        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.weight
            self._entries[key] = entry
            self._bytes += entry.weight
            while self._entries and (
                    len(self._entries) > self.max_entries or
                    self._bytes > self.max_bytes):
                if len(self._entries) == 1:
                    break              # never evict the sole entry
                _, dropped = self._entries.popitem(last=False)
                self._bytes -= dropped.weight
                evicted += 1
        if evicted:
            PLAN_CACHE_EVICTIONS.inc(evicted)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [{"fingerprint": e.fingerprint,
                     "sql": e.sql[:120],
                     "hits": e.hits,
                     "weight_bytes": e.weight,
                     "point_shape": e.point_shape is not None,
                     "cacheable": e.cacheable,
                     "created_at": e.created_at}
                    for e in self._entries.values()]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# --------------------------------------------------------------------------
# result cache
# --------------------------------------------------------------------------

@dataclasses.dataclass
class _ResultEntry:
    names: list
    rows: list
    catalog_version: int
    weight: int
    hits: int = 0


class ResultCache:
    """FINISHED-page cache with catalog-version invalidation. Entries
    are immutable snapshots; readers share the row list (the protocol
    layer never mutates results)."""

    def __init__(self, max_bytes: Optional[int] = None,
                 max_entry_bytes: Optional[int] = None):
        self.max_bytes = max_bytes if max_bytes is not None else \
            _env_int("TRINO_TPU_RESULT_CACHE_BYTES", 128 << 20)
        self.max_entry_bytes = max_entry_bytes if max_entry_bytes \
            is not None else _env_int(
                "TRINO_TPU_RESULT_CACHE_ENTRY_BYTES", 8 << 20)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _ResultEntry]" = OrderedDict()
        self._bytes = 0

    def get(self, key: tuple, catalog_version: int):
        from ..metrics import (RESULT_CACHE_HITS,
                               RESULT_CACHE_INVALIDATIONS,
                               RESULT_CACHE_MISSES)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and \
                    entry.catalog_version != catalog_version:
                # a DDL/write bumped the monotonic counter since this
                # page finished: the entry is unservable, drop it
                self._entries.pop(key)
                self._bytes -= entry.weight
                entry = None
                RESULT_CACHE_INVALIDATIONS.inc()
            if entry is None:
                RESULT_CACHE_MISSES.inc()
                return None
            self._entries.move_to_end(key)
            entry.hits += 1
        RESULT_CACHE_HITS.inc()
        return entry

    def put(self, key: tuple, names, rows, catalog_version: int) -> None:
        weight = _result_weight(rows)
        if weight > self.max_entry_bytes:
            return                     # oversized pages never cache
        entry = _ResultEntry(list(names), rows, catalog_version, weight)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.weight
            self._entries[key] = entry
            self._bytes += weight
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                _, dropped = self._entries.popitem(last=False)
                self._bytes -= dropped.weight

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes}

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# --------------------------------------------------------------------------
# point-shape detection + micro-batching
# --------------------------------------------------------------------------

_INT_LIT = re.compile(r"-?\d+$")

_FORBIDDEN_AST = (A.FunctionCall, A.WindowFunc, A.ScalarSubquery,
                  A.InSubquery, A.ExistsPredicate, A.Query)


def _ast_walk(node):
    yield node
    if dataclasses.is_dataclass(node):
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            items = v if isinstance(v, tuple) else (v,)
            for it in items:
                if dataclasses.is_dataclass(it):
                    yield from _ast_walk(it)


def point_shape(stmt) -> Optional[tuple]:
    """(shape_key, key_identifier, literal_text) when the statement is a
    micro-batchable point query: single-table SELECT whose WHERE is one
    integer-literal equality, no aggregation/ordering/limit — the shape
    where ``k = ?`` generalizes to ``k IN (...)`` and rows demultiplex
    by the key value."""
    if not isinstance(stmt, A.Query):
        return None
    if stmt.distinct or stmt.group_by or stmt.having is not None or \
            stmt.order_by or stmt.limit is not None or stmt.ctes or \
            stmt.grouping_sets:
        return None
    if not isinstance(stmt.relation, A.TableRef):
        return None
    w = stmt.where
    if not (isinstance(w, A.BinaryOp) and w.op == "=" and
            isinstance(w.left, A.Identifier) and
            isinstance(w.right, A.NumberLit)):
        return None
    if not _INT_LIT.match(w.right.text.strip()):
        return None
    for item in stmt.select:
        if item.expr is None:
            continue                   # SELECT *: demux column still last
        for n in _ast_walk(item.expr):
            if isinstance(n, _FORBIDDEN_AST):
                return None
    shape = dataclasses.replace(
        stmt, where=dataclasses.replace(w, right=A.NumberLit("?")))
    return (repr(shape), w.left, w.right.text.strip())


class _Window:
    __slots__ = ("members", "closed")

    def __init__(self):
        # each member: (entry, lit_text, Event, box=[result, error])
        self.members: list = []
        self.closed = False


class MicroBatcher:
    """Gather window for same-shape point queries. The first arrival
    for a shape leads: it sleeps out the window (off every lock),
    coalesces followers' literals into one IN-list dispatch, and
    demultiplexes rows back per client."""

    def __init__(self, serving: "ServingLayer"):
        self.serving = serving
        self._lock = threading.Lock()
        self._windows: Dict[str, _Window] = {}

    def submit(self, entry: PlanEntry, tq) -> Optional[object]:
        shape_key, key_ident, lit_text = entry.point_shape
        props = self.serving.session.properties
        window_s = float(props.get("microbatch_window_ms", 4.0)) / 1000.0
        with self._lock:
            w = self._windows.get(shape_key)
            if w is not None and not w.closed:
                box = [None, None]
                ev = threading.Event()
                w.members.append((entry, lit_text, ev, box))
                follower = True
            else:
                w = _Window()
                self._windows[shape_key] = w
                follower = False
        if follower:
            # deadline/cancel-aware wait: capped by the query's wall
            # deadline, polled so a terminate() lands between slices,
            # and degrading to an individual run on a wedged leader
            # instead of failing the query outright
            sm = getattr(tq, "state_machine", None)
            qdl = getattr(tq, "deadline", None)
            bound = time.time() + 60.0
            if qdl is not None:
                bound = min(bound, qdl)
            flushed = ev.wait(timeout=0.05)
            while not flushed and time.time() < bound:
                if sm is not None and sm.is_done():
                    from ..exec.executor import QueryTerminatedError
                    raise QueryTerminatedError(
                        "query terminated while waiting on a "
                        "micro-batch window")
                flushed = ev.wait(timeout=0.05)
            if not flushed:
                from ..metrics import MICROBATCH_FOLLOWER_TIMEOUTS
                MICROBATCH_FOLLOWER_TIMEOUTS.inc()
                if qdl is not None and time.time() >= qdl:
                    from ..exec.executor import QueryDeadlineError
                    raise QueryDeadlineError(
                        "query deadline expired waiting on a "
                        "micro-batch window (query_max_run_time_s)")
                return self.serving.route_and_run(entry, tq)
            if box[1] is not None:
                raise box[1]
            if tq is not None:
                tq.route = "microbatch"
            return box[0]
        return self._lead(w, shape_key, entry, lit_text, tq, window_s)

    # -- leader ------------------------------------------------------------

    def _lead(self, w: _Window, shape_key: str, entry: PlanEntry,
              lit_text: str, tq, window_s: float):
        time.sleep(window_s)
        with self._lock:
            w.closed = True
            self._windows.pop(shape_key, None)
            members = list(w.members)
        if not members:
            return None                # nobody joined: normal route
        from ..metrics import MICROBATCH_BATCHES, MICROBATCH_QUERIES
        MICROBATCH_BATCHES.inc()
        MICROBATCH_QUERIES.inc(1 + len(members))
        if tq is not None:
            tq.route = "microbatch"
        # stamp cached pages with the version observed BEFORE the merged
        # dispatch: a write landing mid-flight then invalidates them
        # instead of blessing pre-write rows with the post-write version
        version = self.serving.catalog_version()
        try:
            demux = self._run_merged(entry, lit_text, members)
        except Exception:              # noqa: BLE001 — degrade to N
            # merged dispatch failed (or demux was unsafe): run every
            # member individually so one odd shape can't fail a batch
            return self._run_individually(entry, lit_text, members, tq)
        for m_entry, m_lit, ev, box in members:
            res = demux(m_lit)
            self.serving.store_result(m_entry, res, version=version)
            box[0] = res
            ev.set()
        own = demux(lit_text)
        self.serving.store_result(entry, own, version=version)
        return own

    def _run_individually(self, entry: PlanEntry, lit_text: str,
                          members, tq):
        for m_entry, _lit, ev, box in members:
            try:
                box[0] = self.serving.route_and_run(m_entry, None)
            except Exception as e:     # noqa: BLE001 — per-member verdict
                box[1] = e
            ev.set()
        return self.serving.route_and_run(entry, tq)

    def _run_merged(self, entry: PlanEntry, lit_text: str, members):
        """One dispatch for the whole window: rewrite ``k = ?`` into
        ``k IN (all literals)`` with the key column appended, execute
        through the normal route machinery, split rows by key value.
        Returns a demux function lit_text -> QueryResult."""
        stmt = entry.stmt
        _, key_ident, _ = entry.point_shape
        lits: List[str] = []
        seen = set()
        for t in [lit_text] + [m[1] for m in members]:
            v = int(t)
            if v not in seen:
                seen.add(v)
                lits.append(t)
        select = tuple(stmt.select) + (A.SelectItem(key_ident, "$mbkey"),)
        where = A.InPredicate(key_ident,
                              tuple(A.NumberLit(t) for t in lits),
                              negated=False)
        merged = dataclasses.replace(stmt, select=select, where=where)
        session = self.serving.session
        with self.serving.plan_lock:
            rel = session.planner().plan_query(merged)
            root = prune_plan(rel.node)
        result = self.serving.run_routed(rel, root, None)
        rows = result.rows
        if rows and not isinstance(rows[0][-1], int):
            # demux key decoded to a non-integer representation: the
            # split below would silently drop rows — bail to individual
            raise HostUnsupported("non-integer micro-batch key")
        names = result.column_names[:-1]
        by_key: Dict[int, list] = {}
        for r in rows:
            by_key.setdefault(int(r[-1]), []).append(tuple(r[:-1]))
        from ..exec.session import QueryResult

        def demux(t: str) -> QueryResult:
            return QueryResult(list(names), by_key.get(int(t), []),
                               result.elapsed_s)
        return demux


# --------------------------------------------------------------------------
# the serving layer
# --------------------------------------------------------------------------

class ServingLayer:
    """Coordinator-side front end tying the four parts together. Owns
    NO device state: device executions still funnel through the
    dispatcher's exec lock; host/cache paths bypass it entirely."""

    def __init__(self, session, exec_lock: threading.Lock):
        self.session = session
        self.exec_lock = exec_lock
        # serializes parse+plan (the planner touches connector caches &
        # lazily-computed stats; execution stays concurrent)
        self.plan_lock = threading.Lock()
        self.plan_cache = PlanCache()
        self.result_cache = ResultCache()
        self.microbatcher = MicroBatcher(self)
        self.history = None            # QueryHistoryStore (coordinator)
        self.prewarm = None            # PrewarmEngine (exec/prewarm.py)
        # per-tenant device-contention tracker (exec/router.py): under
        # contention from other tenants, host-eligible queries overflow
        # to the host tier instead of queueing on the exec lock
        self.fair_share = TenantFairShare()
        # fingerprints the serving layer does not own: non-query
        # statements (DDL/SET/SHOW) and volatile system-table queries
        # both execute through the legacy session path; remembering them
        # avoids a wasted parse+plan on every repeat
        self._bypass: set = set()

    # -- keys --------------------------------------------------------------

    def catalog_version(self) -> int:
        return getattr(self.session.catalog, "version", 0)

    def props_key(self) -> int:
        items = tuple(sorted((k, str(v)) for k, v in
                             self.session.properties.items()))
        return hash(items)

    # -- plan cache --------------------------------------------------------

    def plan_entry(self, sql: str) -> Optional[PlanEntry]:
        """Planned + pruned entry for a query statement, via the plan
        cache; None for non-query statements (DDL/SET/SHOW execute
        through the session as before)."""
        fp = plan_fingerprint(sql)
        if fp in self._bypass:
            return None
        session = self.session
        enabled = bool(session.properties.get("enable_plan_cache", True))
        key = (fp, self.props_key(), self.catalog_version())
        if enabled:
            entry = self.plan_cache.get(key)
            if entry is not None:
                return entry
        with self.plan_lock:
            stmt = parse(sql)
            if not isinstance(stmt, (A.Query, A.SetOp, A.Values)):
                self._remember_bypass(fp)
                return None
            rel = session.planner().plan_query(stmt)
            root = prune_plan(rel.node)
        cacheable = self._cacheable(root)
        if not cacheable:
            # volatile scans (system / information_schema): the data can
            # change between plan and execution with no catalog-version
            # bump — including by THIS statement's own plan-cache
            # insertion — so a decode scope snapshotted at plan time can
            # go stale. Those statements keep the legacy atomic
            # plan+execute path under the exec lock.
            self._remember_bypass(fp)
            return None
        entry = PlanEntry(
            sql=sql, fingerprint=fp, stmt=stmt, rel=rel, root=root,
            cacheable=cacheable,
            point_shape=point_shape(stmt),
            catalog_version=key[2],
            weight=_plan_weight(root, sql), created_at=time.time())
        if enabled:
            self.plan_cache.put(key, entry)
        return entry

    def _remember_bypass(self, fp: str) -> None:
        if len(self._bypass) > 4096:
            self._bypass.clear()
        self._bypass.add(fp)

    @staticmethod
    def _cacheable(root) -> bool:
        """Deterministic + non-volatile: plans reading system /
        information_schema state change between executions without any
        catalog-version bump, so their pages must never be served from
        cache."""
        for scan in _plan_scans(root):
            if scan.catalog == "system" or \
                    scan.schema_name == "information_schema":
                return False
        return True

    # -- result cache ------------------------------------------------------

    def lookup_cached(self, tq):
        """FINISHED page served straight from the result cache (no lock,
        no planning). None on miss or when the cache is disabled."""
        props = self.session.properties
        if not props.get("enable_result_cache"):
            return None
        if props.get("require_distributed"):
            return None
        fp = plan_fingerprint(tq.sql)
        entry = self.result_cache.get((fp, self.props_key()),
                                      self.catalog_version())
        if entry is None:
            return None
        tq.route = "cache"
        from ..exec.session import QueryResult
        return QueryResult(list(entry.names), entry.rows, 0.0)

    def store_result(self, entry: PlanEntry, result,
                     version: Optional[int] = None) -> None:
        if not self.session.properties.get("enable_result_cache"):
            return
        if not entry.cacheable:
            return
        self.result_cache.put(
            (entry.fingerprint, self.props_key()),
            result.column_names, result.rows,
            self.catalog_version() if version is None else version)

    # -- execution ---------------------------------------------------------

    def execute_local(self, tq):
        """The dispatcher's local execution path: plan via the cache,
        micro-batch point queries, route host/device, fill the result
        cache. Non-query statements fall through to the session under
        the exec lock exactly as before."""
        entry = self.plan_entry(tq.sql)
        if entry is None:
            with self.exec_lock:
                return self.session.execute(tq.sql)
        if entry.point_shape is not None and \
                self.session.properties.get("enable_microbatch"):
            res = self.microbatcher.submit(entry, tq)
            if res is not None:
                return res
        return self.route_and_run(entry, tq)

    def route_and_run(self, entry: PlanEntry, tq):
        version = self.catalog_version()
        try:
            result = self.run_routed(entry.rel, entry.root, tq,
                                     fingerprint=entry.fingerprint)
        except Exception:
            # stale-plan hazard: a concurrent DDL/write can swap table
            # data between this entry's planning and its (lock-free)
            # execution, leaving decode scopes pointing past the new
            # dictionaries. Only that hazard is retried — if the catalog
            # version never moved, the data cannot have changed and the
            # failure is genuine.
            if self.catalog_version() == entry.catalog_version:
                raise
            with self.exec_lock:
                version = self.catalog_version()
                result = self.session.execute(entry.sql)
            if tq is not None:
                tq.route = "device"
                tq.route_reason = "replanned: catalog changed mid-flight"
                log.info("%sreplanned: catalog changed mid-flight",
                         tq_context(tq))
        self.store_result(entry, result, version=version)
        return result

    def run_routed(self, rel, root, tq, fingerprint=None):
        """Route one pruned plan and execute it (host: lock-free numpy;
        device: the session executor under the exec lock). The tenant
        fair-share tracker sees every device occupancy so a contended
        device overflows other tenants' small queries to the host."""
        from ..metrics import ROUTER_DECISIONS
        session = self.session
        t0 = time.monotonic()
        planner = session.planner()
        tenant = getattr(tq, "tenant", None) if tq is not None else None
        decision = decide_route(planner, root, session.properties,
                                history=self.history,
                                fingerprint=fingerprint,
                                tenant=tenant,
                                fair_share=self.fair_share,
                                prewarm=self.prewarm)
        if tq is not None:
            tq.route = decision.target
            tq.route_reason = decision.reason
        if decision.target == "host":
            if self.prewarm is not None and fingerprint and \
                    decision.reason.startswith("device program cold"):
                # compile-aware window: this query is served host-side;
                # warm the device program in the background so the NEXT
                # submission of the fingerprint routes to device
                self.prewarm.ensure_warming(
                    fingerprint, getattr(tq, "sql", None) or "",
                    context=tq_context(tq) if tq is not None else "")
            try:
                result = run_host(session, rel, root, t0)
                ROUTER_DECISIONS.inc(target="host")
                return result
            except HostUnsupported as e:
                # belt and braces: decide_route pre-checks support, but
                # an interpreter gap must degrade, not fail the query
                if tq is not None:
                    tq.route = "device"
                    tq.route_reason = f"host fallback: {e}"
                    log.info("%shost route fell back to device: %s",
                             tq_context(tq), e)
        ROUTER_DECISIONS.inc(target="device")
        self.fair_share.device_begin(tenant or "default")
        try:
            with self.exec_lock:
                result = session.execute_planned(rel, root, t0)
        finally:
            self.fair_share.device_end(tenant or "default")
        if self.prewarm is not None:
            # a completed device run compiled this fingerprint's
            # programs on-path: it is warm from here on
            self.prewarm.mark_warm(fingerprint)
        return result

    def info(self) -> dict:
        return {
            "planCache": {"entries": len(self.plan_cache)},
            "resultCache": self.result_cache.stats(),
        }
