"""Coordinator-side stage scheduling: split assignment, remote tasks,
task-level retry.

Reference: the pipelined scheduler stack — PipelinedQueryScheduler.java:164
creates stages, SourcePartitionedScheduler.java:228 pulls split batches and
places them via UniformNodeSelector.java:55, HttpRemoteTask.java:135
(sendUpdate:730) POSTs fragments+splits to workers and polls status, and
the FTE scheduler retries failed tasks on other nodes
(EventDrivenFaultTolerantQueryScheduler.java:206).

TPU shape: one SOURCE stage (the fragmenter's per-split partial program,
executed worker-side over row-range splits) and one FINAL stage (merge +
remainder of the plan, executed on the coordinator's devices). Workers that
fail mid-query get their unfinished splits reassigned to surviving workers
— task retry with the deterministic-input property Trino gets from durable
exchange (§5.4): a split is a pure row-range of a deterministic connector
table, so any worker can recompute it identically.
"""

from __future__ import annotations

import json
import statistics
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Set, Tuple
from urllib.error import HTTPError, URLError
from urllib.request import Request, urlopen

import numpy as np

from ..exec.chunked import ChunkAnalysis, analyze, merge_partials
from ..metrics import (SCAN_SPLITS_PRUNED, SCHED_HEDGE_WINS, SCHED_HEDGES,
                       SCHED_TASK_RETRIES, SCHED_TASKS, SPLITS_MIGRATED)
from ..planner import logical as L
from ..planner.fragmenter import Fragment, fragment_plan
from ..planner.optimizer import prune_plan
from ..sql import ast_nodes as A
from ..sql.parser import parse
from ..utils.tracing import NOOP
from .failureinjector import InjectedFailure
from .pageserde import PageChecksumError, verify_page
from .retrypolicy import RetryPolicy
from .tasks import Split, decode_columns, encode_fragment


class TaskFailedError(RuntimeError):
    pass


class RetryBudgetExhaustedError(TaskFailedError):
    """The query burned through its per-query retry/hedge amplification
    budget. NOT retryable at the query level: a query whose task
    attempts keep multiplying is amplifying load on a struggling
    cluster, and another full-query attempt would amplify further."""


class PageIntegrityError(TaskFailedError):
    """A drained page failed its CRC32C check: corruption detected on the
    wire/buffer and converted into a retryable task failure (the split
    re-runs on a survivor) instead of silently wrong results."""


class NodeDrainingError(TaskFailedError):
    """A worker refused new work because it is DRAINING/DRAINED (HTTP
    409 on the task POST). The splits migrate to survivors — counted as
    migrations, never as task-retry failures, and the node keeps its
    clean failure-detector record (it is winding down, not broken)."""


def _merge_sorted_runs(sort_node, pages):
    """Order-preserving n-way merge of sorted page runs by the sort
    keys (operator/MergeOperator.java + MergeHashSort's role — each
    page is one split's independently sorted output).

    Vectorized: one np.lexsort over the concatenated runs with a stable
    (run, within-run) tiebreak reproduces exactly what a priority queue
    over per-run cursors yields — the per-row Python key tuples of the
    old heapq merge cost tens of seconds at SF1 ORDER BY sizes,
    defeating the worker-side sort. Descending keys sort by NEGATED
    RANK codes (np.unique inverse), not negated values, so non-numeric
    sort keys (e.g. object-dtype strings) merge correctly.
    Returns (arrays, valids)."""
    from .tasks import decode_columns
    runs = []
    for p in pages:
        arrs, vals = decode_columns(p)
        if len(arrs) and len(arrs[0]):
            runs.append((arrs, vals))
    if not runs:
        return [], []
    keys = sort_node.keys

    ncols = len(runs[0][0])
    arrays = [np.concatenate([a[j] for a, _ in runs])
              for j in range(ncols)]
    valids = [np.concatenate([v[j] for _, v in runs])
              for j in range(ncols)]
    lens = [len(a[0]) for a, _ in runs]
    run_id = np.repeat(np.arange(len(runs), dtype=np.int64), lens)
    within = np.concatenate([np.arange(n, dtype=np.int64)
                             for n in lens])

    # lexsort levels, least significant first: (within, run) tiebreak
    # mirrors heapq.merge's stability (equal keys come out in run
    # order, preserving each run's internal order), then per key —
    # rank code below its null-rank, keys[0]'s pair last (= primary)
    levels = [within, run_id]
    for k in reversed(keys):
        ok = np.asarray(valids[k.index], dtype=bool)
        codes = np.unique(arrays[k.index], return_inverse=True)[1] \
            .astype(np.int64)
        if not k.ascending:
            codes = -codes
        codes = np.where(ok, codes, 0)
        nr = np.where(ok, 1 if k.nulls_first else 0,
                      0 if k.nulls_first else 1).astype(np.int8)
        levels.append(codes)
        levels.append(nr)
    order = np.lexsort(levels)
    return [a[order] for a in arrays], [v[order] for v in valids]


class _HedgedUnit:
    """One work unit (a node's split group) in a drain round. A unit may
    carry several concurrent attempts once hedged; `pages` is set exactly
    once by the first successful attempt (first-success-wins dedup)."""

    __slots__ = ("first_node", "splits", "key", "pages", "live", "hedged",
                 "nodes_used", "failed_nodes", "drained_nodes", "started",
                 "tasks", "winner")

    def __init__(self, first_node: str, splits: List[Split], key: str):
        self.first_node = first_node
        self.splits = splits
        self.key = key
        self.pages: Optional[List[bytes]] = None
        self.live = 0                  # attempts currently in flight
        self.hedged = False
        self.nodes_used: Set[str] = set()
        self.failed_nodes: Set[str] = set()
        # subset of failed_nodes that 409'd the task POST (drain
        # handoff): a unit whose failures are ALL drain handoffs is a
        # migration, not a failure
        self.drained_nodes: Set[str] = set()
        self.started = time.monotonic()
        self.tasks: List["RemoteTask"] = []
        self.winner: Optional["RemoteTask"] = None


class RemoteTask:
    """Coordinator's proxy of one worker task (HttpRemoteTask.java:135)."""

    def __init__(self, node, task_id: str, fragment_blob: str,
                 splits: List[Split], http_timeout_s: float = 30.0,
                 partition: Optional[dict] = None,
                 sources: Optional[dict] = None, injector=None,
                 traceparent: Optional[str] = None,
                 deadline: Optional[float] = None):
        self.node = node
        self.task_id = task_id
        self.fragment_blob = fragment_blob
        self.splits = splits
        self.http_timeout_s = http_timeout_s
        self.partition = partition
        self.sources = sources
        self.injector = injector          # chaos hook (EXCHANGE_DRAIN)
        self.traceparent = traceparent    # W3C context for every hop
        # absolute query deadline (coordinator wall clock, None = no
        # cap); start() ships it normalized to the worker's clock
        self.deadline = deadline
        self.pages: List[dict] = []
        self.bytes_drained = 0            # frame bytes pulled (shuffle)
        self.done = False

    def _url(self, suffix: str = "") -> str:
        return f"{self.node.uri}/v1/task/{self.task_id}{suffix}"

    def _request(self, url: str, data: Optional[bytes] = None,
                 method: str = "GET", accept: str = ""):
        """JSON request; with `accept` = the binary pages media type the
        response may instead be a raw page frame (returned as bytes)."""
        from .security import internal_headers
        headers = {"Content-Type": "application/json",
                   **internal_headers()}
        if accept:
            headers["Accept"] = accept
        if self.traceparent is not None:
            headers["traceparent"] = self.traceparent
        req = Request(url, data=data, method=method, headers=headers)
        with urlopen(req, timeout=self.http_timeout_s) as resp:
            body = resp.read()
            if resp.headers.get("Content-Type", "").startswith(
                    "application/x-trino-pages"):
                return bytes(body)
            return json.loads(body.decode()) if body else {}

    def start(self) -> None:
        payload = {
            "fragment": self.fragment_blob,
            "splits": [vars(s) for s in self.splits],
        }
        if self.partition is not None:
            payload["partition"] = self.partition
        if self.sources is not None:
            payload["sources"] = self.sources
        if self.deadline is not None:
            # ship the remaining budget on the WORKER's wall clock: the
            # announce-estimated offset rebases the coordinator-absolute
            # deadline so a skewed worker enforces the same instant
            payload["deadline"] = self.deadline + \
                getattr(self.node, "clock_offset", 0.0)
        body = json.dumps(payload).encode()
        self._request(self._url(), data=body, method="POST")

    def wait_finished(self, deadline: float) -> None:
        """Poll task status until FINISHED (producer stages whose buffers
        are drained by OTHER workers — the coordinator only needs the
        terminal state, ContinuousTaskStatusFetcher's role)."""
        while time.time() < deadline:
            st = self._request(self._url())
            if st.get("state") == "FINISHED":
                self.done = True
                return
            if st.get("state") in ("FAILED", "CANCELED"):
                raise TaskFailedError(
                    f"task {self.task_id} on {self.node.node_id}: "
                    f"{st.get('error', st.get('state'))}")
            time.sleep(0.02)
        raise TaskFailedError(f"task {self.task_id} timed out")

    def _verified(self, frame: bytes) -> bytes:
        """Chaos corruption hook + CRC32C integrity gate for one drained
        frame. A checksum failure is a *retryable* task failure: the
        work re-runs on a survivor rather than merging garbled columns."""
        if self.injector is not None:
            frame = self.injector.corrupt_page("EXCHANGE_DRAIN",
                                               self.task_id, frame)
        try:
            verify_page(frame)
        except PageChecksumError as e:
            raise PageIntegrityError(
                f"task {self.task_id} on {self.node.node_id}: {e}") from e
        return frame

    def drain(self, deadline: float) -> List[bytes]:
        """Pull result pages token by token until the buffer completes
        (HttpPageBufferClient.sendGetResults:355's loop). Pages cross
        the wire as binary zstd/zlib frames (pageserde.py), the JSON
        envelope only carries terminal/empty states. Every frame is
        CRC32C-verified before it is accepted."""
        token = 0
        while time.time() < deadline:
            if self.injector is not None:
                # chaos: drop/delay/raise at the results-fetch boundary
                self.injector.maybe_fail("EXCHANGE_DRAIN", self.task_id)
            out = self._request(self._url(f"/results/{token}"),
                                accept="application/x-trino-pages")
            if isinstance(out, bytes):
                self.pages.append(self._verified(out))
                self.bytes_drained += len(out)
                token += 1
                continue
            if out.get("page") is not None:
                page = out["page"]
                if isinstance(page, dict) and "b64" in page:
                    import base64
                    page = base64.b64decode(page["b64"])
                if isinstance(page, (bytes, bytearray)):
                    page = self._verified(bytes(page))
                    self.bytes_drained += len(page)
                self.pages.append(page)
                token += 1
                continue
            if out.get("state") == "FAILED":
                raise TaskFailedError(
                    f"task {self.task_id} on {self.node.node_id}: "
                    f"{out.get('error', '')}")
            if out.get("complete"):
                self.done = True
                return self.pages
            time.sleep(0.02)
        raise TaskFailedError(f"task {self.task_id} timed out")

    def cancel(self) -> None:
        try:
            self._request(self._url(), method="DELETE")
        except Exception:        # noqa: BLE001 — best-effort abort
            pass


class StageScheduler:
    """Schedules eligible queries across announced workers; falls back to
    local execution by returning None (the caller keeps the single-node
    path — Trino's coordinator-only queries take the same shortcut)."""

    def __init__(self, coordinator_state, session, split_rows: int = None,
                 max_task_retries: int = None, task_timeout_s: float = 300.0,
                 spool=None):
        self.state = coordinator_state
        self.session = session
        # Constructor args, when given, override session properties —
        # SESSION_PROPERTY_DEFAULTS pre-populates every key, so a plain
        # props.get(name, arg) would silently ignore the caller's values.
        props = getattr(session, "properties", {})
        self.split_rows = split_rows if split_rows is not None \
            else props.get("split_rows", 250_000)
        self.max_task_retries = max_task_retries \
            if max_task_retries is not None \
            else props.get("task_retries", 2)
        self.task_timeout_s = task_timeout_s
        # straggler hedging: a task past max(hedge_min_s, multiplier *
        # median drain time of its round) gets a speculative duplicate on
        # a survivor; first success wins (spool work-key dedup + the
        # all-or-nothing drain make the race safe). multiplier <= 0
        # disables.
        self.hedge_multiplier = float(props.get("hedge_multiplier", 4.0))
        self.hedge_min_s = float(props.get("hedge_min_s", 2.0))
        # backoff between task-retry rounds (shared RetryPolicy shape)
        self.retry_backoff_base_s = float(
            props.get("retry_backoff_base_s", 0.05))
        self.retry_backoff_max_s = float(
            props.get("retry_backoff_max_s", 2.0))
        self._seq = 0
        self._lock = threading.Lock()
        self.stats: Dict[str, int] = {"queries": 0, "tasks": 0,
                                      "task_retries": 0, "spool_hits": 0,
                                      "hedged_tasks": 0, "hedge_wins": 0,
                                      "checksum_failures": 0,
                                      "splits_pruned": 0,
                                      "splits_migrated": 0}
        # observability: per-query stage/task rollup (reset each execute;
        # read by the dispatcher into TrackedQuery.stage_stats), recent
        # task records for system.runtime.tasks, and per-(query, operator)
        # aggregates for system.runtime.operator_stats
        self.last_query: Optional[dict] = None
        self.task_history: "deque[dict]" = deque(maxlen=256)
        self.operator_history: "deque[dict]" = deque(maxlen=512)
        self._current_stage = "source"
        self._profile_tasks = False     # EXPLAIN ANALYZE: force worker
                                        # per-operator profiling
        # durable exchange (FTE): drained task outputs persist keyed by
        # work identity; later attempts reuse instead of re-running
        from .exchange_spool import ExchangeSpool
        self.spool = spool if spool is not None else ExchangeSpool()
        self.failure_injector = None     # hook: fail between stages
        # why the last execute() declined (picked up by the dispatcher
        # into TrackedQuery.fallback_reason — the round-3 verdict's
        # "silently local" complaint)
        self.fallback_reason: Optional[str] = None
        # wired by CoordinatorState: query_id -> TrackedQuery, so
        # EXPLAIN ANALYZE can fold queued time (state-machine stamps)
        # into its critical-path line. None under session-local use.
        self.tracked_lookup = None
        # wired by CoordinatorState: the live-stats store
        # (server/livestats.py) heartbeat folds land in. Launched tasks
        # register so mid-flight rollups know stage/node/split counts
        # before the first heartbeat arrives. None under session-local use.
        self.livestats = None
        # cancellation fan-out (round-22): every in-flight RemoteTask of
        # the current query — hedge twins included — so terminate() can
        # DELETE them all on every assigned worker. Cleared per query.
        self._live_tasks: Dict[str, List[RemoteTask]] = {}
        self._live_tasks_lock = threading.Lock()
        # per-query retry/hedge amplification budget: extra attempts
        # (retry rounds + hedges) past this fail the query instead of
        # multiplying load on a struggling cluster
        self.max_task_amplification = int(
            props.get("task_amplification_budget", 16))
        self._amplification = 0

    # -- durable query ledger hooks ---------------------------------------

    def _ledger_assign(self, task) -> None:
        """Record a task/stage assignment in the coordinator's durable
        query ledger (server/ledger.py) — the promoted coordinator
        reconciles these against live worker task inventories to decide
        re-attach vs re-execute. No-op without a ledger."""
        led = getattr(self.state, "ledger", None)
        qid = (self.last_query or {}).get("query_id")
        if led is None or not qid:
            return
        led.assign(qid, task.task_id, task.node.node_id,
                   self._current_stage)

    def _livestats_register(self, task) -> None:
        """Pre-register a launched task with the live-stats store so the
        per-stage rollup carries stage/node/split-count attribution from
        launch, not from the first heartbeat. No-op without a store."""
        self._track_live(task)
        ls = self.livestats
        qid = (self.last_query or {}).get("query_id")
        if ls is None or not qid:
            return
        ls.register_task(qid, task.task_id, stage=self._current_stage,
                         node=task.node.node_id,
                         splits_total=len(task.splits))

    # -- cancellation fan-out + amplification budget (round-22) ------------

    def _track_live(self, task: "RemoteTask") -> None:
        """Register a launched task in the per-query live registry —
        the terminate() fan-out's worker-task DELETE target list."""
        qid = (self.last_query or {}).get("query_id")
        if not qid:
            return
        with self._live_tasks_lock:
            self._live_tasks.setdefault(qid, []).append(task)

    def cancel_query_tasks(self, query_id: str) -> List[str]:
        """Best-effort DELETE of every in-flight worker task launched
        for `query_id` — hedge twins included. Returns the task ids the
        fan-out covered (the DELETEs themselves never raise)."""
        with self._live_tasks_lock:
            tasks = list(self._live_tasks.get(query_id, ()))
        for t in tasks:
            t.cancel()
        return [t.task_id for t in tasks]

    def _amplify(self, n: int = 1, required: bool = True) -> bool:
        """Charge `n` extra task attempts (a retry round, a hedge)
        against the query's amplification budget. Past the cap:
        required attempts (retries) raise RetryBudgetExhaustedError —
        non-retryable, the query fails rather than multiplying load —
        while optional ones (hedges) are simply declined."""
        if self._amplification + n > self.max_task_amplification:
            from ..metrics import RETRY_BUDGET_EXHAUSTED
            RETRY_BUDGET_EXHAUSTED.inc()
            if required:
                raise RetryBudgetExhaustedError(
                    f"query exceeded its retry/hedge amplification "
                    f"budget ({self.max_task_amplification} extra "
                    f"attempts)")
            return False
        self._amplification += n
        return True

    def _query_deadline(self) -> Optional[float]:
        """The current query's absolute run deadline (coordinator wall
        clock), or None. Caps every stage/drain/wait deadline so no
        scheduler wait outlives the query, and rides every task POST."""
        lookup = self.tracked_lookup
        qid = (self.last_query or {}).get("query_id")
        if lookup is None or not qid:
            return None
        tq = lookup(qid)
        return getattr(tq, "deadline", None) if tq is not None else None

    def _query_dead(self) -> bool:
        """True once the current query's state machine went terminal
        (terminate() fan-out, deadline expiry) — drain loops poll this
        so a canceled query's dispatch stops instead of retrying work
        nobody will read."""
        lookup = self.tracked_lookup
        qid = (self.last_query or {}).get("query_id")
        if lookup is None or not qid:
            return False
        tq = lookup(qid)
        return tq is not None and tq.state_machine.is_done()

    def _ledger_spool(self, key: str) -> None:
        """Record a result-spool pointer: after a failover, spooled
        output keyed here lets a resumed query re-attach instead of
        re-running the work."""
        led = getattr(self.state, "ledger", None)
        qid = (self.last_query or {}).get("query_id")
        if led is None or not qid:
            return
        led.spool(qid, key)

    # -- per-query observability rollup -----------------------------------

    def _tracer(self):
        """The session's tracer (the dispatcher swaps a per-query tracer
        in while a traced query executes); NOOP otherwise."""
        return getattr(self.session, "tracer", None) or NOOP

    def _begin_query(self, query_id: Optional[str]) -> None:
        self._stats_snap = dict(self.stats)
        self.last_query = {"query_id": query_id, "stages": 0,
                           "tasks": [], "operators": {},
                           "bytes_shuffled": 0}
        self._current_stage = "source"
        self._amplification = 0
        if query_id:
            # fresh attempt: drop the previous attempt's task registry
            # (those tasks are already terminal or canceled)
            with self._live_tasks_lock:
                self._live_tasks.pop(query_id, None)
        if self.livestats is not None and query_id:
            self.livestats.begin(query_id)

    def _finalize_rollup(self) -> None:
        """Compute the per-query deltas of the cumulative counters and
        publish operator aggregates to the history ring (idempotent —
        EXPLAIN ANALYZE finalizes early to render, execute()'s finally is
        then a no-op)."""
        lq = self.last_query
        if lq is None or lq.get("_final"):
            return
        lq["_final"] = True
        if lq.get("query_id"):
            with self._live_tasks_lock:
                self._live_tasks.pop(lq["query_id"], None)
        if self.livestats is not None and lq.get("query_id"):
            self.livestats.finish(lq["query_id"])
        snap = getattr(self, "_stats_snap", {})
        for k in ("task_retries", "hedged_tasks", "hedge_wins",
                  "checksum_failures", "spool_hits", "splits_migrated"):
            lq[k] = self.stats.get(k, 0) - snap.get(k, 0)
        lq["stages"] = self.stats.get("stages", 0) - snap.get("stages", 0)
        lq["faults_survived"] = lq["task_retries"] + \
            lq["checksum_failures"]
        if lq.get("splits_pruned"):
            # surface split pruning on the TableScan rollup row so
            # system.runtime.operator_stats carries the verdict
            acc = lq["operators"].setdefault(
                "TableScan", {"rows": 0, "wall_ms": 0.0, "calls": 0,
                              "device_ms": 0.0, "host_ms": 0.0,
                              "compile_ms": 0.0, "strategy": "",
                              "distribution": ""})
            acc["strategy"] = (f"zone-pruned:{lq['splits_pruned']}/"
                               f"{lq.get('splits_total', 0)} splits")
        with self._lock:
            for op, d in lq["operators"].items():
                self.operator_history.append(
                    {"query_id": lq.get("query_id") or "",
                     "operator": op, "rows": d["rows"],
                     "wall_ms": d["wall_ms"], "calls": d["calls"],
                     "device_ms": d.get("device_ms", 0.0),
                     "host_ms": d.get("host_ms", 0.0),
                     "compile_ms": d.get("compile_ms", 0.0),
                     "strategy": d.get("strategy", ""),
                     "distribution": d.get("distribution", "")})

    def _record_task(self, task: "RemoteTask") -> None:
        """Fetch a finished task's terminal status — TaskStats + spans —
        and fold it into the per-query rollup, the system.runtime.tasks
        ring, and the stitched trace (the merge step of the reference's
        operator -> task -> stage -> query stats pyramid)."""
        try:
            st = task._request(task._url())
        except Exception:  # noqa: BLE001 — stats fetch is best-effort
            return
        stats = st.get("stats") or {}
        ops = stats.get("operators") or {}
        rec = {"query_id": (self.last_query or {}).get("query_id") or "",
               "task_id": task.task_id, "node": task.node.node_id,
               "stage": self._current_stage,
               "state": st.get("state", ""),
               "splits": int(stats.get("splitsDone", 0)),
               "rows": int(stats.get("rowsOut", 0)),
               "bytes": int(stats.get("bytesOut", 0)),
               "wall_ms": float(stats.get("wallMs", 0.0)),
               # per-task device/host/compile split: the timeline's
               # blocking-task attribution (server/timeline.py) reads
               # these off the stage's slowest task
               "device_ms": sum(float(d.get("deviceMs", 0.0))
                                for d in ops.values()),
               "host_ms": sum(float(d.get("hostMs", 0.0))
                              for d in ops.values()),
               "compile_ms": sum(float(d.get("compileMs", 0.0))
                                 for d in ops.values())}
        with self._lock:
            self.task_history.append(rec)
            lq = self.last_query
            if lq is not None:
                lq["tasks"].append(rec)
                lq["bytes_shuffled"] += task.bytes_drained
                for op, d in (stats.get("operators") or {}).items():
                    acc = lq["operators"].setdefault(
                        op, {"rows": 0, "wall_ms": 0.0, "calls": 0,
                             "device_ms": 0.0, "host_ms": 0.0,
                             "compile_ms": 0.0, "strategy": "",
                             "distribution": ""})
                    acc["rows"] += int(d.get("rows", 0))
                    acc["wall_ms"] += float(d.get("wallMs", 0.0))
                    acc["calls"] += int(d.get("calls", 0))
                    acc["device_ms"] += float(d.get("deviceMs", 0.0))
                    acc["host_ms"] += float(d.get("hostMs", 0.0))
                    acc["compile_ms"] += float(d.get("compileMs", 0.0))
                    if d.get("strategy"):
                        acc["strategy"] = d["strategy"]
                    if d.get("distribution"):
                        acc["distribution"] = d["distribution"]
        # rebase the worker's span stamps onto the coordinator clock
        # using the offset estimated at announce (skew satellite)
        self._tracer().adopt(
            st.get("spans") or [],
            offset_s=getattr(task.node, "clock_offset", 0.0))

    # -- eligibility + planning -------------------------------------------

    def plan(self, sql: str):
        return self._plan_stmt(parse(sql))

    def _plan_stmt(self, stmt):
        if not isinstance(stmt, A.Query):
            self.fallback_reason = "coordinator-only statement"
            return None
        rel = self.session.planner().plan_query(stmt)
        root = prune_plan(rel.node)
        # eligibility pre-gate: something must be split-worthy, or local
        # execution wins outright (coordinator-only queries, Trino-style)
        from ..planner.fragmenter import _scan_rows, _subtree_nodes
        if not any(isinstance(n, L.ScanNode) and
                   _scan_rows(self.session.catalog, n) > self.split_rows
                   for n in _subtree_nodes(root)):
            self.fallback_reason = (
                f"no scan larger than split_rows={self.split_rows}")
            return None
        return rel, root

    def execute(self, sql: str, query_id: Optional[str] = None):
        """Distributed execution; returns QueryResult or None (fall back
        to local). EXPLAIN ANALYZE of an eligible query executes it
        distributed and renders the merged per-stage/per-operator stats.

        Phased multi-stage execution (PipelinedQueryScheduler.java:164 +
        PhasedExecutionSchedule): the fragmenter cuts heavy join build
        sides into their own stages; build stages run first (distributed
        when their driver table is large, else on the coordinator), each
        materialized output broadcast into its consumers; the probe spine
        then runs as the split-streamed SOURCE stage and the coordinator
        merges in the FINAL stage."""
        stmt = parse(sql)
        self._begin_query(query_id)
        try:
            if isinstance(stmt, A.Explain) and stmt.analyze and \
                    (isinstance(stmt.query, A.Query) or
                     isinstance(stmt.query, (A.InsertInto, A.CreateTable))
                     and getattr(stmt.query, "query", None) is not None):
                return self._execute_explain_analyze(stmt, sql)
            return self._execute_stmt(stmt, sql)
        finally:
            self._finalize_rollup()

    def _execute_stmt(self, stmt, sql: str):
        t0 = time.monotonic()
        tracer = self._tracer()
        self.fallback_reason = None
        # one injector governs every coordinator-side chaos point,
        # including the spool's read/write hooks
        self.spool.injector = self.failure_injector
        workers = self.state.active_nodes()
        if not workers:
            self.fallback_reason = "no active workers"
            return None
        if isinstance(stmt, (A.InsertInto, A.CreateTable)):
            with tracer.span("distributed-write"):
                return self._execute_write(stmt, sql, t0, workers)
        with tracer.span("plan-distributed"):
            planned = self._plan_stmt(stmt)
        if planned is None:
            return None
        rel, root = planned

        # session-forced partitioned join distribution: hash-repartition
        # both sides across workers instead of broadcasting the build
        # (DetermineJoinDistributionType.java:51's PARTITIONED choice)
        props = getattr(self.session, "properties", {})
        if props.get("join_distribution_type") == "partitioned":
            desc = self._analyze_partitioned(root)
            if desc is not None:
                self._current_stage = "partitioned"
                with tracer.span("partitioned-exchange",
                                 workers=len(workers)):
                    result = self._execute_partitioned(rel, root, workers,
                                                       desc)
                result.elapsed_s = time.monotonic() - t0
                self.stats["queries"] += 1
                return result
            self.fallback_reason = ("join_distribution_type=PARTITIONED "
                                    "but plan shape does not support a "
                                    "partitioned exchange")

        frags = fragment_plan(root, self.session.catalog,
                              min_build_rows=self.split_rows)
        # the probe spine itself must be split-worthy BEFORE any build
        # stage runs — otherwise distributed builds execute and the local
        # fallback throws their work away
        from ..planner.fragmenter import _scan_rows, _subtree_nodes
        if not any(isinstance(n, L.ScanNode) and
                   _scan_rows(self.session.catalog, n) > self.split_rows
                   for n in _subtree_nodes(frags[-1].root)):
            self.fallback_reason = "probe spine below split threshold"
            return None
        self.stats["stages"] = self.stats.get("stages", 0) + len(frags) + 1
        materialized: Dict[int, L.ValuesNode] = {}
        for f in frags[:-1]:
            plan_f = self._bind_remotes(f.root, materialized)
            self._current_stage = f"build-{f.id}"
            with tracer.span("build-stage", fragment=f.id):
                materialized[f.id] = self._run_build_stage(plan_f)
            if self.failure_injector is not None:
                self.failure_injector.maybe_fail("STAGE_BOUNDARY", sql)
        self._current_stage = "source"
        root = self._bind_remotes(frags[-1].root, materialized)

        analysis = analyze(root, self.session.catalog, self.split_rows,
                           allow_sort_merge=True)
        if analysis is None:
            self.fallback_reason = ("plan shape not split-streamable "
                                    "(sort/window/distinct below the "
                                    "merge point, or driver on a build "
                                    "side)")
            return None
        workers = self.state.active_nodes()
        if not workers:      # every worker died during the build stages
            self.fallback_reason = "all workers failed during build stages"
            return None
        partial_pages = self._run_source_stage(workers, analysis, root)
        if self.failure_injector is not None:
            # between-stage failure point: source outputs are already
            # spooled, so the QUERY retry resumes from them
            self.failure_injector.maybe_fail("STAGE_BOUNDARY", sql)
        with tracer.span("final-stage", pages=len(partial_pages)):
            result = self._run_final_stage(rel, root, analysis,
                                           partial_pages)
        result.elapsed_s = time.monotonic() - t0
        self.stats["queries"] += 1
        return result

    def _execute_write(self, stmt, sql: str, t0: float, workers):
        """Distributed INSERT / CTAS with exactly-once commit (the FTE
        write path: TableWriterOperator staging + TableFinishOperator
        commit under task retries). Source tasks run the inner query
        split-streamed with hash-partitioned output; P write tasks each
        pull one partition through the CRC-framed exchange and stage an
        attempt file, reporting a manifest in terminal status; the
        coordinator dedups manifests first-success-wins, journals the
        commit, publishes by rename, and bumps the catalog version.
        Returns None (local staged fallback) only before any task has
        side effects."""
        import os as _os
        import uuid as _uuid
        from ..batch import Field
        from ..exec import zonemap
        from ..exec.session import QueryResult
        from ..metrics import WRITE_ATTEMPTS_DEDUPED
        from ..types import BIGINT
        from . import writeprotocol as wp
        sess = self.session
        inner = getattr(stmt, "query", None)
        if inner is None or not isinstance(inner, A.Query):
            self.fallback_reason = "coordinator-only statement"
            return None
        cat, sch, tbl = sess.resolve_table(stmt.table)
        try:
            conn = sess.catalog.connector(cat)
        except Exception:
            self.fallback_reason = f"unknown catalog {cat}"
            return None
        if not getattr(conn, "supports_staged_writes", False):
            self.fallback_reason = (f"connector {cat} has no staged "
                                    f"write support")
            return None
        is_ctas = isinstance(stmt, A.CreateTable)
        qid = (self.last_query or {}).get("query_id") or \
            f"adhoc_{_uuid.uuid4().hex[:10]}"
        table_dir = _os.path.abspath(conn._table_dir(sch, tbl))
        # commit-phase wall (stage / commit), surfaced on the EXPLAIN
        # ANALYZE write line and read by the timeline's write-commit
        # attribution when tracing is off; empty on the idempotent
        # already-committed path (no staging happened this attempt)
        phase_times: Dict[str, float] = {}

        def _finish_commit(stats, partitions, staged):
            conn._cache.pop((sch, tbl), None)
            sess.catalog.bump_version()
            sess.executor.invalidate_scan_cache()
            try:
                zonemap.note_table(conn.get_table(sch, tbl))
            except Exception:   # noqa: BLE001 — registration best-effort
                pass
            with self._lock:
                lq = self.last_query
                if lq is not None:
                    lq["write"] = {
                        "partitions": partitions, "staged": staged,
                        "deduped": stats.get("deduped", 0),
                        "rows": stats["rows"],
                        "bytes": stats.get("bytes", 0),
                        "phase": stats.get("phase", "committed"),
                        "stage_s": round(phase_times.get("stage", 0.0), 6),
                        "commit_s": round(phase_times.get("commit", 0.0),
                                          6)}
            return QueryResult(["rows"], [(stats["rows"],)],
                               time.monotonic() - t0)

        # a prior attempt of this very query already committed: the
        # protocol's idempotence — return its result, never re-stage
        already = wp.published_rows_for(table_dir, qid)
        if already is not None:
            wp.recover_table_dir(table_dir)
            return _finish_commit({"rows": already, "phase": "committed"},
                                  0, 0)
        wp.recover_table_dir(table_dir)
        if is_ctas and conn.table_exists(sch, tbl):
            self.fallback_reason = "CTAS target exists (local path " \
                                   "resolves IF NOT EXISTS / errors)"
            return None
        if not is_ctas and not conn.table_exists(sch, tbl):
            self.fallback_reason = "insert target missing (local path " \
                                   "raises the canonical error)"
            return None
        planned = self._plan_stmt(inner)
        if planned is None:
            return None
        rel, root = planned
        analysis = analyze(root, sess.catalog, self.split_rows)
        if analysis is None or analysis.merge_agg is not None or \
                analysis.merge_sort is not None:
            self.fallback_reason = ("write source not split-streamable "
                                    "in concat mode")
            return None
        out_fields = []
        for name, sc in zip(root.names, rel.scope.columns):
            fld = sc.field if sc.field is not None else Field(name,
                                                              sc.dtype)
            out_fields.append(Field(name, sc.dtype,
                                    dictionary=fld.dictionary))
        if not is_ctas:
            target = conn.get_table_schema(sch, tbl)
            if len(target) != len(out_fields) or any(
                    tf.dtype.kind is not of.dtype.kind
                    for tf, of in zip(target, out_fields)):
                self.fallback_reason = ("insert column mismatch (local "
                                        "path raises)")
                return None
            out_fields = [Field(tf.name, of.dtype,
                                dictionary=of.dictionary)
                          for tf, of in zip(target, out_fields)]

        props = getattr(sess, "properties", {})
        P = int(props.get("write_partitions") or 0) or len(workers)
        src_root = root.child
        keys = [i for i, (_, dt) in enumerate(src_root.output)
                if np.issubdtype(dt.np_dtype, np.integer)][:1]
        if not keys:
            # no hashable column: everything lands in partition 0, so a
            # single write partition avoids empty-part churn
            P = 1
        t_deadline = time.time() + self.task_timeout_s
        qd = self._query_deadline()
        if qd is not None:
            t_deadline = min(t_deadline, qd)
        traceparent = self._tracer().traceparent()
        splits = self._make_splits(analysis)
        blob = encode_fragment({"root": src_root,
                                "driver": analysis.driver})
        src_tasks = []
        live: Dict[int, list] = {}
        _os.makedirs(table_dir, exist_ok=True)
        created_dir = is_ctas
        tracer = self._tracer()
        try:
            _t_stage = time.monotonic()
            with tracer.span("write-stage", partitions=P):
                for wi, w in enumerate(workers):
                    sp = [s for i, s in enumerate(splits)
                          if i % len(workers) == wi]
                    if not sp:
                        continue
                    with self._lock:
                        self._seq += 1
                        tid = f"t{self._seq}"
                    task = RemoteTask(w, tid, blob, sp,
                                      partition={"keys": keys, "count": P},
                                      injector=self.failure_injector,
                                      traceparent=traceparent,
                                      deadline=qd)
                    task.start()
                    self._ledger_assign(task)
                    self._livestats_register(task)
                    self.stats["tasks"] += 1
                    SCHED_TASKS.inc()
                    src_tasks.append(task)

                def launch_writer(p: int, attempt_no: int, exclude=()):
                    w = next((n for n in self.state.active_nodes()
                              if n.node_id not in exclude),
                             None) or workers[(p + attempt_no) % len(workers)]
                    with self._lock:
                        self._seq += 1
                        tid = f"t{self._seq}"
                    node = L.TableWriterNode(
                        child=L.RemoteSourceNode(1, src_root.output),
                        catalog=cat, schema_name=sch, table=tbl,
                        table_dir=table_dir, fmt=conn.fmt, query_id=qid,
                        stage=1, partition=p, attempt=tid,
                        fields=tuple(out_fields), output=(("rows", BIGINT),))
                    wblob = encode_fragment({"root": node,
                                             "timeout_s":
                                                 self.task_timeout_s})
                    sources = {"1": [{"uri": t.node.uri, "taskId": t.task_id,
                                      "buffer": p} for t in src_tasks]}
                    task = RemoteTask(w, tid, wblob, [], sources=sources,
                                      injector=self.failure_injector,
                                      traceparent=traceparent,
                                      deadline=qd)
                    task.start()
                    self._ledger_assign(task)
                    self._livestats_register(task)
                    self.stats["tasks"] += 1
                    SCHED_TASKS.inc()
                    return task

                attempts: Dict[int, int] = {}
                for p in range(P):
                    live[p] = [launch_writer(p, 0)]
                    attempts[p] = 1
                    if getattr(self, "force_write_hedge", False):
                        # duplicate-attempt injection: both stage; commit's
                        # (stage, partition) dedup must drop one
                        live[p].append(launch_writer(p, 1))
                        attempts[p] += 1
                        self.stats["hedged_tasks"] = \
                            self.stats.get("hedged_tasks", 0) + 1
                manifests: List[dict] = []
                collected: Set[str] = set()
                done: Set[int] = set()
                max_attempts = 4
                while len(done) < P:
                    if time.time() > t_deadline:
                        raise TaskFailedError("write stage timed out")
                    for p in range(P):
                        if p in done:
                            continue
                        failed_nodes = []
                        all_failed = bool(live[p])
                        for t in list(live[p]):
                            try:
                                st = t._request(t._url())
                            except Exception:
                                st = {"state": "FAILED", "error": "status "
                                      "fetch failed (node dead?)"}
                            state = st.get("state")
                            if state == "FINISHED":
                                m = (st.get("stats") or {}).get("manifest")
                                if m is not None:
                                    manifests.append(m)
                                    collected.add(t.task_id)
                                    done.add(p)
                                    self._record_task(t)
                                    all_failed = False
                                    break
                                state = "FAILED"
                            if state in ("FAILED", "CANCELED"):
                                live[p].remove(t)
                                failed_nodes.append(t.node.node_id)
                                self._amplify(1)
                                self.stats["task_retries"] += 1
                                SCHED_TASK_RETRIES.inc()
                            else:
                                all_failed = False
                        if p in done or not all_failed:
                            continue
                        if attempts[p] >= max_attempts:
                            raise TaskFailedError(
                                f"write partition {p} exhausted "
                                f"{max_attempts} attempts")
                        live[p].append(launch_writer(p, attempts[p],
                                                     exclude=failed_nodes))
                        attempts[p] += 1
                    time.sleep(0.02)
                # duplicate attempts that also finished report their
                # manifests too — commit's (stage, partition) dedup drops
                # them; still-running stragglers are cancelled (their staged
                # files, if any, fall to the post-commit sweep)
                for p in range(P):
                    for t in live[p]:
                        if t.task_id in collected:
                            continue
                        try:
                            st = t._request(t._url())
                            m = (st.get("stats") or {}).get("manifest") \
                                if st.get("state") == "FINISHED" else None
                        except Exception:  # noqa: BLE001
                            m = None
                        if m is not None:
                            manifests.append(m)
                            collected.add(t.task_id)
                            continue
                        try:
                            t.cancel()
                        except Exception:  # noqa: BLE001
                            pass
                for t in src_tasks:
                    t.wait_finished(t_deadline)
                    self._record_task(t)
            phase_times["stage"] = time.monotonic() - _t_stage
            _t_commit = time.monotonic()
            with tracer.span("write-commit", partitions=P,
                             manifests=len(manifests)):
                stats = wp.commit(table_dir, qid, manifests,
                                  injector=self.failure_injector,
                                  tracer=tracer)
            phase_times["commit"] = time.monotonic() - _t_commit
            WRITE_ATTEMPTS_DEDUPED.inc(stats.get("deduped", 0))
            self.stats["stages"] = self.stats.get("stages", 0) + 2
            self.stats["queries"] += 1
            return _finish_commit(stats, P, len(manifests))
        except BaseException:
            for t in src_tasks + [t for ts in live.values() for t in ts]:
                try:
                    t.cancel()
                except Exception:  # noqa: BLE001
                    pass
            wp.abort(table_dir, qid)
            committed = wp.published_rows_for(table_dir, qid)
            if committed is not None:
                # the INTENT was durable: abort rolled the commit
                # FORWARD — report success, a re-run would double-write
                return _finish_commit(
                    {"rows": committed, "phase": "committed"}, P, 0)
            if created_dir:
                try:
                    _os.rmdir(table_dir)
                except OSError:
                    pass
            raise

    def _critical_path_line(self, t0: float) -> str:
        """The `critical path: ...` EXPLAIN ANALYZE line — phase
        attribution over this query's elapsed wall (server/timeline.py).
        Dispatcher-tracked queries fold in queued time from their
        state-machine stamps; session-local runs attribute only the
        scheduler-observed elapsed."""
        from .timeline import attribute_phases, breakdown_line
        lq = self.last_query or {}
        wall = max(0.0, time.monotonic() - t0)
        queued = 0.0
        lookup = self.tracked_lookup
        tq = lookup(lq.get("query_id") or "") if lookup else None
        if tq is not None:
            sm = tq.state_machine
            stamps = getattr(sm, "state_times", {}) or {}
            queued = max(0.0, stamps.get("PLANNING", sm.created_at) -
                         sm.created_at)
            wall = max(queued, time.time() - sm.created_at)
        phases = attribute_phases(wall, queued, self._tracer().export(),
                                  lq, lq.get("write"))
        return breakdown_line(phases, wall)

    def _execute_explain_analyze(self, stmt, sql: str):
        """EXPLAIN ANALYZE over the cluster: run the inner query
        distributed (with worker-side per-operator profiling forced),
        then render the logical plan followed by the merged per-stage and
        per-operator rollup — the distributed half EXPLAIN ANALYZE
        previously lacked (it profiled only coordinator-local runs)."""
        from ..exec.session import QueryResult
        from ..planner.logical import explain_text
        t0 = time.monotonic()
        self._profile_tasks = True
        try:
            result = self._execute_stmt(stmt.query, sql)
        finally:
            self._profile_tasks = False
        if result is None:
            return None      # not eligible: local EXPLAIN ANALYZE runs
        self._finalize_rollup()
        lq = self.last_query
        inner = stmt.query
        wstmt = None
        if isinstance(inner, (A.InsertInto, A.CreateTable)):
            wstmt, inner = inner, inner.query
        rel = self.session.planner().plan_query(inner)
        lines = explain_text(prune_plan(rel.node)).split("\n")
        if wstmt is not None:
            cat, sch, tbl = self.session.resolve_table(wstmt.table)
            lines = [f"TableCommit[{cat}.{sch}.{tbl}]",
                     f"  TableWriter[{cat}.{sch}.{tbl}]"] + \
                [f"    {ln}" for ln in lines]
        stages: Dict[str, list] = {}
        for t in lq["tasks"]:
            s = stages.setdefault(t["stage"], [0, 0, 0, 0.0])
            s[0] += 1
            s[1] += t["splits"]
            s[2] += t["rows"]
            s[3] = max(s[3], t["wall_ms"])
        lines += ["", f"Distributed execution: {lq['stages']} stages, "
                      f"{len(lq['tasks'])} tasks, "
                      f"{lq['bytes_shuffled']} bytes shuffled, "
                      f"{lq['task_retries']} task retries, "
                      f"{lq['hedged_tasks']} hedged",
                  self._critical_path_line(t0),
                  f"scan: {lq.get('splits_total', 0)} splits, "
                  f"{lq.get('splits_pruned', 0)} pruned by zone maps"]
        wr = lq.get("write")
        if wr is not None:
            lines.append(f"write: {wr['partitions']} partitions, "
                         f"{wr['staged']} staged, "
                         f"{wr['deduped']} deduped, {wr['rows']} rows "
                         f"(stage {wr.get('stage_s', 0.0) * 1000:.1f}ms + "
                         f"commit {wr.get('commit_s', 0.0) * 1000:.1f}ms)")
        for name in sorted(stages):
            n, splits, rows, wall = stages[name]
            lines.append(f"Stage {name}: tasks={n}, splits={splits}, "
                         f"rows={rows}, max task wall={wall:.1f}ms")
        for op in sorted(lq["operators"]):
            d = lq["operators"][op]
            lines.append(f"  operator {op}: rows={d['rows']}, "
                         f"wall={d['wall_ms']:.1f}ms "
                         f"(device {d.get('device_ms', 0.0):.1f} + "
                         f"host {d.get('host_ms', 0.0):.1f} + "
                         f"compile {d.get('compile_ms', 0.0):.1f}), "
                         f"calls={d['calls']}")
        return QueryResult(["query plan"],
                           [(line,) for line in lines],
                           time.monotonic() - t0)

    # -- build stages ------------------------------------------------------

    def _bind_remotes(self, plan: L.PlanNode, materialized) -> L.PlanNode:
        from ..planner.fragmenter import _subtree_nodes
        mapping = {id(n): materialized[n.fragment_id]
                   for n in _subtree_nodes(plan)
                   if isinstance(n, L.RemoteSourceNode)}
        return L.replace_nodes(plan, mapping) if mapping else plan

    def _run_build_stage(self, plan: L.PlanNode) -> L.ValuesNode:
        """Execute one build fragment to completion and materialize its
        output as a broadcastable ValuesNode (REPLICATED distribution).
        Distributed over workers when the fragment's own driver table is
        split-worthy, else executed on the coordinator's devices."""
        from ..batch import batch_to_numpy
        out_node = L.OutputNode(plan, tuple(n for n, _ in plan.output),
                                plan.output)
        analysis = analyze(out_node, self.session.catalog, self.split_rows)
        workers = self.state.active_nodes()
        if analysis is not None and workers:
            pages = self._run_source_stage(workers, analysis, out_node)
            batch = self._merge_pages(out_node, analysis, pages)
        else:
            ex = self.session.executor
            batch = ex.run(plan)
        arrays, valids = batch_to_numpy(batch)
        # build output now lives on host inside the ValuesNode: drop the
        # device-side reservations the stage's plan-node runs took
        self.session.executor.release_all_reservations()
        return L.ValuesNode(arrays=tuple(arrays), valids=tuple(valids),
                            num_rows=len(arrays[0]) if arrays else 0,
                            fields=(), output=plan.output)

    def _merge_pages(self, root: L.OutputNode, analysis: ChunkAnalysis,
                     pages: List[dict]):
        """Merge source-stage partial pages and run the rest of the
        fragment — the FINAL step shared by build stages and the root
        stage. Partial-agg states re-aggregate with merge functions;
        concat-mode pages concatenate below the output node."""
        from ..batch import batch_from_numpy
        ex = self.session.executor
        saved = dict(ex._subst)
        saved_opaque = set(ex._subst_opaque)
        try:
            if analysis.merge_agg is not None:
                partials = []
                for p in pages:
                    arrs, vals = decode_columns(p)
                    if len(arrs) == 0 or len(arrs[0]) == 0:
                        continue
                    partials.append(batch_from_numpy(arrs, valids=vals))
                merged = merge_partials(ex, analysis.merge_agg, partials) \
                    if partials else self._empty_like(analysis.merge_agg)
                ex._subst[id(analysis.merge_agg)] = merged
                ex._subst_opaque.add(id(analysis.merge_agg))
            elif analysis.merge_sort is not None:
                arrs, vals = _merge_sorted_runs(
                    analysis.merge_sort, pages)
                ex._subst[id(analysis.merge_sort)] = batch_from_numpy(
                    arrs, valids=vals)
                ex._subst_opaque.add(id(analysis.merge_sort))
            else:
                from .tasks import concat_pages
                arrs, vals = concat_pages(pages, root.child.output)
                ex._subst[id(root.child)] = batch_from_numpy(
                    arrs, valids=vals)
                ex._subst_opaque.add(id(root.child))
            return ex.run(root.child)
        finally:
            ex._subst.clear()
            ex._subst.update(saved)
            ex._subst_opaque.clear()
            ex._subst_opaque.update(saved_opaque)

    # -- source stage ------------------------------------------------------

    def _make_splits(self, analysis: ChunkAnalysis) -> List[Split]:
        d = analysis.driver
        splits = [Split(d.catalog, d.schema_name, d.table, start,
                        min(self.split_rows, analysis.driver_rows - start))
                  for start in range(0, analysis.driver_rows,
                                     self.split_rows)]
        total = len(splits)
        # zone-map split pruning: drop row-range splits whose zones
        # provably cannot match the scan's pushed-down predicate — the
        # dispatch never happens (vs. the worker decoding the range and
        # filtering it to nothing). Advisory: the fragment's residual
        # filter makes dropping a MAY-match split unnecessary and keeping
        # a cannot-match split harmless.
        props = getattr(self.session, "properties", {})
        pred = getattr(d, "predicate", None)
        if pred is not None and props.get("enable_zone_map_pruning", True):
            try:
                from ..exec import zonemap
                data = self.session.catalog.get_table(
                    d.catalog, d.schema_name, d.table)
                zm = zonemap.zone_map_for(
                    data, props.get("zone_map_rows",
                                    zonemap.DEFAULT_ZONE_ROWS))
                kept = [s for s in splits
                        if zonemap.range_may_match(
                            zm, pred, d.column_indices, s.start, s.count)]
                # keep one split so every downstream merge path sees at
                # least one page; its residual filter drops all rows
                splits = kept or splits[:1]
            except Exception:   # noqa: BLE001 — pruning is best-effort
                pass
        pruned = total - len(splits)
        if pruned:
            self.stats["splits_pruned"] = \
                self.stats.get("splits_pruned", 0) + pruned
            SCAN_SPLITS_PRUNED.inc(pruned)
        lq = self.last_query
        if lq is not None:
            lq["splits_total"] = lq.get("splits_total", 0) + total
            lq["splits_pruned"] = lq.get("splits_pruned", 0) + pruned
        return splits

    def _run_source_stage(self, workers, analysis: ChunkAnalysis,
                          root: L.OutputNode) -> List[dict]:
        # agg mode: workers compute PARTIAL aggregates; sort mode: they
        # sort per split (sorted RUNS the coordinator n-way merges);
        # concat mode: they run everything below the output node
        fragment_root = analysis.merge_agg if analysis.merge_agg \
            is not None else (analysis.merge_sort
                              if analysis.merge_sort is not None
                              else root.child)
        frag = {"root": fragment_root, "driver": analysis.driver}
        if self._profile_tasks:
            # EXPLAIN ANALYZE: workers profile per-operator device time
            # (also keys the spool differently, so profiled runs never
            # reuse unprofiled spooled output)
            frag["profile"] = True
        blob = encode_fragment(frag)
        # the work key hashes (fragment, splits) but not data contents:
        # only deterministic generator sources may reuse spooled outputs
        # (a memory-connector table can change between attempts)
        use_spool = analysis.driver.catalog in ("tpch", "tpcds")
        splits = self._make_splits(analysis)
        # memory-aware placement: order workers by heartbeat-reported
        # reserved bytes so the round-robin lands extra splits on the
        # least-pressured nodes first (UniformNodeSelector weighted by
        # the ClusterMemoryManager's per-node view)
        workers = sorted(
            workers,
            key=lambda w: (getattr(w, "memory", None) or {}).get(
                "reserved", 0))
        # uniform assignment (UniformNodeSelector's round-robin core)
        assignment: Dict[str, List[Split]] = {w.node_id: [] for w in workers}
        by_id = {w.node_id: w for w in workers}
        for i, s in enumerate(splits):
            assignment[workers[i % len(workers)].node_id].append(s)

        pages: List[dict] = []
        pending = {nid: sp for nid, sp in assignment.items() if sp}
        retries = 0
        # backoff between retry rounds (decorrelated jitter): an
        # immediately-retried round lands on the same overloaded or
        # flapping survivors it just failed on
        backoff = RetryPolicy(self.retry_backoff_base_s,
                              self.retry_backoff_max_s,
                              max_attempts=self.max_task_retries + 2
                              ).delays()
        with self._tracer().span("source-stage", splits=len(splits),
                                 workers=len(workers)):
            pages = self._drain_rounds(pending, by_id, blob, use_spool,
                                       backoff)
        return pages

    def _drain_rounds(self, pending, by_id, blob, use_spool,
                      backoff) -> List[bytes]:
        pages: List[bytes] = []
        retries = 0
        migration_rounds = 0
        while pending:
            if self._query_dead():
                from ..exec.executor import QueryTerminatedError
                raise QueryTerminatedError(
                    "query terminated during stage drain")
            units: List[_HedgedUnit] = []
            for nid, sp in list(pending.items()):
                # durable-exchange hit: a prior attempt already produced
                # this work's output — consume the spool, skip dispatch
                key = self.spool.work_key(blob, sp)
                spooled = self.spool.get(key) if use_spool else None
                if spooled is not None:
                    pages.extend(spooled)
                    self.stats["spool_hits"] += 1
                    continue
                units.append(_HedgedUnit(nid, sp, key))
            failed_splits, failed_nodes, migrated = self._drain_units(
                units, by_id, blob, use_spool, pages)
            if not failed_splits:
                break
            if migrated:
                self.stats["splits_migrated"] += migrated
                SPLITS_MIGRATED.inc(migrated)
            if migrated == len(failed_splits):
                # pure drain handoff: the splits move to survivors
                # without burning retry budget, backoff, or the nodes'
                # detector records — the cluster is healthy, just
                # smaller. Bounded so a cluster draining faster than the
                # inventory updates cannot ping-pong forever.
                migration_rounds += 1
                if migration_rounds > 16:
                    raise TaskFailedError(
                        "drain handoff did not converge: " +
                        ", ".join(sorted(failed_nodes)))
            else:
                # task retry: reassign failed nodes' splits to survivors
                # (EventDrivenFaultTolerantQueryScheduler's per-task retry)
                self._amplify(1)
                retries += 1
                self.stats["task_retries"] += 1
                SCHED_TASK_RETRIES.inc()
                if retries > self.max_task_retries:
                    raise TaskFailedError(
                        "task retries exhausted: " +
                        ", ".join(sorted(failed_nodes)))
                time.sleep(next(backoff, self.retry_backoff_max_s))
            survivors = [w for w in self.state.active_nodes()
                         if w.node_id not in failed_nodes]
            if not survivors:
                raise TaskFailedError("no active workers left")
            workers = survivors
            by_id = {w.node_id: w for w in workers}
            redo: Dict[str, List[Split]] = {w.node_id: [] for w in workers}
            for i, s in enumerate(failed_splits):
                redo[workers[i % len(workers)].node_id].append(s)
            pending = {nid: sp for nid, sp in redo.items() if sp}
        return pages

    def _drain_units(self, units: List["_HedgedUnit"], by_id, blob: str,
                     use_spool: bool, pages: List[bytes]
                     ) -> Tuple[List[Split], Set[str], int]:
        """Dispatch and drain one round of work units CONCURRENTLY with
        straggler hedging. Successful units' pages append to `pages`
        (and spool, when eligible); returns (failed splits, failed node
        ids, migrated-split count) for the caller's retry round — a
        unit whose failures were ALL drain handoffs (409s from
        DRAINING workers) contributes to the migrated count and its
        nodes keep clean detector records.

        Hedging: once enough units complete to establish a median drain
        time, any unit still running past max(hedge_min_s, multiplier *
        median) gets a second, speculative attempt on a node it has not
        tried. The first successful attempt wins — a unit's attempts all
        compute the same deterministic split set, drains are
        all-or-nothing, and only the winning attempt's pages are kept
        (the spool's work-key dedup gives later query attempts the same
        guarantee) — so hedging can duplicate WORK but never RESULTS."""
        if not units:
            return [], set(), 0
        deadline = time.time() + self.task_timeout_s
        qd = self._query_deadline()
        if qd is not None:
            deadline = min(deadline, qd)
        lock = threading.Lock()
        durations: List[float] = []
        # capture the trace context ON THIS THREAD (the source-stage span
        # is open here; drain threads have empty span stacks)
        traceparent = self._tracer().traceparent()

        def attempt(unit: "_HedgedUnit", node) -> None:
            t0 = time.monotonic()
            with self._lock:
                self._seq += 1
                tid = f"t{self._seq}"
            task = RemoteTask(node, tid, blob, unit.splits,
                              injector=self.failure_injector,
                              traceparent=traceparent,
                              deadline=qd)
            with lock:
                unit.tasks.append(task)
            losers: List[RemoteTask] = []
            try:
                task.start()
                self._ledger_assign(task)
                self._livestats_register(task)
                self.stats["tasks"] += 1
                SCHED_TASKS.inc()
                drained = task.drain(deadline)
            except (TaskFailedError, InjectedFailure, URLError,
                    HTTPError, OSError) as e:
                if isinstance(e, HTTPError) and e.code == 409:
                    # drain handoff: the worker refused the POST because
                    # it is winding down. No _mark_failed (the node is
                    # healthy), no detector sample — the splits simply
                    # migrate to a survivor in the next round.
                    with lock:
                        unit.failed_nodes.add(node.node_id)
                        unit.drained_nodes.add(node.node_id)
                        unit.live -= 1
                    return
                if isinstance(e, PageIntegrityError):
                    self.stats["checksum_failures"] += 1
                task.cancel()
                self._mark_failed(node.node_id, e)
                with lock:
                    unit.failed_nodes.add(node.node_id)
                    unit.live -= 1
            else:
                with lock:
                    unit.live -= 1
                    if unit.pages is None:     # first success wins
                        unit.pages = drained
                        unit.winner = task
                        durations.append(time.monotonic() - t0)
                        losers = [t for t in unit.tasks if t is not task]
                        if unit.hedged and task is not unit.tasks[0]:
                            # the speculative attempt beat the original
                            self.stats["hedge_wins"] += 1
                            SCHED_HEDGE_WINS.inc()
                # abort outstanding hedge twins outside the lock — their
                # output is dropped either way
                for t in losers:
                    t.cancel()

        def launch(unit: "_HedgedUnit", node) -> None:
            with lock:
                unit.live += 1
                unit.nodes_used.add(node.node_id)
            t = threading.Thread(target=attempt, args=(unit, node),
                                 name=f"drain-{node.node_id}", daemon=True)
            t.start()

        for u in units:
            launch(u, by_id[u.first_node])

        while time.time() < deadline + 5.0:
            if self._query_dead():
                break    # terminate() fan-out already DELETEd the tasks
            with lock:
                unresolved = [u for u in units
                              if u.pages is None and u.live > 0]
                if not unresolved:
                    break
                med = statistics.median(durations) if durations else None
            # drain-aware hedging: a unit whose attempt is running on a
            # node the inventory now shows DRAINING hedges immediately —
            # the drain deadline may cut that attempt off, so a
            # survivor copy starts NOW instead of after the straggler
            # threshold (first success still wins either way)
            with self.state.nodes_lock:
                draining = {nid for nid, n in self.state.nodes.items()
                            if n.state in ("DRAINING", "DRAINED")}
            # live-evidence straggler feed (server/livestats.py): a
            # RUNNING task whose heartbeat-observed per-split pace trails
            # its stage peers past the hedge multiplier is treated like a
            # draining node — its unit hedges NOW on live skew evidence
            # rather than waiting out the wall-clock threshold
            live_skew: Set[str] = set()
            if self.livestats is not None:
                lq_qid = (self.last_query or {}).get("query_id")
                if lq_qid:
                    live_skew = self.livestats.straggler_task_ids(
                        lq_qid, self.hedge_multiplier)
            if self.hedge_multiplier > 0 and \
                    (med is not None or draining or live_skew):
                threshold = max(self.hedge_min_s,
                                self.hedge_multiplier * med) \
                    if med is not None else float("inf")
                now = time.monotonic()
                for u in unresolved:
                    candidate = None
                    with lock:
                        urgent = bool(u.nodes_used & draining) or \
                            any(t.task_id in live_skew
                                for t in u.tasks)
                        if u.hedged or u.pages is not None or \
                                (not urgent and
                                 now - u.started < threshold):
                            continue
                        for w in self.state.active_nodes():
                            if w.node_id not in u.nodes_used:
                                candidate = w
                                break
                        if candidate is None:
                            continue
                        u.hedged = True
                    if not self._amplify(required=False):
                        # amplification budget spent: no more hedges
                        # this query (the original attempt still runs)
                        continue
                    self.stats["hedged_tasks"] += 1
                    SCHED_HEDGES.inc()
                    launch(u, candidate)
            time.sleep(0.02)

        failed_splits: List[Split] = []
        failed_nodes: Set[str] = set()
        migrated = 0
        with lock:
            resolved = [(u, u.pages, u.winner) for u in units]
        for u, got, winner in resolved:
            if got is not None:
                pages.extend(got)
                if use_spool:
                    self.spool.put(u.key, got)
                    self._ledger_spool(u.key)
                if winner is not None:
                    # TaskStats + worker spans ride the terminal status —
                    # fetched HERE (main thread, before the stage
                    # returns) so the rollup is complete by the time the
                    # dispatcher publishes the completion event
                    self._record_task(winner)
            else:
                failed_splits.extend(u.splits)
                failed_nodes.update(u.failed_nodes or {u.first_node})
                if u.failed_nodes and \
                        u.failed_nodes <= u.drained_nodes:
                    migrated += len(u.splits)
        return failed_splits, failed_nodes, migrated

    def _mark_failed(self, node_id: str, err: Exception) -> None:
        with self.state.nodes_lock:
            n = self.state.nodes.get(node_id)
            if n is not None:
                n.state = "FAILED"
        # record the task-path failure into the heartbeat detector's
        # decayed stats too: without this, the node's very next
        # successful ping (or re-announce) flips it straight back to
        # ACTIVE even while its task executor is wedged — now the same
        # hysteresis that governs ping failures applies (it must sustain
        # several clean pings before rejoining the schedulable set)
        det = getattr(self.state, "failure_detector", None)
        if det is not None:
            det.record_failure(node_id)

    # -- final stage -------------------------------------------------------

    def _run_final_stage(self, rel, root: L.OutputNode,
                         analysis: ChunkAnalysis, pages: List[dict]):
        from ..exec.session import QueryResult
        ex = self.session.executor
        batch = self._merge_pages(root, analysis, pages)
        names, arrays, valids = ex.result_to_host(root, batch)
        rows = self.session.decode_rows(rel, arrays, valids)
        # the merge ran plan nodes outside execute(): release their pool
        # reservations now that the result is host rows — otherwise a
        # stream of distributed queries leaks the pool dry
        ex.release_all_reservations()
        return QueryResult(names, rows, 0.0, ex.stats)

    def _empty_like(self, agg: L.AggregateNode):
        from ..batch import batch_from_numpy
        arrs = [np.zeros(0, dtype=dt.np_dtype) for _, dt in agg.output]
        return batch_from_numpy(arrs)

    # -- partitioned worker<->worker exchange ------------------------------
    #
    # A 3-stage tree (PipelinedQueryScheduler's FIXED_HASH_DISTRIBUTION
    # path): stage A streams the probe side's splits and hash-partitions
    # its output by the join keys into P buffers; stage B does the same
    # for the build side; stage C runs P exchange-consumer tasks, task p
    # pulling buffer p from EVERY upstream task (worker<->worker binary
    # page frames, DirectExchangeClient.java:56) and running
    # join+partial-agg on its co-partitioned slice; the coordinator FINAL
    # merges. Pulls overlap production: C tasks start with A/B and poll
    # buffers until upstream completes.

    def _analyze_partitioned(self, root: L.OutputNode):
        """Match Agg(Filter/Project*(Join(probe, build))) where BOTH join
        sides contain split-worthy scans and every join key is integer-
        typed (dictionary varchar codes are per-table, so hash routing
        on them would be inconsistent across tables). Returns (join,
        merge_agg, probe_driver, build_driver) or None."""
        from ..exec.chunked import MERGE_FUNC
        from ..planner.fragmenter import _scan_rows, _subtree_nodes
        # phase 1 — above the merge point: Sort/Limit/Filter/Project all
        # run on the coordinator after the merge, so they may be skipped
        node = root.child
        merge_agg = None
        while isinstance(node, (L.FilterNode, L.ProjectNode,
                                L.SortNode, L.LimitNode)):
            node = node.child
        if isinstance(node, L.AggregateNode):
            if any(a.distinct for a in node.aggs) or \
                    any(a.func not in MERGE_FUNC for a in node.aggs):
                return None
            merge_agg = node
            node = node.child
        if merge_agg is None:     # concat-mode repartition needs ordered
            return None           # merge support; agg merge only for now
        # phase 2 — below the merge point, INSIDE the consumer fragment:
        # only order-insensitive nodes are allowed (a Sort/Limit here
        # would compute per-partition top-N, not global)
        while isinstance(node, (L.FilterNode, L.ProjectNode)):
            node = node.child
        if not isinstance(node, L.JoinNode) or node.null_aware or \
                node.kind not in ("inner", "left", "semi", "anti"):
            return None
        join = node
        for side, keys in ((join.left, join.left_keys),
                           (join.right, join.right_keys)):
            for k in keys:
                dt = side.output[k][1]
                if not np.issubdtype(np.dtype(dt.np_dtype), np.integer):
                    return None

        def driver_of(side):
            scans = [n for n in _subtree_nodes(side)
                     if isinstance(n, L.ScanNode)]
            if not scans:
                return None
            d = max(scans, key=lambda s: _scan_rows(
                self.session.catalog, s))
            return d if _scan_rows(self.session.catalog, d) > \
                self.split_rows else None

        probe_driver = driver_of(join.left)
        build_driver = driver_of(join.right)
        if probe_driver is None or build_driver is None:
            return None
        # the worker streams splits of the driver scan; everything else
        # in the side's subtree must be split-invariant (pinned)
        for side, driver in ((join.left, probe_driver),
                             (join.right, build_driver)):
            an = analyze(L.OutputNode(side, tuple(n for n, _ in
                                                  side.output),
                                      side.output),
                         self.session.catalog, self.split_rows)
            if an is None or an.driver is not driver:
                return None
        return join, merge_agg, probe_driver, build_driver

    def _execute_partitioned(self, rel, root: L.OutputNode, workers,
                             desc):
        join, merge_agg, probe_driver, build_driver = desc
        P = len(workers)
        t_deadline = time.time() + self.task_timeout_s
        qd = self._query_deadline()
        if qd is not None:
            t_deadline = min(t_deadline, qd)
        traceparent = self._tracer().traceparent()

        def stage_tasks(side_root, driver, keys):
            blob = encode_fragment({"root": side_root, "driver": driver})
            rows = self.session.catalog.get_table(
                driver.catalog, driver.schema_name, driver.table).num_rows
            splits = [Split(driver.catalog, driver.schema_name,
                            driver.table, start,
                            min(self.split_rows, rows - start))
                      for start in range(0, rows, self.split_rows)]
            tasks = []
            for wi, w in enumerate(workers):
                sp = [s for i, s in enumerate(splits)
                      if i % len(workers) == wi]
                if not sp:
                    continue
                with self._lock:
                    self._seq += 1
                    tid = f"t{self._seq}"
                task = RemoteTask(w, tid, blob, sp,
                                  partition={"keys": list(keys),
                                             "count": P},
                                  injector=self.failure_injector,
                                  traceparent=traceparent,
                                  deadline=qd)
                task.start()
                self._ledger_assign(task)
                self._livestats_register(task)
                self.stats["tasks"] += 1
                SCHED_TASKS.inc()
                tasks.append(task)
            return tasks

        a_tasks = stage_tasks(join.left, probe_driver, join.left_keys)
        b_tasks = stage_tasks(join.right, build_driver, join.right_keys)

        rs_a = L.RemoteSourceNode(1, join.left.output)
        rs_b = L.RemoteSourceNode(2, join.right.output)
        c_root = L.replace_nodes(
            merge_agg, {id(join.left): rs_a, id(join.right): rs_b})
        blob_c = encode_fragment({"root": c_root,
                                  "timeout_s": self.task_timeout_s})
        c_tasks = []
        for p in range(P):
            sources = {
                "1": [{"uri": t.node.uri, "taskId": t.task_id,
                       "buffer": p} for t in a_tasks],
                "2": [{"uri": t.node.uri, "taskId": t.task_id,
                       "buffer": p} for t in b_tasks],
            }
            with self._lock:
                self._seq += 1
                tid = f"t{self._seq}"
            task = RemoteTask(workers[p % len(workers)], tid, blob_c, [],
                              sources=sources,
                              injector=self.failure_injector,
                              traceparent=traceparent,
                              deadline=qd)
            task.start()
            self._ledger_assign(task)
            self._livestats_register(task)
            self.stats["tasks"] += 1
            SCHED_TASKS.inc()
            c_tasks.append(task)

        pages: List[bytes] = []
        try:
            for t in c_tasks:
                pages.extend(t.drain(t_deadline))
            for t in a_tasks + b_tasks:
                t.wait_finished(t_deadline)
        except Exception:
            for t in a_tasks + b_tasks + c_tasks:
                t.cancel()
            raise
        for t in a_tasks + b_tasks + c_tasks:
            self._record_task(t)
        self.stats["stages"] = self.stats.get("stages", 0) + 4
        self.stats["partitioned_joins"] = \
            self.stats.get("partitioned_joins", 0) + 1
        shim = ChunkAnalysis(None, merge_agg, [], 0)
        return self._run_final_stage(rel, root, shim, pages)
