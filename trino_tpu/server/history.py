"""Query history store + latency-regression detection.

Reference: the reference engine keeps completed QueryInfo in the
QueryTracker's bounded ring (query.min-expire-age) and ships
QueryCompletedEvents to listener plugins; slow-query logs and history
tables are built on top of that event stream. Here both live
coordinator-side: a persistent JSONL ring of completed-query records
keyed by *plan fingerprint* (normalized statement hash), and a detector
that compares each completed query's latency / bytes-shuffled / spill
counters against its fingerprint's robust baseline (median + MAD — the
estimator that ignores a few outliers instead of chasing them).

Flow: QueryCompletedEvent -> HistoryEventListener -> store.record()
(dedup by query id; the QueryTracker's eviction flush calls the same
path, so stats survive the tracker's max_history cap). A flagged
regression emits one slow-query log line, increments
trino_tpu_query_latency_regressions_total, and marks the record —
`system.runtime.query_history` serves the ring, and
`bench.py --check-regressions` applies the same median+MAD rule across
BENCH_r*.json rounds.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import threading
import time
from collections import deque
from typing import Dict, List, Optional

log = logging.getLogger("trino_tpu.history")

# per-fingerprint baseline rules (shared with bench --check-regressions):
# flag when the value exceeds BOTH the ratio gate (median * RATIO) and
# the robust spread gate (median + MAD_K * 1.4826 * MAD) — the ratio
# alone fires on tiny-median jitter, the MAD alone on tight baselines
MIN_BASELINE = 5            # prior finished records before judging
RATIO = 2.0
MAD_K = 6.0
MAD_SCALE = 1.4826          # MAD -> sigma for normal data
# per-metric floors below which differences are noise, not regressions
FLOORS = {"elapsed_s": 0.005, "bytes_shuffled": 1 << 16, "spills": 0}
METRICS = ("elapsed_s", "bytes_shuffled", "spills")


def plan_fingerprint(sql: str) -> str:
    """Stable statement-shape key: normalized SQL text (lower-cased,
    whitespace-collapsed, trailing ';' stripped), hashed. Two
    submissions of the same statement share a fingerprint regardless of
    formatting — the history analog of the executor's wire-form plan
    hash, computable without planning."""
    norm = re.sub(r"\s+", " ", sql.strip().rstrip(";").lower())
    return hashlib.sha256(norm.encode()).hexdigest()[:16]


def robust_baseline(values: List[float]) -> tuple:
    """(median, MAD) of a sample."""
    vs = sorted(values)
    n = len(vs)
    med = vs[n // 2] if n % 2 else (vs[n // 2 - 1] + vs[n // 2]) / 2
    devs = sorted(abs(v - med) for v in vs)
    mad = devs[n // 2] if n % 2 else (devs[n // 2 - 1] + devs[n // 2]) / 2
    return med, mad


def is_regressed(value: float, median: float, mad: float,
                 floor: float = 0.0, ratio: float = RATIO,
                 mad_k: float = MAD_K) -> bool:
    """The shared regression rule (history detector AND the bench
    gate): past the ratio gate AND outside the MAD envelope, with a
    floor so sub-noise medians never judge."""
    if median <= floor:
        return False
    return value > median * ratio and \
        (value - median) > max(mad_k * MAD_SCALE * mad, 0.05 * median)


def _default_path() -> str:
    env = os.environ.get("TRINO_TPU_HISTORY_PATH")
    if env:
        return env
    from ..connectors.diskcache import cache_root
    return os.path.join(cache_root(), "query_history.jsonl")


class QueryHistoryStore:
    """Persistent JSONL ring of completed-query records.

    One record per completed query: {query_id, fingerprint, sql, state,
    user, elapsed_s, rows, bytes_shuffled, spills, end_time,
    regressed}. The file is append-only until the ring overflows, then
    rewritten atomically from the in-memory tail — corruption or a
    missing file just means an empty baseline, never an error."""

    PER_FINGERPRINT = 64        # baseline window per statement shape

    def __init__(self, path: Optional[str] = None,
                 max_records: int = 4096):
        self.path = _default_path() if path is None else path
        self.max_records = max_records
        self._lock = threading.Lock()
        self.records: "deque[dict]" = deque(maxlen=max_records)
        self._by_fp: Dict[str, "deque[dict]"] = {}
        self._ids: set = set()
        self._appended_since_rewrite = 0
        self._load()

    # -- persistence -------------------------------------------------------

    def _load(self) -> None:
        if not self.path or not os.path.isfile(self.path):
            return
        try:
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue          # torn write: skip the line
                    if isinstance(rec, dict) and rec.get("query_id"):
                        self._remember(rec)
        except OSError:
            pass

    def _remember(self, rec: dict) -> None:
        self.records.append(rec)
        self._ids.add(rec["query_id"])
        fp = rec.get("fingerprint", "")
        dq = self._by_fp.get(fp)
        if dq is None:
            dq = self._by_fp[fp] = deque(maxlen=self.PER_FINGERPRINT)
        dq.append(rec)

    def _append_file(self, rec: dict) -> None:
        if not self.path:
            return
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            # ring rewrite: once the file has grown well past the
            # in-memory cap, rewrite it from the retained tail so the
            # on-disk ring stays bounded too
            self._appended_since_rewrite += 1
            if self._appended_since_rewrite >= self.max_records:
                tmp = self.path + f".tmp{os.getpid()}"
                with open(tmp, "w") as f:
                    for r in self.records:
                        f.write(json.dumps(r) + "\n")
                os.replace(tmp, self.path)
                self._appended_since_rewrite = 0
                return
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            pass                          # history is best-effort

    # -- recording + detection ---------------------------------------------

    def baseline(self, fingerprint: str,
                 metric: str = "elapsed_s") -> Optional[tuple]:
        """(median, mad, n) over this fingerprint's prior FINISHED
        records, or None below the minimum sample size."""
        with self._lock:
            vals = [float(r.get(metric, 0) or 0)
                    for r in self._by_fp.get(fingerprint, ())
                    if r.get("state") == "FINISHED"]
        if len(vals) < MIN_BASELINE:
            return None
        med, mad = robust_baseline(vals)
        return med, mad, len(vals)

    def check(self, rec: dict) -> Optional[dict]:
        """Compare one completed record against its fingerprint's
        baseline; returns {metric, value, median, mad, n} for the first
        regressed metric, or None."""
        if rec.get("state") != "FINISHED":
            return None
        fp = rec.get("fingerprint", "")
        for metric in METRICS:
            base = self.baseline(fp, metric)
            if base is None:
                continue
            med, mad, n = base
            val = float(rec.get(metric, 0) or 0)
            if is_regressed(val, med, mad, floor=FLOORS.get(metric, 0)):
                return {"metric": metric, "value": val, "median": med,
                        "mad": mad, "n": n}
        return None

    def record(self, rec: dict) -> Optional[dict]:
        """Append one completed-query record (idempotent per query id);
        returns the regression verdict when the detector flags it."""
        if not rec.get("query_id"):
            return None
        rec = dict(rec)
        rec.setdefault("fingerprint", plan_fingerprint(rec.get("sql", "")))
        rec.setdefault("end_time", time.time())
        with self._lock:
            if rec["query_id"] in self._ids:
                return None               # completion event already did it
        regression = self.check(rec)
        rec["regressed"] = bool(regression)
        with self._lock:
            if rec["query_id"] in self._ids:
                return None
            self._remember(rec)
            self._append_file(rec)
        from ..metrics import HISTORY_RECORDS, LATENCY_REGRESSIONS
        HISTORY_RECORDS.inc()
        if regression:
            LATENCY_REGRESSIONS.inc()
            from ..utils.log import query_context
            dominant = rec.get("dominant_phase") or "unattributed"
            log.warning(
                "%sslow query (fingerprint %s): %s=%.4g vs baseline "
                "median %.4g (MAD %.4g over %d runs), wall dominated by "
                "%s: %s",
                query_context(rec["query_id"]), rec["fingerprint"],
                regression["metric"], regression["value"],
                regression["median"], regression["mad"],
                regression["n"], dominant, (rec.get("sql") or "")[:200])
        return regression

    def record_tracked(self, tq) -> None:
        """Eviction flush (QueryTracker.on_evict): persist a tracked
        query's stats before the tracker forgets it. A no-op when the
        completion event already recorded the query."""
        try:
            st = getattr(tq, "stage_stats", None) or {}
            self.record({
                "query_id": tq.query_id,
                "sql": tq.sql,
                "user": tq.session_user,
                "tenant": getattr(tq, "tenant", "default"),
                "state": tq.state,
                "elapsed_s": float(tq.elapsed_s),
                "rows": int(tq.rows_returned),
                "bytes_shuffled": int(st.get("bytes_shuffled", 0)),
                "spills": int(getattr(tq, "spills", 0)),
                "dominant_phase": (getattr(tq, "timeline", None) or
                                   {}).get("dominant", ""),
                # live-observability post-mortem context: how far the
                # query got (1.0 when FINISHED) and the stage that held
                # the most in-flight work when it ended — the fields an
                # OOM-killed query's autopsy starts from
                "progress_ratio": (1.0 if tq.state == "FINISHED" else
                                   float(getattr(tq, "progress_ratio",
                                                 0.0))),
                "dominant_stage": getattr(tq, "dominant_stage", ""),
            })
        except Exception:    # noqa: BLE001 — eviction must never fail
            log.exception("history eviction flush failed for %s",
                          getattr(tq, "query_id", "?"))

    # -- read surface ------------------------------------------------------

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self.records]

    def top_fingerprints(self, n: int = 8) -> List[dict]:
        """Rank fingerprints by frequency x recency over the ring — the
        prewarm engine's pick list (exec/prewarm.py) and the rank/score
        columns of system.runtime.query_history.

        Score: each FINISHED record contributes 2^(-age/half_life)
        with a one-hour half life, so a shape run 50 times yesterday
        still outranks a one-off from a minute ago, but dead shapes
        decay out of the top-N instead of pinning prewarm budget
        forever. Returns [{fingerprint, sql, count, last_end_time,
        score}] best-first; `sql` is the most recent FINISHED text for
        the shape (what prewarm re-plans)."""
        half_life_s = 3600.0
        now = time.time()
        agg: Dict[str, dict] = {}
        with self._lock:
            for r in self.records:
                if r.get("state") != "FINISHED":
                    continue
                fp = r.get("fingerprint", "")
                if not fp or not r.get("sql"):
                    continue
                end = float(r.get("end_time", now) or now)
                ent = agg.get(fp)
                if ent is None:
                    ent = agg[fp] = {"fingerprint": fp, "sql": r["sql"],
                                     "count": 0, "last_end_time": 0.0,
                                     "score": 0.0}
                ent["count"] += 1
                ent["score"] += 2.0 ** (-max(0.0, now - end) /
                                        half_life_s)
                if end >= ent["last_end_time"]:
                    ent["last_end_time"] = end
                    ent["sql"] = r["sql"]
        ranked = sorted(agg.values(),
                        key=lambda e: (-e["score"], -e["count"],
                                       e["fingerprint"]))
        return ranked[:max(0, int(n))]

    def for_fingerprint(self, fingerprint: str) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._by_fp.get(fingerprint, ())]

    def __len__(self) -> int:
        with self._lock:
            return len(self.records)


class HistoryEventListener:
    """EventListener feeding the store from QueryCompletedEvent — the
    same SPI surface billing/SLO listeners use, so history never needs
    to scrape /v1/query."""

    def __init__(self, store: QueryHistoryStore):
        self.store = store

    def query_created(self, event) -> None:
        pass

    def query_completed(self, event) -> None:
        self.store.record({
            "query_id": event.query_id,
            "sql": event.sql,
            "user": event.user,
            "tenant": getattr(event, "tenant", "default"),
            "state": event.state,
            "elapsed_s": float(event.elapsed_s),
            "rows": int(event.rows),
            "bytes_shuffled": int(event.bytes_shuffled),
            "spills": int(getattr(event, "spills", 0)),
            "dominant_phase": getattr(event, "dominant_phase", ""),
            "progress_ratio": float(getattr(event, "progress_ratio",
                                            0.0)),
            "dominant_stage": getattr(event, "dominant_stage", ""),
            "end_time": event.end_time,
        })
