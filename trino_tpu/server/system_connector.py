"""System connector: runtime tables queryable via SQL.

Reference: the `system` catalog (connector/system/ in trino-main — 86
files) exposing system.runtime.queries / .nodes backed by live engine
state, plus the task and operator-stats views EXPLAIN ANALYZE and the
web UI read. Registered by the coordinator with its tracker + node
inventory + stage scheduler, so `SELECT * FROM system.runtime.tasks`
shows the recent remote-task rollup (TaskStats merged back from workers)
and `system.runtime.operator_stats` the per-(query, operator) aggregates.
"""

from __future__ import annotations

import numpy as np

from ..batch import Field, Schema
from ..catalog import _strings_table
from ..connectors.tpch.datagen import TableData
from ..types import BIGINT, DOUBLE


class SystemConnector:
    name = "system"

    def __init__(self, coordinator_state=None):
        self.state = coordinator_state

    def schema_names(self):
        return ["runtime"]

    def table_names(self, schema: str):
        if schema == "runtime":
            return ["queries", "nodes", "tasks", "operator_stats",
                    "resource_groups", "jit_cache", "query_history",
                    "plan_cache", "query_timeline", "metrics_history",
                    "live_queries", "utilization"]
        return []

    def get_table(self, schema: str, table: str) -> TableData:
        if schema != "runtime":
            raise KeyError(f"system schema {schema!r} not found")
        if table == "queries":
            return self._queries_table()
        if table == "nodes":
            return self._nodes_table()
        if table == "tasks":
            return self._tasks_table()
        if table == "operator_stats":
            return self._operator_stats_table()
        if table == "resource_groups":
            return self._resource_groups_table()
        if table == "jit_cache":
            return self._jit_cache_table()
        if table == "query_history":
            return self._query_history_table()
        if table == "plan_cache":
            return self._plan_cache_table()
        if table == "query_timeline":
            return self._query_timeline_table()
        if table == "metrics_history":
            return self._metrics_history_table()
        if table == "live_queries":
            return self._live_queries_table()
        if table == "utilization":
            return self._utilization_table()
        raise KeyError(f"system table {table!r} not found")

    def _scheduler(self):
        return getattr(self.state, "scheduler", None) if self.state \
            else None

    def _livestats(self):
        return getattr(self.state, "livestats", None) if self.state \
            else None

    def _queries_table(self) -> TableData:
        queries = self.state.tracker.all() if self.state else []
        ids = [q.query_id for q in queries]
        states = [q.state for q in queries]
        users = [q.session_user for q in queries]
        sqls = [q.sql[:200] for q in queries]
        base = _strings_table("queries",
                              [("query_id", ids), ("state", states),
                               ("user", users), ("query", sqls)])
        elapsed = np.array([q.elapsed_s for q in queries],
                           dtype=np.float64)
        rows = np.array([q.rows_returned for q in queries],
                        dtype=np.int64)
        return TableData(
            "queries",
            Schema(base.schema.fields +
                   (Field("elapsed_seconds", DOUBLE),
                    Field("rows", BIGINT))),
            base.columns + [elapsed, rows])

    def _nodes_table(self) -> TableData:
        """Node inventory + each worker's last heartbeat-reported memory
        pool and live device/HBM allocator stats (zeros until the first
        heartbeat lands, and always zero off-TPU)."""
        nodes = list(self.state.nodes.values()) if self.state else []
        base = _strings_table(
            "nodes",
            [("node_id", [n.node_id for n in nodes]),
             ("http_uri", [n.uri for n in nodes]),
             ("state", [n.state for n in nodes])])
        mem = [getattr(n, "memory", None) or {} for n in nodes]
        dev = [getattr(n, "device", None) or {} for n in nodes]
        reserved = np.array([int(m.get("reserved", 0)) for m in mem],
                            dtype=np.int64)
        revocable = np.array([int(m.get("revocable", 0)) for m in mem],
                             dtype=np.int64)
        in_use = np.array([int(d.get("bytesInUse", 0)) for d in dev],
                          dtype=np.int64)
        limit = np.array([int(d.get("bytesLimit", 0)) for d in dev],
                         dtype=np.int64)
        peak = np.array([int(d.get("peakBytesInUse", 0)) for d in dev],
                        dtype=np.int64)
        return TableData(
            "nodes",
            Schema(base.schema.fields +
                   (Field("reserved_bytes", BIGINT),
                    Field("revocable_bytes", BIGINT),
                    Field("device_bytes_in_use", BIGINT),
                    Field("device_bytes_limit", BIGINT),
                    Field("device_peak_bytes", BIGINT))),
            base.columns + [reserved, revocable, in_use, limit, peak])

    def _tasks_table(self) -> TableData:
        """Recent remote tasks with their merged TaskStats (the
        system.runtime.tasks view of the reference). Live records from
        the heartbeat fold (server/livestats.py) lead the view, so
        in-flight tasks are queryable BEFORE their terminal stats are
        drained back — the reference's tasks view is live the same way."""
        sched = self._scheduler()
        recs = list(sched.task_history) if sched is not None else []
        ls = self._livestats()
        if ls is not None:
            seen = {r["task_id"] for r in recs}
            live = [{"query_id": r.get("query_id") or "",
                     "task_id": r["task_id"], "node": r.get("node", ""),
                     "stage": r.get("stage", ""),
                     "state": r.get("state", ""),
                     "splits": int(r.get("splits_done", 0)),
                     "rows": int(r.get("rows", 0)),
                     "bytes": int(r.get("bytes", 0)),
                     "wall_ms": float(r.get("wall_ms", 0.0))}
                    for r in ls.live_tasks()
                    if r["task_id"] not in seen]
            recs = live + recs
        base = _strings_table(
            "tasks",
            [("query_id", [r["query_id"] for r in recs]),
             ("task_id", [r["task_id"] for r in recs]),
             ("node_id", [r["node"] for r in recs]),
             ("stage", [r["stage"] for r in recs]),
             ("state", [r["state"] for r in recs])])
        splits = np.array([r["splits"] for r in recs], dtype=np.int64)
        rows = np.array([r["rows"] for r in recs], dtype=np.int64)
        byts = np.array([r["bytes"] for r in recs], dtype=np.int64)
        wall = np.array([r["wall_ms"] for r in recs], dtype=np.float64)
        return TableData(
            "tasks",
            Schema(base.schema.fields +
                   (Field("splits", BIGINT), Field("rows", BIGINT),
                    Field("bytes", BIGINT), Field("wall_ms", DOUBLE))),
            base.columns + [splits, rows, byts, wall])

    def _resource_groups_table(self) -> TableData:
        """Live admission state per group — concurrency, queue depth,
        queue-wait totals, and the memory-aware admission fields
        (system.runtime view of resourcegroups.ResourceGroupManager)."""
        rgm = getattr(getattr(self.state, "dispatcher", None),
                      "resource_groups", None) if self.state else None
        recs = rgm.info() if rgm is not None else []
        base = _strings_table(
            "resource_groups",
            [("group_name", [r["group"] for r in recs])])
        running = np.array([r["running"] for r in recs], dtype=np.int64)
        queued = np.array([r["queued"] for r in recs], dtype=np.int64)
        limit = np.array([r["hardConcurrencyLimit"] for r in recs],
                         dtype=np.int64)
        admitted = np.array([r["totalAdmitted"] for r in recs],
                            dtype=np.int64)
        soft = np.array([r["softMemoryLimitBytes"] or 0 for r in recs],
                        dtype=np.int64)
        mem = np.array([r["memoryUsageBytes"] for r in recs],
                       dtype=np.int64)
        wait = np.array([r["totalQueueWaitSeconds"] for r in recs],
                        dtype=np.float64)
        return TableData(
            "resource_groups",
            Schema(base.schema.fields +
                   (Field("running", BIGINT), Field("queued", BIGINT),
                    Field("hard_concurrency_limit", BIGINT),
                    Field("total_admitted", BIGINT),
                    Field("soft_memory_limit_bytes", BIGINT),
                    Field("memory_usage_bytes", BIGINT),
                    Field("total_queue_wait_seconds", DOUBLE))),
            base.columns + [running, queued, limit, admitted, soft, mem,
                            wait])

    def _operator_stats_table(self) -> TableData:
        """Per-(query, operator) rollup from worker TaskStats — the
        operator half of the OperatorStats pyramid, queryable like the
        reference's optimizer_rule_stats/operator views. Profiled runs
        (EXPLAIN ANALYZE / enable_profiling) carry the fenced
        device/host/compile wall split; unprofiled rows read 0."""
        sched = self._scheduler()
        recs = list(sched.operator_history) if sched is not None else []
        base = _strings_table(
            "operator_stats",
            [("query_id", [r["query_id"] for r in recs]),
             ("operator", [r["operator"] for r in recs]),
             ("strategy", [r.get("strategy", "") for r in recs]),
             ("distribution", [r.get("distribution", "")
                               for r in recs])])
        rows = np.array([r["rows"] for r in recs], dtype=np.int64)
        wall = np.array([r["wall_ms"] for r in recs], dtype=np.float64)
        calls = np.array([r["calls"] for r in recs], dtype=np.int64)
        device = np.array([r.get("device_ms", 0.0) for r in recs],
                          dtype=np.float64)
        host = np.array([r.get("host_ms", 0.0) for r in recs],
                        dtype=np.float64)
        compile_ = np.array([r.get("compile_ms", 0.0) for r in recs],
                            dtype=np.float64)
        return TableData(
            "operator_stats",
            Schema(base.schema.fields +
                   (Field("rows", BIGINT), Field("wall_ms", DOUBLE),
                    Field("calls", BIGINT),
                    Field("device_ms", DOUBLE),
                    Field("host_ms", DOUBLE),
                    Field("compile_ms", DOUBLE))),
            base.columns + [rows, wall, calls, device, host, compile_])

    def _jit_cache_table(self) -> TableData:
        """The process compile recorder's per-(site, fingerprint)
        aggregates (exec/profiler.py) — the SQL twin of GET /v1/jit."""
        from ..exec.profiler import RECORDER
        recs = RECORDER.snapshot()
        base = _strings_table(
            "jit_cache",
            [("site", [r["site"] for r in recs]),
             ("fingerprint", [r["fingerprint"] for r in recs])])
        compiles = np.array([r["compiles"] for r in recs],
                            dtype=np.int64)
        hits = np.array([r["hits"] for r in recs], dtype=np.int64)
        total_ms = np.array([r["compile_ms"] for r in recs],
                            dtype=np.float64)
        last_ms = np.array([r["last_compile_ms"] for r in recs],
                           dtype=np.float64)
        prewarmed = np.array([int(bool(r.get("prewarmed"))) for r in recs],
                             dtype=np.int64)
        prewarm_hits = np.array([int(r.get("prewarm_hits", 0))
                                 for r in recs], dtype=np.int64)
        return TableData(
            "jit_cache",
            Schema(base.schema.fields +
                   (Field("compiles", BIGINT),
                    Field("cache_hits", BIGINT),
                    Field("compile_ms", DOUBLE),
                    Field("last_compile_ms", DOUBLE),
                    Field("prewarmed", BIGINT),
                    Field("prewarm_hits", BIGINT))),
            base.columns + [compiles, hits, total_ms, last_ms,
                            prewarmed, prewarm_hits])

    def _plan_cache_table(self) -> TableData:
        """The serving layer's logical-plan cache (server/serving.py):
        one row per cached plan with its fingerprint, hit count, and
        byte-cap weight — the SQL twin of the plan-cache metrics."""
        serving = getattr(getattr(self.state, "dispatcher", None),
                          "serving", None) if self.state else None
        recs = serving.plan_cache.snapshot() if serving is not None \
            else []
        base = _strings_table(
            "plan_cache",
            [("fingerprint", [r["fingerprint"] for r in recs]),
             ("query", [r["sql"] for r in recs])])
        hits = np.array([r["hits"] for r in recs], dtype=np.int64)
        weight = np.array([r["weight_bytes"] for r in recs],
                          dtype=np.int64)
        point = np.array([int(r["point_shape"]) for r in recs],
                         dtype=np.int64)
        cacheable = np.array([int(r["cacheable"]) for r in recs],
                             dtype=np.int64)
        return TableData(
            "plan_cache",
            Schema(base.schema.fields +
                   (Field("hits", BIGINT),
                    Field("weight_bytes", BIGINT),
                    Field("point_shape", BIGINT),
                    Field("result_cacheable", BIGINT))),
            base.columns + [hits, weight, point, cacheable])

    def _query_timeline_table(self) -> TableData:
        """Per-(query, phase) wall attribution from the critical-path
        analyzer (server/timeline.py) — one row per phase per tracked
        query, phases summing exactly to elapsed wall, plus the
        dominant phase label repeated on each row for easy filtering."""
        from .timeline import PHASES, build_timeline
        queries = self.state.tracker.all() if self.state else []
        rows = []
        for q in queries:
            tl = q.timeline
            if tl is None and q.state_machine.is_done():
                try:
                    tl = build_timeline(q)
                except Exception:  # noqa: BLE001 — view is best-effort
                    tl = None
            if tl is None:
                continue
            for ph in PHASES:
                rows.append((q.query_id, ph, tl["phases"].get(ph, 0.0),
                             tl["dominant"], tl["wall_s"],
                             tl["criticalPathSeconds"]))
        base = _strings_table(
            "query_timeline",
            [("query_id", [r[0] for r in rows]),
             ("phase", [r[1] for r in rows]),
             ("dominant", [r[3] for r in rows])])
        seconds = np.array([r[2] for r in rows], dtype=np.float64)
        wall = np.array([r[4] for r in rows], dtype=np.float64)
        cp = np.array([r[5] for r in rows], dtype=np.float64)
        return TableData(
            "query_timeline",
            Schema(base.schema.fields +
                   (Field("seconds", DOUBLE),
                    Field("wall_seconds", DOUBLE),
                    Field("critical_path_seconds", DOUBLE))),
            base.columns + [seconds, wall, cp])

    def _metrics_history_table(self) -> TableData:
        """The cluster flight recorder's federated time series
        (server/telemetry.py) — one row per (timestamp, node, metric)
        sample. Reading the table triggers a collection round so the
        view is current even without the background federation thread."""
        tel = getattr(self.state, "telemetry", None) if self.state \
            else None
        recs = []
        if tel is not None:
            try:
                tel.collect()
            except Exception:  # noqa: BLE001 — scrape is best-effort
                pass
            recs = tel.rows()
        base = _strings_table(
            "metrics_history",
            [("node_id", [r[1] for r in recs]),
             ("metric", [r[2] for r in recs])])
        ts = np.array([r[0] for r in recs], dtype=np.float64)
        value = np.array([r[3] for r in recs], dtype=np.float64)
        return TableData(
            "metrics_history",
            Schema(base.schema.fields +
                   (Field("ts", DOUBLE), Field("value", DOUBLE))),
            base.columns + [ts, value])

    def _live_queries_table(self) -> TableData:
        """In-flight query summaries from the live-stats fold
        (server/livestats.py): split-weighted progress, per-stage task
        and split counts, and the stuck-query diagnosis — the SQL twin
        of the web UI's live cluster overview."""
        ls = self._livestats()
        recs = ls.live_queries() if ls is not None else []
        base = _strings_table(
            "live_queries",
            [("query_id", [r["query_id"] for r in recs]),
             ("state", [r["state"] for r in recs]),
             ("stuck_stage", [r["diagnosis"] for r in recs])])
        progress = np.array([r["progress"] for r in recs],
                            dtype=np.float64)
        stages = np.array([r["stages"] for r in recs], dtype=np.int64)
        tasks = np.array([r["tasks"] for r in recs], dtype=np.int64)
        tasks_done = np.array([r["tasks_done"] for r in recs],
                              dtype=np.int64)
        splits_done = np.array([r["splits_done"] for r in recs],
                               dtype=np.int64)
        splits_total = np.array([r["splits_total"] for r in recs],
                                dtype=np.int64)
        rows = np.array([r["rows"] for r in recs], dtype=np.int64)
        byts = np.array([r["bytes"] for r in recs], dtype=np.int64)
        stuck = np.array([int(r["stuck"]) for r in recs],
                         dtype=np.int64)
        return TableData(
            "live_queries",
            Schema(base.schema.fields +
                   (Field("progress", DOUBLE),
                    Field("stages", BIGINT), Field("tasks", BIGINT),
                    Field("tasks_done", BIGINT),
                    Field("splits_done", BIGINT),
                    Field("splits_total", BIGINT),
                    Field("rows", BIGINT), Field("bytes", BIGINT),
                    Field("stuck", BIGINT))),
            base.columns + [progress, stages, tasks, tasks_done,
                            splits_done, splits_total, rows, byts,
                            stuck])

    def _utilization_table(self) -> TableData:
        """Per-(node, tier) busy fractions from worker heartbeats
        (server/livestats.py): how much of each node's recent wall the
        device and host tiers spent doing split work."""
        ls = self._livestats()
        recs = ls.utilization() if ls is not None else []
        base = _strings_table(
            "utilization",
            [("node_id", [r["node_id"] for r in recs]),
             ("tier", [r["tier"] for r in recs])])
        frac = np.array([r["busy_fraction"] for r in recs],
                        dtype=np.float64)
        busy_ms = np.array([r["busy_ms"] for r in recs],
                           dtype=np.float64)
        ts = np.array([r["ts"] for r in recs], dtype=np.float64)
        return TableData(
            "utilization",
            Schema(base.schema.fields +
                   (Field("busy_fraction", DOUBLE),
                    Field("busy_ms", DOUBLE), Field("ts", DOUBLE))),
            base.columns + [frac, busy_ms, ts])

    def _query_history_table(self) -> TableData:
        """The coordinator's persistent completed-query ring
        (server/history.py) — latency/bytes/spill records per plan
        fingerprint with the detector's regression verdicts."""
        store = getattr(self.state, "history", None) if self.state \
            else None
        recs = store.snapshot() if store is not None else []
        # prewarm ranking surface: the same (rank, score) the AOT warm
        # pass orders fingerprints by (history.top_fingerprints)
        ranked = store.top_fingerprints(len(recs) or 1) \
            if store is not None else []
        rank_by_fp = {e["fingerprint"]: (i + 1, e["score"])
                      for i, e in enumerate(ranked)}
        base = _strings_table(
            "query_history",
            [("query_id", [r.get("query_id", "") for r in recs]),
             ("fingerprint", [r.get("fingerprint", "") for r in recs]),
             ("state", [r.get("state", "") for r in recs]),
             ("user", [r.get("user", "") for r in recs])])
        elapsed = np.array([float(r.get("elapsed_s", 0) or 0)
                            for r in recs], dtype=np.float64)
        rows = np.array([int(r.get("rows", 0) or 0) for r in recs],
                        dtype=np.int64)
        shuffled = np.array([int(r.get("bytes_shuffled", 0) or 0)
                             for r in recs], dtype=np.int64)
        spills = np.array([int(r.get("spills", 0) or 0) for r in recs],
                          dtype=np.int64)
        regressed = np.array([int(bool(r.get("regressed")))
                              for r in recs], dtype=np.int64)
        prewarm_rank = np.array(
            [rank_by_fp.get(r.get("fingerprint", ""), (0, 0.0))[0]
             for r in recs], dtype=np.int64)
        prewarm_score = np.array(
            [rank_by_fp.get(r.get("fingerprint", ""), (0, 0.0))[1]
             for r in recs], dtype=np.float64)
        return TableData(
            "query_history",
            Schema(base.schema.fields +
                   (Field("elapsed_seconds", DOUBLE),
                    Field("rows", BIGINT),
                    Field("bytes_shuffled", BIGINT),
                    Field("spills", BIGINT),
                    Field("regressed", BIGINT),
                    Field("prewarm_rank", BIGINT),
                    Field("prewarm_score", DOUBLE))),
            base.columns + [elapsed, rows, shuffled, spills, regressed,
                            prewarm_rank, prewarm_score])
