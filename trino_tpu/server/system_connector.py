"""System connector: runtime tables queryable via SQL.

Reference: the `system` catalog (connector/system/ in trino-main — 86
files) exposing system.runtime.queries / .nodes backed by live engine
state. Registered by the coordinator with its tracker + node inventory.
"""

from __future__ import annotations

import numpy as np

from ..batch import Field, Schema
from ..catalog import _strings_table
from ..connectors.tpch.datagen import TableData
from ..types import BIGINT, DOUBLE


class SystemConnector:
    name = "system"

    def __init__(self, coordinator_state=None):
        self.state = coordinator_state

    def schema_names(self):
        return ["runtime"]

    def table_names(self, schema: str):
        if schema == "runtime":
            return ["queries", "nodes"]
        return []

    def get_table(self, schema: str, table: str) -> TableData:
        if schema != "runtime":
            raise KeyError(f"system schema {schema!r} not found")
        if table == "queries":
            return self._queries_table()
        if table == "nodes":
            return self._nodes_table()
        raise KeyError(f"system table {table!r} not found")

    def _queries_table(self) -> TableData:
        queries = self.state.tracker.all() if self.state else []
        ids = [q.query_id for q in queries]
        states = [q.state for q in queries]
        users = [q.session_user for q in queries]
        sqls = [q.sql[:200] for q in queries]
        base = _strings_table("queries",
                              [("query_id", ids), ("state", states),
                               ("user", users), ("query", sqls)])
        elapsed = np.array([q.elapsed_s for q in queries],
                           dtype=np.float64)
        rows = np.array([q.rows_returned for q in queries],
                        dtype=np.int64)
        return TableData(
            "queries",
            Schema(base.schema.fields +
                   (Field("elapsed_seconds", DOUBLE),
                    Field("rows", BIGINT))),
            base.columns + [elapsed, rows])

    def _nodes_table(self) -> TableData:
        nodes = list(self.state.nodes.values()) if self.state else []
        return _strings_table(
            "nodes",
            [("node_id", [n.node_id for n in nodes]),
             ("http_uri", [n.uri for n in nodes]),
             ("state", [n.state for n in nodes])])
