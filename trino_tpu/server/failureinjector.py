"""Fault injection for failure-recovery testing — the chaos layer.

Reference: FailureInjector is part of the engine proper
(execution/FailureInjector.java:35,51 — injected failure types fired at
task-management and results-fetch boundaries), driven by
BaseFailureRecoveryTest (testing/trino-testing/.../BaseFailureRecoveryTest.java:85)
to kill work mid-query and assert identical results under retry.

Round 7 grows the two coordinator-side points (DISPATCH/EXECUTION) into a
seeded, pluggable chaos schedule covering the whole distributed control
plane — worker task create/run, the coordinator's exchange drain, spool
read/write, heartbeat pings — with fault *types* beyond a clean raise:

    RAISE    clean exception at the point (the original behavior)
    CRASH    worker-crash analog: kills the task executor mid-split
    DELAY    fixed/random sleep — a straggling node
    DROP     connection drop (raises a ConnectionResetError subclass so
             it takes the same path as a real peer reset)
    CORRUPT  payload corruption: bit-flip a spooled/served page frame
             (detected downstream by the pageserde CRC32C checksum)

`FailureInjector.from_seed` generates a randomized schedule from a seed so
a chaos soak (tests/test_chaos.py, `bench.py --chaos`) is reproducible:
same seed, same faults, same query matrix, bit-identical results required.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# Injection points in the query lifecycle (the reference's
# InjectedFailureType values, mapped to this runtime's boundaries).
DISPATCH = "DISPATCH"                  # before planning (task management)
EXECUTION = "EXECUTION"                # during stage execution
STAGE_BOUNDARY = "STAGE_BOUNDARY"      # between build/source/final stages
WORKER_TASK_CREATE = "WORKER_TASK_CREATE"  # worker POST /v1/task intake
WORKER_TASK_RUN = "WORKER_TASK_RUN"    # worker executor, per split
EXCHANGE_DRAIN = "EXCHANGE_DRAIN"      # coordinator pulling result pages
SPOOL_READ = "SPOOL_READ"              # durable exchange get()
SPOOL_WRITE = "SPOOL_WRITE"            # durable exchange put()
HEARTBEAT_PING = "HEARTBEAT_PING"      # failure detector /v1/status probe
SCAN_PREFETCH = "SCAN_PREFETCH"        # chunked-driver prefetch worker,
                                       # per staged chunk (exec/chunked.py)
WRITE_STAGE = "WRITE_STAGE"            # write task staging an attempt file
WRITE_COMMIT = "WRITE_COMMIT"          # coordinator journaling the commit
WRITE_PUBLISH = "WRITE_PUBLISH"        # per-file atomic rename publish

POINTS = (DISPATCH, EXECUTION, STAGE_BOUNDARY, WORKER_TASK_CREATE,
          WORKER_TASK_RUN, EXCHANGE_DRAIN, SPOOL_READ, SPOOL_WRITE,
          HEARTBEAT_PING, SCAN_PREFETCH, WRITE_STAGE, WRITE_COMMIT,
          WRITE_PUBLISH)

# The write-protocol boundaries, for `bench.py --write-chaos` and targeted
# soaks (kept out of the from_seed default so the round-7 chaos series
# keeps its historical schedule).
WRITE_POINTS = (WRITE_STAGE, WRITE_COMMIT, WRITE_PUBLISH)

# Fault types.
RAISE = "RAISE"
CRASH = "CRASH"
DELAY = "DELAY"
DROP = "DROP"
CORRUPT = "CORRUPT"
# Infinite-delay straggler: the site blocks until the injector's hangs
# are released (clear() / release_hangs()) or the rule's delay_s safety
# bound passes. Kept OUT of the from_seed default rotation — adding it
# would rewrite every historical seeded chaos schedule — so only the
# deadline/overload soaks (`bench.py --overload`) and targeted tests
# schedule it explicitly.
HANG = "HANG"

FAULTS = (RAISE, CRASH, DELAY, DROP, CORRUPT)


class InjectedFailure(Exception):
    pass


class InjectedCrash(InjectedFailure):
    """Worker-crash analog: the task executor dies mid-split."""


class InjectedDrop(InjectedFailure, ConnectionResetError):
    """Connection drop: an OSError so it rides the same retry path as a
    real peer reset (the scheduler/client catch (URLError, OSError))."""


@dataclass
class ChaosRule:
    point: str
    fault: str = RAISE
    remaining: int = 1             # fire this many times, then let through
    match: Optional[str] = None    # substring filter on the site key
    delay_s: float = 0.05          # DELAY faults sleep this long


class FailureInjector:
    """Fires scheduled faults at chaos points a fixed number of times.

    One injector instance may be shared by every component of a cluster
    (dispatcher, scheduler, spool, workers' task managers, detector) —
    the `point` argument disambiguates the site. Thread-safe.
    """

    def __init__(self, seed: Optional[int] = None):
        self._rules: List[ChaosRule] = []
        self._lock = threading.Lock()
        self.injected_count = 0
        self.injected_by_fault: Dict[str, int] = {f: 0 for f in FAULTS}
        # (wall time, point, fault, key) — bench.py --chaos correlates
        # these with recovery latencies
        self.events: List[tuple] = []
        self._rng = random.Random(seed)
        # HANG faults block on this event; release_hangs()/clear() set
        # it so soak teardown can unstick every hung thread at once
        self._hang_release = threading.Event()

    # -- scheduling --------------------------------------------------------

    def inject(self, point: str, times: int = 1,
               match_sql: Optional[str] = None, fault: str = RAISE,
               delay_s: float = 0.05) -> None:
        """Backward-compatible entry: schedule `times` faults at `point`
        (optionally filtered by a substring of the site key/SQL)."""
        self.add_rule(ChaosRule(point, fault, times, match_sql, delay_s))

    def add_rule(self, rule: ChaosRule) -> None:
        with self._lock:
            self._rules.append(rule)

    @classmethod
    def from_seed(cls, seed: int, n_faults: Optional[int] = None,
                  points=None, faults=None,
                  max_delay_s: float = 0.5) -> "FailureInjector":
        """Seeded randomized chaos schedule: `n_faults` rules drawn over
        `points` x `faults` (defaults: every distributed-runtime point,
        every fault type). Deterministic per seed."""
        inj = cls(seed=seed)
        rng = random.Random(seed)
        if points is None:
            points = (STAGE_BOUNDARY, WORKER_TASK_CREATE, WORKER_TASK_RUN,
                      EXCHANGE_DRAIN, SPOOL_READ, SPOOL_WRITE,
                      HEARTBEAT_PING)
        if faults is None:
            faults = FAULTS
        if n_faults is None:
            n_faults = rng.randint(1, 3)
        for _ in range(n_faults):
            point = rng.choice(points)
            fault = rng.choice(faults)
            if fault == CORRUPT:
                # corruption only applies where a page payload exists
                point = rng.choice((SPOOL_WRITE, EXCHANGE_DRAIN))
            if point == HEARTBEAT_PING and fault == CRASH:
                fault = RAISE          # no task executor at a ping
            if point in (SPOOL_READ, SPOOL_WRITE) and fault == CRASH:
                fault = RAISE
            inj.add_rule(ChaosRule(point, fault,
                                   remaining=rng.randint(1, 2),
                                   delay_s=rng.uniform(0.05, max_delay_s)))
        return inj

    def schedule(self) -> List[ChaosRule]:
        with self._lock:
            return [ChaosRule(r.point, r.fault, r.remaining, r.match,
                              r.delay_s) for r in self._rules]

    # -- firing ------------------------------------------------------------

    def _take(self, point: str, key: str,
              payload_site: bool) -> Optional[ChaosRule]:
        """Consume one matching rule, or None. CORRUPT rules only match
        at payload sites (corrupt_page); everything else at maybe_fail."""
        with self._lock:
            for rule in self._rules:
                if rule.point != point or rule.remaining <= 0:
                    continue
                if (rule.fault == CORRUPT) != payload_site:
                    continue
                if rule.match is not None and rule.match not in key:
                    continue
                rule.remaining -= 1
                self.injected_count += 1
                self.injected_by_fault[rule.fault] = \
                    self.injected_by_fault.get(rule.fault, 0) + 1
                self.events.append((time.time(), point, rule.fault, key))
                return rule
        return None

    def maybe_fail(self, point: str, sql: str = "") -> None:
        """Fire a non-payload fault scheduled at `point`, if any: RAISE /
        CRASH / DROP raise, DELAY sleeps then returns. `sql` doubles as
        the site key (query text, task id, node id — whatever identifies
        the work at that point)."""
        rule = self._take(point, sql, payload_site=False)
        if rule is None:
            return
        if rule.fault == DELAY:
            time.sleep(rule.delay_s)
            return
        if rule.fault == HANG:
            # infinite-delay straggler: block until released (or the
            # rule's delay_s safety bound — schedule HANG rules with a
            # large delay_s; the default 0.05 makes a mere hiccup)
            self._hang_release.wait(rule.delay_s)
            return
        if rule.fault == CRASH:
            raise InjectedCrash(
                f"injected {point} crash ({rule.remaining} left)")
        if rule.fault == DROP:
            raise InjectedDrop(
                f"injected {point} connection drop "
                f"({rule.remaining} left)")
        raise InjectedFailure(
            f"injected {point} failure ({rule.remaining} left)")

    def corrupt_page(self, point: str, key: str, page: bytes) -> bytes:
        """Apply a scheduled CORRUPT fault to a page frame: flip one
        seeded bit. Returns the page unchanged when no rule matches."""
        if not isinstance(page, (bytes, bytearray)) or len(page) == 0:
            return page
        rule = self._take(point, key, payload_site=True)
        if rule is None:
            return page
        buf = bytearray(page)
        bit = self._rng.randrange(len(buf) * 8)
        buf[bit >> 3] ^= 1 << (bit & 7)
        return bytes(buf)

    def release_hangs(self) -> None:
        """Unblock every thread currently stuck in a HANG fault."""
        self._hang_release.set()

    def clear(self) -> None:
        with self._lock:
            self._rules.clear()
        self._hang_release.set()
