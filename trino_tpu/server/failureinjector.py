"""Fault injection for failure-recovery testing.

Reference: FailureInjector is part of the engine proper
(execution/FailureInjector.java:35,51 — injected failure types fired at
task-management and results-fetch boundaries), driven by
BaseFailureRecoveryTest (testing/trino-testing/.../BaseFailureRecoveryTest.java:85)
to kill work mid-query and assert identical results under retry.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional

# Injection points in the query lifecycle (the reference's
# InjectedFailureType values, mapped to this runtime's boundaries).
DISPATCH = "DISPATCH"          # before planning (task-management analog)
EXECUTION = "EXECUTION"        # during stage execution (results-fetch analog)


class InjectedFailure(Exception):
    pass


@dataclass
class _Rule:
    point: str
    remaining: int             # fail this many times, then let through
    match_sql: Optional[str]   # substring filter, None = all queries


class FailureInjector:
    """Fails matching queries at a chosen point a fixed number of times."""

    def __init__(self):
        self._rules: list = []
        self._lock = threading.Lock()
        self.injected_count = 0

    def inject(self, point: str, times: int = 1,
               match_sql: Optional[str] = None) -> None:
        with self._lock:
            self._rules.append(_Rule(point, times, match_sql))

    def maybe_fail(self, point: str, sql: str) -> None:
        with self._lock:
            for rule in self._rules:
                if rule.point != point or rule.remaining <= 0:
                    continue
                if rule.match_sql is not None and \
                        rule.match_sql not in sql:
                    continue
                rule.remaining -= 1
                self.injected_count += 1
                raise InjectedFailure(
                    f"injected {point} failure ({rule.remaining} left)")

    def clear(self) -> None:
        with self._lock:
            self._rules.clear()
