"""Durable coordinator query ledger + epoch-fenced failover state.

The coordinator analogue of the round-18 write-commit journal: an
append-only, CRC-framed, fsync'd record of everything the coordinator
must not forget across a crash — query admission (SQL, principal,
session props, plan fingerprint), every state transition, task/stage
assignments, result-spool pointers, and terminal outcomes. Replaying
any byte prefix of the file is safe (torn tails are tolerated exactly
like the write journal's), and replay is a pure fold into `LedgerView`
whose `apply` is idempotent — double replay, or replay interleaved with
live appends after a resume, converges to the same registry /
resource-group / catalog-version state.

Fencing: leadership is an epoch in a sidecar file (`<ledger>.epoch`),
bumped atomically (tmp + rename + dir fsync) by `claim_epoch` at
promotion. Every append re-checks ownership (TTL-cached); a deposed
primary's appends become no-ops and `owns_epoch()` flips false, which
the coordinator uses to demote itself — the classic fencing-token
scheme, so a resurrected old primary can never split-brain the ledger.

The frame format is writeprotocol's (`TWJ1` magic + crc32c + length +
sorted-keys JSON) so the torn-tail replay machinery is shared, not
re-implemented.
"""

import json
import logging
import os
import threading
import time

from ..metrics import LEDGER_BYTES, LEDGER_RECORDS
from ..utils.atomicio import fsync_dir
from .writeprotocol import JOURNAL_MAGIC, _frame, replay_journal

log = logging.getLogger("trino_tpu.ledger")

# record kinds, also the lint-enforced label values of
# trino_tpu_ledger_records_total{kind=...}
KINDS = ("admit", "state", "assign", "spool", "terminal", "catalog",
         "promote")

# lifecycle order for the view's monotonic state advance (terminal
# states compare equal-highest: a terminal record always wins)
_ORDER = ("QUEUED", "PLANNING", "STARTING", "RUNNING", "FINISHING",
          "FINISHED", "FAILED", "CANCELED")
_TERMINAL = ("FINISHED", "FAILED", "CANCELED")


def _rank(state: str) -> int:
    try:
        i = _ORDER.index(state)
    except ValueError:
        return -1
    return len(_ORDER) if state in _TERMINAL else i


class LedgerView:
    """Pure fold over ledger records. `apply` is idempotent per record
    content: first-wins for admission facts and timestamps, monotonic
    max for lifecycle state / catalog version / epoch — so replaying a
    prefix, the whole file, or the whole file twice all agree."""

    def __init__(self):
        self.queries = {}           # qid -> dict
        self.catalog_version = 0
        self.epoch = 0
        self.promotions = []        # [(epoch, node)] in epoch order

    def _q(self, qid: str) -> dict:
        return self.queries.setdefault(qid, {
            "query_id": qid, "sql": None, "user": None, "tenant": None,
            "fingerprint": None, "properties": {}, "state": "QUEUED",
            "state_times": {}, "assigned": {}, "spooled": [],
            "terminal": None, "error": None, "error_name": None,
            "error_code": 0, "rows": None, "elapsed_s": None,
        })

    def apply(self, rec: dict) -> None:
        kind = rec.get("rec")
        if kind == "admit":
            q = self._q(rec["query"])
            if q["sql"] is None:            # first admit wins
                q["sql"] = rec.get("sql")
                q["user"] = rec.get("user")
                q["tenant"] = rec.get("tenant")
                q["fingerprint"] = rec.get("fingerprint")
                q["properties"] = dict(rec.get("properties") or {})
            q["state_times"].setdefault("QUEUED", rec.get("ts", 0.0))
        elif kind == "state":
            q = self._q(rec["query"])
            st = rec.get("state", "")
            q["state_times"].setdefault(st, rec.get("ts", 0.0))
            if _rank(st) > _rank(q["state"]) and q["terminal"] is None:
                q["state"] = st
        elif kind == "terminal":
            q = self._q(rec["query"])
            st = rec.get("state", "FAILED")
            q["state_times"].setdefault(st, rec.get("ts", 0.0))
            if q["terminal"] is None:       # first terminal wins
                q["terminal"] = st
                q["state"] = st
                q["error"] = rec.get("error")
                q["error_name"] = rec.get("error_name")
                q["error_code"] = rec.get("error_code", 0)
                q["rows"] = rec.get("rows")
                q["elapsed_s"] = rec.get("elapsed_s")
            if rec.get("catalog_version"):
                self.catalog_version = max(self.catalog_version,
                                           rec["catalog_version"])
        elif kind == "assign":
            q = self._q(rec["query"])
            q["assigned"].setdefault(rec["task"], {
                "node": rec.get("node"), "stage": rec.get("stage")})
        elif kind == "spool":
            q = self._q(rec["query"])
            if rec["key"] not in q["spooled"]:
                q["spooled"].append(rec["key"])
        elif kind == "catalog":
            self.catalog_version = max(self.catalog_version,
                                       rec.get("version", 0))
        elif kind == "promote":
            e = rec.get("epoch", 0)
            if e > self.epoch:
                self.epoch = e
                self.promotions.append((e, rec.get("node")))

    def live(self):
        """Non-terminal queries, in admission order (qids sort by
        admission thanks to the tracker's timestamped sequence ids)."""
        return [q for _, q in sorted(self.queries.items())
                if q["terminal"] is None]

    def fingerprint(self) -> str:
        """Canonical digest of the whole view — the idempotence oracle
        the replay tests compare across single/double/prefix replays."""
        import hashlib
        blob = json.dumps(
            {"queries": self.queries,
             "catalog_version": self.catalog_version,
             "epoch": self.epoch, "promotions": self.promotions},
            sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()


class QueryLedger:
    """Append side + replay + epoch fencing for one ledger file."""

    EPOCH_TTL_S = 0.25          # ownership re-check cadence on append

    def __init__(self, path: str, node_id: str = "coordinator"):
        self.path = os.path.abspath(path)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self.node_id = node_id
        self.sealed = False
        self._lock = threading.RLock()
        self._owner_checked = 0.0
        self._owner = None          # cached (epoch, node)

    # ---- epoch fencing ---------------------------------------------------

    @property
    def epoch_path(self) -> str:
        return self.path + ".epoch"

    def read_epoch(self):
        """(epoch, owner_node) from the sidecar; (0, None) if never
        claimed — the unfenced single-coordinator mode."""
        try:
            with open(self.epoch_path) as f:
                doc = json.load(f)
            return int(doc.get("epoch", 0)), doc.get("node")
        except (OSError, ValueError):
            return 0, None

    def claim_epoch(self) -> int:
        """Atomically bump the epoch and take ownership. The returned
        token fences every previous holder: their cached ownership
        expires within EPOCH_TTL_S and appends turn into no-ops."""
        with self._lock:
            cur, _ = self.read_epoch()
            new = cur + 1
            tmp = self.epoch_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"epoch": new, "node": self.node_id}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.epoch_path)
            fsync_dir(os.path.dirname(self.epoch_path))
            self._owner = (new, self.node_id)
            self._owner_checked = time.monotonic()
            self.append({"rec": "promote", "epoch": new,
                         "node": self.node_id, "ts": time.time()})
            log.info("ledger epoch %d claimed by %s", new, self.node_id)
            return new

    def owns_epoch(self, force: bool = False) -> bool:
        """True while this node may append. An unclaimed ledger is
        owned by everyone (no failover configured yet)."""
        now = time.monotonic()
        if force or self._owner is None or \
                now - self._owner_checked > self.EPOCH_TTL_S:
            self._owner = self.read_epoch()
            self._owner_checked = now
        epoch, node = self._owner
        return epoch == 0 or node == self.node_id

    # ---- append side -----------------------------------------------------

    def seal(self) -> None:
        """In-process crash model: a sealed ledger accepts no appends,
        exactly as if the coordinator process died."""
        self.sealed = True

    def append(self, rec: dict) -> bool:
        """Append one fenced, fsync'd record. Returns False (no-op)
        when sealed or deposed — callers never need to special-case a
        lost leadership race; the record simply does not happen."""
        with self._lock:
            if self.sealed or not self.owns_epoch():
                return False
            frame = _frame(rec)
            with open(self.path, "ab") as f:
                f.write(frame)
                f.flush()
                os.fsync(f.fileno())
                size = f.tell()
            fsync_dir(os.path.dirname(self.path))
        kind = rec.get("rec", "")
        if kind in KINDS:
            LEDGER_RECORDS.inc(kind=kind)
        LEDGER_BYTES.set(size)
        return True

    # typed appenders ------------------------------------------------------

    def admit(self, qid: str, sql: str, user: str, tenant: str,
              fingerprint: str, properties: dict) -> bool:
        props = {k: v for k, v in (properties or {}).items()
                 if isinstance(v, (str, int, float, bool))}
        return self.append({"rec": "admit", "query": qid, "sql": sql,
                            "user": user, "tenant": tenant,
                            "fingerprint": fingerprint,
                            "properties": props, "ts": time.time()})

    def state(self, qid: str, state: str, ts: float) -> bool:
        return self.append({"rec": "state", "query": qid, "state": state,
                            "ts": ts})

    def terminal(self, qid: str, state: str, ts: float, error=None,
                 error_name=None, error_code=0, rows=None,
                 elapsed_s=None, catalog_version=0) -> bool:
        return self.append({
            "rec": "terminal", "query": qid, "state": state, "ts": ts,
            "error": error, "error_name": error_name,
            "error_code": error_code, "rows": rows,
            "elapsed_s": elapsed_s, "catalog_version": catalog_version})

    def assign(self, qid: str, task: str, node: str, stage: str) -> bool:
        return self.append({"rec": "assign", "query": qid, "task": task,
                            "node": node, "stage": stage,
                            "ts": time.time()})

    def spool(self, qid: str, key: str) -> bool:
        return self.append({"rec": "spool", "query": qid, "key": key,
                            "ts": time.time()})

    # ---- replay side -----------------------------------------------------

    def replay(self):
        """(LedgerView, torn_tail) — a pure function of the file bytes
        plus the epoch sidecar, safe on torn tails and safe to call any
        number of times."""
        return replay_path(self.path)

    def tail_records(self, offset: int):
        """Complete frames at/after byte `offset`; returns
        (records, new_offset). Torn or incomplete tails leave the
        offset at the last complete frame so the standby's tail loop
        just retries — the same contract as replay_journal, but
        incremental."""
        import struct
        try:
            with open(self.path, "rb") as f:
                f.seek(offset)
                buf = f.read()
        except OSError:
            return [], offset
        from .pageserde import _crc32c
        recs, off = [], 0
        while off + 12 <= len(buf):
            if buf[off:off + 4] != JOURNAL_MAGIC:
                break
            crc, ln = struct.unpack_from("<II", buf, off + 4)
            body = buf[off + 12:off + 12 + ln]
            if len(body) != ln or (_crc32c(body) & 0xFFFFFFFF) != crc:
                break
            try:
                recs.append(json.loads(body.decode()))
            except ValueError:
                break
            off += 12 + ln
        return recs, offset + off


def replay_path(path: str):
    """Replay a ledger file (possibly truncated mid-frame) into a
    LedgerView. The epoch sidecar, when present, floors the view's
    epoch so fencing survives even a fully torn ledger tail."""
    view = LedgerView()
    records, torn = replay_journal(path)
    for rec in records:
        view.apply(rec)
    try:
        with open(path + ".epoch") as f:
            doc = json.load(f)
        view.epoch = max(view.epoch, int(doc.get("epoch", 0)))
    except (OSError, ValueError):
        pass
    return view, torn
