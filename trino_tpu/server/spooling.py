"""Result spooling: large result sets as fetch/ack segments.

Reference: the spooled client protocol (server/protocol/spooling/ —
SpoolingManagerBridge, CoordinatorSegmentResource; SPI spi/spool/
SpoolingManager.java; plugin/trino-spooling-filesystem). Clients that
opt in receive segment descriptors instead of inline data, fetch each
segment by URI, and acknowledge it — decoupling result lifetime from the
query and keeping coordinator memory flat.

Here: segments are JSON files under a spool directory; ack deletes.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
import uuid
from typing import List, Optional


class SpoolingManager:
    def __init__(self, directory: Optional[str] = None,
                 segment_rows: int = 5000):
        self.directory = directory or tempfile.mkdtemp(prefix="spool-")
        os.makedirs(self.directory, exist_ok=True)
        self.segment_rows = segment_rows
        self._lock = threading.Lock()
        self.segments_written = 0

    def _path(self, segment_id: str) -> str:
        # ids are uuid4 hex (validated on read): no path traversal
        return os.path.join(self.directory, f"{segment_id}.json")

    def spool(self, rows: List[list]) -> List[dict]:
        """Write rows as segments; returns descriptors
        [{id, uri(relative), rowCount}]."""
        descriptors = []
        for start in range(0, len(rows), self.segment_rows):
            chunk = rows[start:start + self.segment_rows]
            sid = uuid.uuid4().hex
            with open(self._path(sid), "w") as f:
                json.dump(chunk, f)
            with self._lock:
                self.segments_written += 1
            descriptors.append({
                "id": sid,
                "uri": f"/v1/spooled/segments/{sid}",
                "rowCount": len(chunk)})
        return descriptors

    def read(self, segment_id: str) -> Optional[list]:
        if not segment_id.isalnum():
            return None
        try:
            with open(self._path(segment_id)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def ack(self, segment_id: str) -> None:
        if not segment_id.isalnum():
            return
        try:
            os.remove(self._path(segment_id))
        except FileNotFoundError:
            pass
