"""Data-only wire serde for plan fragments.

Reference: Trino ships plan fragments between coordinator and workers as
Jackson-serialized JSON (sql/planner/PlanFragment.java + the codec in
server/InternalCommunicationModule) — data-only: deserializing attacker
bytes can at worst build a malformed plan, never execute code.  Round-2's
pickle serde did not have that property (a crafted POST /v1/task body could
run arbitrary code in the worker); this module replaces it.

Design: every node in a plan tree is a frozen dataclass from a closed set
of modules (planner.logical, ir, batch, types, server.tasks).  The encoder
reflects over dataclass fields; the decoder instantiates ONLY classes in
the registry, via their constructors.  Leaves: JSON primitives, tuples,
numpy arrays (base64), enums from the registry.  Shared references are
encoded once and re-linked on decode ("$ref"), preserving the object
identity the executor's driver-scan substitution relies on (id(scan)).
"""

from __future__ import annotations

import base64
import dataclasses
import enum
import json
import threading
from typing import Any, Dict

import numpy as np


def _build_registry():
    from .. import ir
    from ..batch import Field, Schema
    from ..planner import logical
    from ..sql import ast_nodes
    from ..types import DataType, TypeKind

    classes: Dict[str, type] = {}
    for mod in (ir, logical, ast_nodes):
        for name in dir(mod):
            obj = getattr(mod, name)
            if isinstance(obj, type) and dataclasses.is_dataclass(obj):
                classes[obj.__name__] = obj
    for cls in (Field, Schema, DataType):
        classes[cls.__name__] = cls
    enums = {"TypeKind": TypeKind}
    return classes, enums


_registry_lock = threading.Lock()
_classes: Dict[str, type] = {}
_enums: Dict[str, type] = {}


def _registry():
    global _classes, _enums
    if not _classes:
        with _registry_lock:
            if not _classes:
                _classes, _enums = _build_registry()
    return _classes, _enums


def register(cls: type) -> type:
    """Add an out-of-module dataclass (e.g. Split) to the closed set."""
    _registry()
    _classes[cls.__name__] = cls
    return cls


class _Encoder:
    def __init__(self):
        self.memo: Dict[int, int] = {}     # id(obj) -> slot
        self.slots = []                    # slot -> encoded node

    def enc(self, obj: Any) -> Any:
        if obj is None or isinstance(obj, (bool, int, float, str)):
            return obj
        if isinstance(obj, (np.integer, np.floating, np.bool_)):
            return {"$np": obj.dtype.name, "v": obj.item()}
        if isinstance(obj, tuple):
            return {"$tup": [self.enc(x) for x in obj]}
        if isinstance(obj, list):
            return {"$list": [self.enc(x) for x in obj]}
        if isinstance(obj, frozenset):
            return {"$fset": [self.enc(x) for x in sorted(obj, key=repr)]}
        if isinstance(obj, dict):
            return {"$dict": [[self.enc(k), self.enc(v)]
                              for k, v in obj.items()]}
        if isinstance(obj, np.ndarray):
            a = np.ascontiguousarray(obj)
            return {"$nd": a.dtype.str, "shape": list(a.shape),
                    "data": base64.b64encode(a.tobytes()).decode()}
        if isinstance(obj, enum.Enum):
            return {"$enum": type(obj).__name__, "v": obj.value}
        if dataclasses.is_dataclass(obj):
            slot = self.memo.get(id(obj))
            if slot is not None:
                return {"$ref": slot}
            classes, _ = _registry()
            name = type(obj).__name__
            if classes.get(name) is not type(obj):
                raise TypeError(f"unregistered fragment class: {name}")
            slot = len(self.slots)
            self.memo[id(obj)] = slot
            self.slots.append(None)        # reserve (cycles impossible in
            fields = {}                    # frozen trees, but keep order)
            for f in dataclasses.fields(obj):
                if f.name == "lock":
                    continue
                fields[f.name] = self.enc(getattr(obj, f.name))
            self.slots[slot] = {"$dc": name, "f": fields}
            return {"$ref": slot}
        raise TypeError(f"cannot encode {type(obj).__name__} on the wire")


class _Decoder:
    def __init__(self, slots):
        self.raw = slots
        self.built = [None] * len(slots)
        self.done = [False] * len(slots)

    def dec(self, obj: Any) -> Any:
        if obj is None or isinstance(obj, (bool, int, float, str)):
            return obj
        if isinstance(obj, list):          # only produced inside markers
            return [self.dec(x) for x in obj]
        if "$np" in obj:
            return np.dtype(obj["$np"]).type(obj["v"])
        if "$tup" in obj:
            return tuple(self.dec(x) for x in obj["$tup"])
        if "$list" in obj:
            return [self.dec(x) for x in obj["$list"]]
        if "$fset" in obj:
            return frozenset(self.dec(x) for x in obj["$fset"])
        if "$dict" in obj:
            return {self.dec(k): self.dec(v) for k, v in obj["$dict"]}
        if "$nd" in obj:
            a = np.frombuffer(base64.b64decode(obj["data"]),
                              dtype=np.dtype(obj["$nd"]))
            return a.reshape(obj["shape"])
        if "$enum" in obj:
            _, enums = _registry()
            return enums[obj["$enum"]](obj["v"])
        if "$ref" in obj:
            slot = obj["$ref"]
            if not self.done[slot]:
                node = self.raw[slot]
                classes, _ = _registry()
                cls = classes.get(node["$dc"])
                if cls is None:
                    raise TypeError(
                        f"unregistered fragment class: {node['$dc']}")
                kwargs = {k: self.dec(v) for k, v in node["f"].items()}
                self.built[slot] = cls(**kwargs)
                self.done[slot] = True
            return self.built[slot]
        raise TypeError(f"bad wire object: {list(obj)[:3]}")


def dumps(obj: Any) -> str:
    e = _Encoder()
    root = e.enc(obj)
    return json.dumps({"v": 1, "slots": e.slots, "root": root})


def loads(blob: str) -> Any:
    payload = json.loads(blob)
    if payload.get("v") != 1:
        raise ValueError("unknown fragment wire version")
    return _Decoder(payload["slots"]).dec(payload["root"])
