"""Cluster flight recorder: bounded in-process metric time series.

Reference: Trino ships point-in-time counters over JMX/OpenMetrics and
leaves retention to an external scraper; the soak/SLO tooling here needs
p99-over-time *without* a Prometheus deployment, so each node keeps a
small delta-encoded ring of registry samples (the "flight recorder") and
the coordinator federates worker rings into cluster-wide series.

Design:
- `FlightRecorder` walks the process `MetricsRegistry` at a configurable
  interval. Counters and histogram slots are stored as per-interval
  DELTAS (rate numerators); gauges as current values. A sample only
  carries keys whose value moved since the previous sample, so an idle
  cluster costs a timestamp per tick.
- The ring is byte-bounded: each sample's encoded size is tracked and the
  oldest samples are evicted (counted in
  trino_tpu_telemetry_ring_evictions_total) until the ring fits
  `max_bytes`. Memory use therefore cannot grow with uptime.
- The sampler THREAD only exists when an interval is configured
  (`TRINO_TPU_TELEMETRY_INTERVAL_S` or an explicit constructor value) —
  the default path adds zero threads and zero samples.
- Federation: workers serve `GET /v1/telemetry?since=<ts>` (internal
  route class); `ClusterTelemetry.collect()` scrapes every registered
  node incrementally (per-node `since` cursors) and merges the samples
  into one bounded cluster series served via
  system.runtime.metrics_history and consumed by `bench.py --soak`.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..metrics import REGISTRY, Histogram

DEFAULT_MAX_BYTES = 256 * 1024


def _interval_from_env() -> float:
    import os
    try:
        return float(os.environ.get("TRINO_TPU_TELEMETRY_INTERVAL_S", "0"))
    except ValueError:
        return 0.0


def registry_series_snapshot(registry=None) -> Dict[str, float]:
    """{metric-key: value} over every family in the registry.

    Keys are `name|labelval|...`; histograms contribute their cumulative
    bucket counts (`name_bucket|...|le`), `name_count` and `name_sum`
    slots so a per-interval delta of two snapshots is a well-formed
    per-interval histogram (the p99-over-time input).
    """
    registry = registry or REGISTRY
    out: Dict[str, float] = {}
    with registry._lock:
        metrics = list(registry._metrics.items())
    for name, m in metrics:
        if isinstance(m, Histogram):
            with m._lock:
                hists = [(k, list(h)) for k, h in m._hists.items()]
            for key, h in hists:
                prefix = "|".join((name,) + key)
                for i, b in enumerate(m.buckets):
                    out[f"{prefix}_bucket|le={b}"] = h[i]
                out[f"{prefix}_bucket|le=+Inf"] = h[-2]
                out[f"{prefix}_count"] = h[-2]
                out[f"{prefix}_sum"] = h[-1]
        else:
            with m._lock:
                vals = list(m._values.items())
            for key, v in vals:
                out["|".join((name,) + key)] = v
    return out


def _metric_kinds(registry=None) -> Dict[str, str]:
    registry = registry or REGISTRY
    with registry._lock:
        return {name: m.kind for name, m in registry._metrics.items()}


class FlightRecorder:
    """One node's bounded, delta-encoded metric ring."""

    def __init__(self, node_id: str, interval_s: Optional[float] = None,
                 max_bytes: int = DEFAULT_MAX_BYTES, registry=None):
        self.node_id = node_id
        self.interval_s = (_interval_from_env() if interval_s is None
                           else float(interval_s))
        self.max_bytes = int(max_bytes)
        self.registry = registry or REGISTRY
        self._ring: "deque[dict]" = deque()
        self._bytes = 0
        self._prev: Dict[str, float] = {}
        self._prev_ts: Optional[float] = None
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- sampling ---------------------------------------------------------

    def sample_once(self, now: Optional[float] = None) -> dict:
        """Take one sample: gauges by value, counters/histogram slots as
        deltas since the previous sample; store only keys that moved."""
        from ..metrics import TELEMETRY_SAMPLES
        now = time.time() if now is None else now
        snap = registry_series_snapshot(self.registry)
        kinds = _metric_kinds(self.registry)
        with self._lock:
            values: Dict[str, float] = {}
            for key, v in snap.items():
                name = key.split("|", 1)[0]
                kind = kinds.get(name)
                if kind is None:
                    # histogram slot keys carry suffixes; resolve by the
                    # longest registered prefix
                    for suffix in ("_bucket", "_count", "_sum"):
                        if name.endswith(suffix):
                            kind = kinds.get(name[: -len(suffix)])
                            break
                prev = self._prev.get(key)
                if kind == "gauge":
                    if prev is None or v != prev:
                        values[key] = v
                else:                      # counter / histogram slot
                    delta = v - (prev or 0.0)
                    if delta:
                        values[key] = delta
            interval = (now - self._prev_ts) if self._prev_ts else 0.0
            self._prev = snap
            self._prev_ts = now
            sample = {"ts": now, "interval_s": round(interval, 6),
                      "values": values}
            cost = self._estimate_bytes(sample)
            self._ring.append(sample)
            self._bytes += cost
            sample["_bytes"] = cost
            evicted = 0
            while self._bytes > self.max_bytes and len(self._ring) > 1:
                old = self._ring.popleft()
                self._bytes -= old.get("_bytes", 0)
                evicted += 1
        TELEMETRY_SAMPLES.inc()
        if evicted:
            from ..metrics import TELEMETRY_RING_EVICTIONS
            TELEMETRY_RING_EVICTIONS.inc(evicted)
        return sample

    @staticmethod
    def _estimate_bytes(sample: dict) -> int:
        # a JSON encode is the honest cost model: the ring is served as
        # JSON and the estimate is what eviction budgets against
        return len(json.dumps(
            {k: v for k, v in sample.items() if k != "_bytes"},
            separators=(",", ":")))

    # -- reads ------------------------------------------------------------

    def since(self, ts: float = 0.0) -> List[dict]:
        with self._lock:
            return [{"ts": s["ts"], "interval_s": s["interval_s"],
                     "values": dict(s["values"])}
                    for s in self._ring if s["ts"] > ts]

    def ring_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def sample_count(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- sampler lifecycle ------------------------------------------------

    @property
    def sampling(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "FlightRecorder":
        """Start the sampler thread — only when an interval is
        configured; the default (interval 0) stays thread-free."""
        if self.interval_s <= 0 or self.sampling:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"telemetry-{self.node_id}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — telemetry never kills a node
                pass


class ClusterTelemetry:
    """Coordinator-side federation: the local recorder plus incremental
    scrapes of every registered worker's /v1/telemetry ring, merged into
    one bounded cluster series of (ts, node, metric, value) rows."""

    def __init__(self, recorder: FlightRecorder, nodes_fn,
                 max_rows: int = 200_000):
        self.recorder = recorder
        self._nodes_fn = nodes_fn          # -> [(node_id, uri)]
        self._rows: "deque[tuple]" = deque(maxlen=max_rows)
        self._cursors: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- federation loop (only runs when an interval is configured) -------

    @property
    def collecting(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "ClusterTelemetry":
        if self.recorder.interval_s <= 0 or self.collecting:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="telemetry-federation", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)

    def _loop(self) -> None:
        while not self._stop.wait(self.recorder.interval_s):
            try:
                self.collect()
            except Exception:  # noqa: BLE001 — telemetry never kills a node
                pass

    def _merge(self, node_id: str, samples: List[dict]) -> None:
        with self._lock:
            for s in samples:
                ts = s["ts"]
                self._cursors[node_id] = max(
                    self._cursors.get(node_id, 0.0), ts)
                for key, v in s.get("values", {}).items():
                    self._rows.append((ts, node_id, key, float(v)))

    def collect(self, sample_local: bool = True) -> int:
        """One federation round: sample the local ring, then scrape every
        worker incrementally. Returns the number of nodes that answered
        (coordinator included). Unreachable workers are skipped — the
        series gaps instead of the collector failing."""
        answered = 0
        if sample_local:
            try:
                self.recorder.sample_once()
            except Exception:  # noqa: BLE001
                pass
        local_id = self.recorder.node_id
        self._merge(local_id,
                    self.recorder.since(self._cursors.get(local_id, 0.0)))
        answered += 1
        from urllib.request import Request, urlopen

        from .security import internal_headers
        for node_id, uri in list(self._nodes_fn()):
            cursor = self._cursors.get(node_id, 0.0)
            try:
                req = Request(f"{uri}/v1/telemetry?since={cursor}",
                              headers=internal_headers())
                with urlopen(req, timeout=5) as resp:
                    doc = json.loads(resp.read().decode())
                self._merge(node_id, doc.get("samples", []))
                answered += 1
            except Exception:  # noqa: BLE001 — a dead worker gaps the series
                continue
        return answered

    def rows(self, since: float = 0.0,
             metric: Optional[str] = None) -> List[tuple]:
        """(ts, node, metric-key, value) rows, oldest first. `metric`
        filters by family-name prefix of the key."""
        with self._lock:
            out = [r for r in self._rows if r[0] > since]
        if metric:
            out = [r for r in out if r[2] == metric or
                   r[2].startswith(metric + "|") or
                   r[2].startswith(metric + "_")]
        return out

    def series(self, metric: str, node: Optional[str] = None) -> List[tuple]:
        """[(ts, value)] for one metric key prefix, optionally one node."""
        return [(ts, v) for ts, n, k, v in self.rows(metric=metric)
                if node is None or n == node]


# -- series math: the soak gate's per-interval percentile estimator --------

def percentile_from_buckets(bucket_deltas, quantile: float) -> Optional[float]:
    """Prometheus-style histogram_quantile over per-interval bucket
    deltas: [(upper_bound, count)] cumulative within the interval,
    linear interpolation inside the winning bucket. Returns None for an
    empty interval."""
    buckets = sorted(((float("inf") if b in ("+Inf", float("inf")) else
                       float(b)), c) for b, c in bucket_deltas)
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = quantile * total
    lo_bound, lo_count = 0.0, 0.0
    for bound, count in buckets:
        if count >= rank:
            if bound == float("inf"):
                return lo_bound
            span = count - lo_count
            frac = (rank - lo_count) / span if span > 0 else 1.0
            return lo_bound + (bound - lo_bound) * frac
        lo_bound, lo_count = bound, count
    return lo_bound


def histogram_deltas(samples: List[dict], family: str,
                     labelval: Optional[str] = None) -> List[dict]:
    """Per-interval bucket deltas of one histogram family from a list of
    flight-recorder samples: [{'ts', 'interval_s', 'buckets': [(le,
    delta)], 'count', 'sum'}] — the input `percentile_from_buckets`
    wants, one entry per sample that saw observations."""
    prefix = family + ("|" + labelval if labelval else "")
    out = []
    for s in samples:
        buckets, count, total = [], 0.0, 0.0
        for key, v in s.get("values", {}).items():
            if not key.startswith(prefix):
                continue
            rest = key[len(prefix):]
            if rest.startswith("_bucket|le="):
                buckets.append((rest[len("_bucket|le="):], v))
            elif rest == "_count":
                count = v
            elif rest == "_sum":
                total = v
        if buckets and count > 0:
            out.append({"ts": s["ts"], "interval_s": s.get("interval_s", 0),
                        "buckets": buckets, "count": count, "sum": total})
    return out
