"""Live query stats: the coordinator's fold of streamed TaskStats.

Reference: the reference engine's coordinator continuously polls task
status (ContinuousTaskStatusFetcher) and folds the streams into live
QueryStats — progress bars, stuck-task detection and the Web UI's stage
view all read that fold, never the workers. Here the stream direction is
inverted to fit the announce path: workers PUSH bounded, delta-encoded
live TaskStats piggybacked on their announce heartbeats
(WorkerServer._heartbeat_payload), and this store folds them into:

- a per-query, per-stage live rollup (`/v1/query/{id}` stageStats,
  `system.runtime.tasks` and `system.runtime.live_queries` mid-flight);
- a split-weighted progress estimator (monotonic per query, forced to
  1.0 by the protocol layer at FINISHED) surfaced through the client
  protocol's stats pages and rendered by the CLI `--progress` line;
- a stuck/skew diagnoser: a query whose live counters stop advancing
  for `stuck_after` consecutive heartbeat folds gets one structured
  diagnosis (stage, task, node, timeline phase, max/median split-time
  skew) attached to its TrackedQuery and a slow-query-style log line;
  the same skew evidence feeds the scheduler's hedging decision
  (StageScheduler._drain_units) so stragglers hedge on LIVE data
  instead of terminal-drain medians;
- per-node host/device utilization snapshots federated as
  `system.runtime.utilization`.

Zero overhead when off: the store only changes state inside fold(), and
fold() only runs when a heartbeat arrives — no heartbeat interval, no
folds, no threads, nothing. Task registration (register_task at the
scheduler's launch sites) is a dict insert.
"""

from __future__ import annotations

import logging
import statistics
import threading
import time
from typing import Dict, List, Optional, Set

log = logging.getLogger("trino_tpu.livestats")

# live records are bounded: finished queries past this cap are evicted
# oldest-first together with their task records
MAX_FINISHED_QUERIES = 64

# a RUNNING task must have held its current work at least this long
# before pace skew can flag it for hedging — sub-ms stage medians on
# tiny queries would otherwise flag healthy tasks that merely sit
# between two heartbeats
STRAGGLER_MIN_WALL_MS = 250.0


def _split_frac(rec: dict) -> float:
    total = rec.get("splits_total") or 0
    if total <= 0:
        # exchange consumers / writers carry no splits: done-or-not
        return 1.0 if rec.get("state") in ("FINISHED",) else 0.0
    return min(1.0, rec.get("splits_done", 0) / total)


def _phase_guess(rec: dict) -> str:
    """Which timeline phase (server/timeline.py PHASES) a live task is
    most plausibly stuck in, from its so-far tier attribution."""
    dev = rec.get("device_ms", 0.0)
    host = rec.get("host_ms", 0.0)
    comp = rec.get("compile_ms", 0.0)
    if comp > dev and comp > host:
        return "compile"
    if dev > 0 and dev >= host:
        return "device"
    if host > 0:
        return "host"
    # running but never finished a split and no tier time folded yet:
    # it is waiting on inputs, the exchange-wait phase
    return "exchange-wait"


class LiveStatsStore:
    """Coordinator-side fold of heartbeat-streamed live TaskStats."""

    def __init__(self, tracked_lookup=None, stuck_after: int = 5):
        self._lock = threading.Lock()
        # task_id -> live record {query_id, task_id, stage, node, state,
        # splits_done, splits_total, rows, bytes, wall_ms, device_ms,
        # host_ms, compile_ms, updated}
        self._tasks: Dict[str, dict] = {}
        # query_id -> {task_ids, high_water, advance_sig, stale_folds,
        # diagnosed, done, started, diagnosis}
        self._queries: Dict[str, dict] = {}
        # node_id -> {device, host, busy_device_ms, busy_host_ms, ts}
        self._nodes: Dict[str, dict] = {}
        self._finished_order: List[str] = []
        # TrackedQuery lookup (CoordinatorState wires tracker.get) for
        # attaching diagnoses and reading live states
        self.tracked_lookup = tracked_lookup
        # heartbeat folds without counter advance before a running
        # query is diagnosed as stuck
        self.stuck_after = max(1, int(stuck_after))
        self.folds = 0                    # observability counter
        # stuck-query escalation: after a diagnosed query stays stalled
        # this many MORE folds, it is terminated through the hook below
        # (reason="stuck"). 0 disables — diagnosis stays report-only.
        import os
        try:
            self.escalate_after = int(os.environ.get(
                "TRINO_TPU_STUCK_ESCALATE_FOLDS", "0"))
        except ValueError:
            self.escalate_after = 0
        # terminate(query_id, reason=..., message=...) — CoordinatorState
        # wires the dispatcher's single termination path here
        self.terminate = None

    # -- registration (scheduler launch sites + failover reattach) --------

    def begin(self, query_id: Optional[str]) -> None:
        if not query_id:
            return
        with self._lock:
            self._queries.setdefault(query_id, {
                "task_ids": set(), "high_water": 0.0,
                "advance_sig": None, "stale_folds": 0,
                "diagnosed": False, "done": False,
                "started": time.time(), "diagnosis": None})

    def register_task(self, query_id: Optional[str], task_id: str,
                      stage: str = "", node: str = "",
                      splits_total: Optional[int] = None) -> None:
        """Attribute `task_id` to a query/stage. Called beside the
        scheduler's ledger-assign at every task launch, and by failover
        reattachment with only the (query, task) pair — the worker's
        next heartbeat fills in the counters (entries carry
        splitsTotal), which is how a promoted coordinator re-derives
        progress for reattached queries."""
        if not query_id:
            return
        with self._lock:
            q = self._queries.setdefault(query_id, {
                "task_ids": set(), "high_water": 0.0,
                "advance_sig": None, "stale_folds": 0,
                "diagnosed": False, "done": False,
                "started": time.time(), "diagnosis": None})
            q["task_ids"].add(task_id)
            rec = self._tasks.setdefault(task_id, {
                "query_id": query_id, "task_id": task_id,
                "stage": stage, "node": node, "state": "PENDING",
                "splits_done": 0, "splits_total": splits_total,
                "rows": 0, "bytes": 0, "wall_ms": 0.0,
                "device_ms": 0.0, "host_ms": 0.0, "compile_ms": 0.0,
                "updated": 0.0})
            rec["query_id"] = query_id
            if stage:
                rec["stage"] = stage
            if node:
                rec["node"] = node
            if splits_total is not None:
                rec["splits_total"] = splits_total

    def finish(self, query_id: Optional[str]) -> None:
        """Terminal-rollup hook (scheduler finalize): the query's live
        view is complete; clamp progress and schedule eviction."""
        if not query_id:
            return
        with self._lock:
            q = self._queries.get(query_id)
            if q is None or q["done"]:
                return
            q["done"] = True
            q["high_water"] = 1.0
            self._finished_order.append(query_id)
            while len(self._finished_order) > MAX_FINISHED_QUERIES:
                old = self._finished_order.pop(0)
                dead = self._queries.pop(old, None)
                for tid in (dead or {}).get("task_ids", ()):
                    self._tasks.pop(tid, None)

    # -- the heartbeat fold ------------------------------------------------

    def fold(self, node_id: str, payload: Optional[dict],
             now: Optional[float] = None) -> None:
        """Merge one worker's heartbeat: absolute-valued entries for
        every task that changed since the worker's cursor, plus the
        node's utilization snapshot. Idempotent — replayed deltas fold
        to the same state."""
        if not payload:
            return
        now = time.time() if now is None else now
        diagnoses = []
        escalations = []
        with self._lock:
            self.folds += 1
            util = payload.get("utilization") or {}
            busy = payload.get("busy") or {}
            self._nodes[node_id] = {
                "device": float(util.get("device", 0.0)),
                "host": float(util.get("host", 0.0)),
                "busy_device_ms": float(busy.get("deviceMs", 0.0)),
                "busy_host_ms": float(busy.get("hostMs", 0.0)),
                "ts": now}
            touched: Set[str] = set()
            for e in payload.get("tasks", ()):
                tid = e.get("taskId")
                if not tid:
                    continue
                rec = self._tasks.get(tid)
                if rec is None:
                    # heartbeat beat the registration (or an untracked
                    # task): hold it unattributed; a later
                    # register_task adopts it into its query
                    rec = self._tasks[tid] = {
                        "query_id": None, "task_id": tid, "stage": "",
                        "node": node_id, "state": "PENDING",
                        "splits_done": 0, "splits_total": None,
                        "rows": 0, "bytes": 0, "wall_ms": 0.0,
                        "device_ms": 0.0, "host_ms": 0.0,
                        "compile_ms": 0.0, "updated": 0.0}
                rec["node"] = node_id
                rec["state"] = e.get("state", rec["state"])
                rec["splits_done"] = int(e.get("splitsDone", 0))
                if int(e.get("splitsTotal", 0) or 0) > 0:
                    rec["splits_total"] = int(e["splitsTotal"])
                rec["rows"] = int(e.get("rowsOut", 0))
                rec["bytes"] = int(e.get("bytesOut", 0))
                rec["wall_ms"] = float(e.get("wallMs", 0.0))
                rec["device_ms"] = float(e.get("deviceMs", 0.0))
                rec["host_ms"] = float(e.get("hostMs", 0.0))
                rec["compile_ms"] = float(e.get("compileMs", 0.0))
                rec["updated"] = now
                if rec["query_id"]:
                    touched.add(rec["query_id"])
            # advance/stall bookkeeping: only queries with live work on
            # THIS node get their stale counter bumped by its heartbeat
            for qid, q in self._queries.items():
                if q["done"]:
                    continue
                recs = [self._tasks[t] for t in q["task_ids"]
                        if t in self._tasks]
                if not any(r["node"] == node_id and
                           r["state"] in ("PENDING", "RUNNING")
                           for r in recs):
                    continue
                sig = (sum(r["splits_done"] for r in recs),
                       sum(r["rows"] for r in recs),
                       sum(r["bytes"] for r in recs),
                       tuple(sorted(r["state"] for r in recs)))
                if sig != q["advance_sig"]:
                    q["advance_sig"] = sig
                    q["stale_folds"] = 0
                    q["diagnosed"] = False
                    continue
                q["stale_folds"] += 1
                if q["stale_folds"] >= self.stuck_after and \
                        not q["diagnosed"]:
                    d = self._diagnose_locked(qid, q, recs)
                    if d is not None:
                        q["diagnosed"] = True
                        q["diagnosis"] = d
                        diagnoses.append(d)
                if self.escalate_after > 0 and q["diagnosed"] and \
                        not q.get("escalated") and q["stale_folds"] >= \
                        self.stuck_after + self.escalate_after:
                    q["escalated"] = True
                    escalations.append((qid, q["stale_folds"]))
        # attach + log OUTSIDE the lock (tracked_lookup takes the
        # tracker's lock; the log handler may block)
        for d in diagnoses:
            self._publish_diagnosis(d)
        for qid, stale in escalations:
            if self.terminate is None:
                continue
            try:
                self.terminate(
                    qid, reason="stuck",
                    message="Query terminated by the stuck-query "
                            f"escalator: live stats stalled for {stale} "
                            "consecutive heartbeats past diagnosis")
            except Exception:  # noqa: BLE001 — escalation must not
                pass           # fail the heartbeat fold

    def _diagnose_locked(self, qid: str, q: dict,
                         recs: List[dict]) -> Optional[dict]:
        live = [r for r in recs if r["state"] in ("PENDING", "RUNNING")]
        if not live:
            return None
        # the suspect: split-holding producers outrank splitless waiters
        # (a consumer in exchange-wait is stalled BECAUSE its upstream
        # is), then least split progress, longest wall among ties
        suspect = min(live, key=lambda r: (
            0 if (r.get("splits_total") or 0) > 0 else 1,
            _split_frac(r), -r.get("wall_ms", 0.0)))
        # split-time skew across the suspect's stage peers
        peers = [r for r in recs if r["stage"] == suspect["stage"]
                 and r.get("splits_done", 0) > 0
                 and r.get("wall_ms", 0.0) > 0]
        ratio = 0.0
        if peers:
            avgs = [r["wall_ms"] / r["splits_done"] for r in peers]
            med = statistics.median(avgs)
            if med > 0:
                ratio = round(max(avgs) / med, 3)
        return {"queryId": qid, "stage": suspect["stage"] or "?",
                "taskId": suspect["task_id"],
                "node": suspect.get("node", ""),
                "phase": _phase_guess(suspect),
                "skewRatio": ratio,
                "staleHeartbeats": q["stale_folds"],
                "progress": round(q["high_water"], 4),
                "ts": time.time()}

    def _publish_diagnosis(self, d: dict) -> None:
        from ..metrics import STUCK_QUERIES_DIAGNOSED
        STUCK_QUERIES_DIAGNOSED.inc()
        tq = self.tracked_lookup(d["queryId"]) \
            if self.tracked_lookup else None
        if tq is not None:
            tq.live_diagnosis = d
        from ..utils.log import query_context
        log.warning(
            "%sstuck query: live stats stalled for %d heartbeats — "
            "stage %s task %s on %s, likely phase %s, split-time skew "
            "%.2fx, progress %.1f%%",
            query_context(d["queryId"]), d["staleHeartbeats"],
            d["stage"], d["taskId"], d["node"] or "?", d["phase"],
            d["skewRatio"], 100 * d["progress"])

    # -- progress ----------------------------------------------------------

    def progress(self, query_id: Optional[str]) -> Optional[float]:
        """Split-weighted progress in [0, 1], monotonic per query (the
        high-water clamp): Σ splits_done / Σ splits_total over the
        query's registered tasks; tasks without splits (exchange
        consumers, writers) weigh one split each, done at FINISHED.
        None for queries this store never saw."""
        if not query_id:
            return None
        with self._lock:
            q = self._queries.get(query_id)
            if q is None:
                return None
            if q["done"]:
                return 1.0
            recs = [self._tasks[t] for t in q["task_ids"]
                    if t in self._tasks]
            done = total = 0.0
            for r in recs:
                w = max(1, r.get("splits_total") or 1)
                total += w
                done += w * _split_frac(r)
            ratio = (done / total) if total > 0 else 0.0
            q["high_water"] = max(q["high_water"], min(ratio, 1.0))
            return round(q["high_water"], 6)

    def dominant_stage(self, query_id: Optional[str]) -> str:
        """The stage currently holding the most incomplete split work —
        the 'where is this query right now' label beside the progress
        ratio (and the OOM post-mortem's dominant stage)."""
        if not query_id:
            return ""
        with self._lock:
            q = self._queries.get(query_id)
            if q is None:
                return ""
            recs = [dict(self._tasks[t]) for t in q["task_ids"]
                    if t in self._tasks]
        live = [r for r in recs
                if r["state"] in ("PENDING", "RUNNING")] or recs
        if not live:
            return ""
        by_stage: Dict[str, List[dict]] = {}
        for r in live:
            by_stage.setdefault(r["stage"] or "?", []).append(r)

        def remaining(rs: List[dict]) -> float:
            return sum((r.get("splits_total") or 1) *
                       (1.0 - _split_frac(r)) for r in rs)

        return max(sorted(by_stage.items()),
                   key=lambda kv: remaining(kv[1]))[0]

    # -- read surfaces -----------------------------------------------------

    def query_rollup(self, query_id: Optional[str]) -> Optional[dict]:
        """Live per-stage rollup for /v1/query stageStats: {stages:
        {stage: {tasks, tasks_done, splits_done, splits_total, rows,
        bytes, device_ms, host_ms}}, tasks, progress, diagnosis}."""
        if not query_id:
            return None
        with self._lock:
            q = self._queries.get(query_id)
            if q is None:
                return None
            recs = [dict(self._tasks[t]) for t in q["task_ids"]
                    if t in self._tasks]
            diagnosis = q["diagnosis"]
        stages: Dict[str, dict] = {}
        for r in recs:
            st = stages.setdefault(r["stage"] or "?", {
                "tasks": 0, "tasks_done": 0, "splits_done": 0,
                "splits_total": 0, "rows": 0, "bytes": 0,
                "device_ms": 0.0, "host_ms": 0.0})
            st["tasks"] += 1
            if r["state"] in ("FINISHED", "FAILED", "CANCELED"):
                st["tasks_done"] += 1
            st["splits_done"] += r.get("splits_done", 0)
            st["splits_total"] += r.get("splits_total") or 0
            st["rows"] += r.get("rows", 0)
            st["bytes"] += r.get("bytes", 0)
            st["device_ms"] += r.get("device_ms", 0.0)
            st["host_ms"] += r.get("host_ms", 0.0)
        return {"stages": stages, "tasks": recs,
                "progress": self.progress(query_id),
                "diagnosis": diagnosis}

    def live_tasks(self) -> List[dict]:
        """Every live task record (system.runtime.tasks' mid-flight
        rows), newest update first."""
        with self._lock:
            recs = [dict(r) for r in self._tasks.values()]
        recs.sort(key=lambda r: -r.get("updated", 0.0))
        return recs

    def live_queries(self) -> List[dict]:
        """Per-query live summaries for system.runtime.live_queries."""
        with self._lock:
            qids = list(self._queries.keys())
        out = []
        for qid in qids:
            roll = self.query_rollup(qid)
            if roll is None:
                continue
            tq = self.tracked_lookup(qid) if self.tracked_lookup else None
            stages = roll["stages"]
            out.append({
                "query_id": qid,
                "state": tq.state if tq is not None else "",
                "progress": roll["progress"] or 0.0,
                "stages": len(stages),
                "tasks": sum(s["tasks"] for s in stages.values()),
                "tasks_done": sum(s["tasks_done"]
                                  for s in stages.values()),
                "splits_done": sum(s["splits_done"]
                                   for s in stages.values()),
                "splits_total": sum(s["splits_total"]
                                    for s in stages.values()),
                "rows": sum(s["rows"] for s in stages.values()),
                "bytes": sum(s["bytes"] for s in stages.values()),
                "stuck": bool(roll["diagnosis"]),
                "diagnosis": (roll["diagnosis"] or {}).get("stage", "")})
        return out

    def utilization(self) -> List[dict]:
        """Per-node busy snapshots for system.runtime.utilization:
        one row per (node, tier)."""
        with self._lock:
            nodes = {n: dict(s) for n, s in self._nodes.items()}
        rows = []
        for node, s in sorted(nodes.items()):
            for tier in ("device", "host"):
                rows.append({"node_id": node, "tier": tier,
                             "busy_fraction": s.get(tier, 0.0),
                             "busy_ms": s.get(f"busy_{tier}_ms", 0.0),
                             "ts": s.get("ts", 0.0)})
        return rows

    # -- hedging feed ------------------------------------------------------

    def straggler_task_ids(self, query_id: Optional[str],
                           multiplier: float) -> Set[str]:
        """Live-skew evidence for the hedging loop: RUNNING tasks whose
        observed per-split time (or, for tasks yet to finish a split,
        wall so far) exceeds `multiplier` x the median per-split time
        of their stage peers. Empty when there is no live evidence —
        hedging then behaves exactly as before."""
        if not query_id or multiplier <= 0:
            return set()
        with self._lock:
            q = self._queries.get(query_id)
            if q is None:
                return set()
            recs = [dict(self._tasks[t]) for t in q["task_ids"]
                    if t in self._tasks]
        now = time.time()
        by_stage: Dict[str, List[dict]] = {}
        for r in recs:
            by_stage.setdefault(r["stage"], []).append(r)
        out: Set[str] = set()
        for peers in by_stage.values():
            avgs = [r["wall_ms"] / r["splits_done"] for r in peers
                    if r.get("splits_done", 0) > 0
                    and r.get("wall_ms", 0.0) > 0]
            if not avgs:
                continue
            med = statistics.median(avgs)
            if med <= 0:
                continue
            for r in peers:
                if r["state"] != "RUNNING":
                    continue
                # delta encoding means a stalled task ships nothing —
                # its folded wall_ms stops moving exactly when its real
                # wall keeps running. Extend by the time since its last
                # fold so a frozen task's observed pace climbs in real
                # time instead of freezing with its counters.
                wall = r.get("wall_ms", 0.0)
                if r.get("updated", 0.0):
                    wall += max(0.0, (now - r["updated"]) * 1000)
                pace = (wall / r["splits_done"]
                        if r.get("splits_done", 0) > 0 else wall)
                if pace > multiplier * med and \
                        wall >= STRAGGLER_MIN_WALL_MS:
                    out.add(r["task_id"])
        return out
