"""Authentication + access control (minimal production shape).

Reference: the layered security stack — password authenticators
(plugin/trino-password-authenticators), AccessControlManager dispatching
to system access controls (security/AccessControlManager.java), and the
file-based rules plugin (FileBasedSystemAccessControl). Here: a static
password/token authenticator on the coordinator's HTTP intake, and a
rule-list access control consulted at dispatch with the statement's
RESOLVED table references (post-planning, so views/CTEs can't smuggle
reads past the checker).
"""

from __future__ import annotations

import fnmatch
from dataclasses import dataclass
from typing import List, Optional, Tuple


class AccessDeniedError(RuntimeError):
    """Authorization failure — never retried, surfaced to the client
    (spi/security/AccessDeniedException.java)."""


class AuthenticationError(RuntimeError):
    """Credential failure — HTTP 401 at the protocol layer."""


# --------------------------------------------------------------------------
# cluster-internal shared secret (the reference's internal-communication
# shared secret, security/internal-communication.md): when
# TRINO_TPU_INTERNAL_SECRET is set, every data-plane/control-plane route
# that only cluster members may call (worker task/exchange routes, the
# coordinator announce route) requires the header; unset = open cluster
# (dev/test compatibility).
# --------------------------------------------------------------------------

INTERNAL_HEADER = "X-Trino-Internal-Bearer"


def internal_secret() -> Optional[str]:
    import os
    return os.environ.get("TRINO_TPU_INTERNAL_SECRET") or None


def internal_headers() -> dict:
    """Headers a cluster member attaches to internal HTTP calls
    (announce, task create/status, exchange page pulls)."""
    secret = internal_secret()
    return {INTERNAL_HEADER: secret} if secret else {}


def check_internal_request(headers) -> bool:
    """True when the request may use an internal route: either the
    cluster is open (no secret configured) or the caller presented the
    matching secret (constant-time compare)."""
    import hmac
    secret = internal_secret()
    if secret is None:
        return True
    presented = headers.get(INTERNAL_HEADER, "")
    return hmac.compare_digest(str(presented), secret)


class PasswordAuthenticator:
    """Static user -> secret map (the PasswordAuthenticator SPI shape;
    file/LDAP backends would subclass). Secrets compare in constant
    time."""

    def __init__(self, credentials: dict):
        self._creds = dict(credentials)

    def authenticate(self, user: str, secret: Optional[str]) -> str:
        import hmac
        want = self._creds.get(user)
        if want is None or secret is None or \
                not hmac.compare_digest(str(want), str(secret)):
            raise AuthenticationError(f"invalid credentials for {user!r}")
        return user


@dataclass(frozen=True)
class AccessRule:
    """One allow/deny rule; glob patterns per part
    (FileBasedSystemAccessControl's catalog/schema/table rules)."""
    user: str = "*"
    catalog: str = "*"
    schema: str = "*"
    table: str = "*"
    privileges: Tuple[str, ...] = ("select", "write")
    allow: bool = True

    def matches(self, user, catalog, schema, table, privilege) -> bool:
        return (fnmatch.fnmatchcase(user, self.user) and
                fnmatch.fnmatchcase(catalog, self.catalog) and
                fnmatch.fnmatchcase(schema, self.schema) and
                fnmatch.fnmatchcase(table, self.table) and
                privilege in self.privileges)


class AllowAllAccessControl:
    """Default: open cluster (AllowAllSystemAccessControl)."""

    def check(self, user, catalog, schema, table, privilege) -> None:
        pass


class RuleAccessControl:
    """First-match-wins rule list; NO match denies (the reference's
    file-based control denies whatever the rules don't grant)."""

    def __init__(self, rules: List[AccessRule]):
        self.rules = list(rules)

    def check(self, user, catalog, schema, table, privilege) -> None:
        for r in self.rules:
            if r.matches(user, catalog, schema, table, privilege):
                if r.allow:
                    return
                break
        raise AccessDeniedError(
            f"Access Denied: user {user!r} cannot {privilege} "
            f"{catalog}.{schema}.{table}")


def _plan_scan_nodes(root):
    """Every ScanNode reachable from a plan, INCLUDING subplans embedded
    in expressions (scalar / IN subqueries carry their planned subtree
    inside ScalarSubqueryRef / InSubqueryRef) — a denied table must not
    slip past the checker inside a select-item or SET subquery."""
    from .. import ir
    from ..planner import logical as L
    from ..planner.fragmenter import _subtree_nodes

    def node_exprs(n):
        if isinstance(n, L.FilterNode):
            return (n.predicate,)
        if isinstance(n, L.ProjectNode):
            return n.exprs
        if isinstance(n, L.AggregateNode):
            return tuple(a.arg for a in n.aggs if a.arg is not None)
        return ()

    todo = [root]
    while todo:
        node = todo.pop()
        for n in _subtree_nodes(node):
            if isinstance(n, L.ScanNode):
                yield n
            for e in node_exprs(n):
                for sub in ir.walk(e):
                    plan = getattr(sub, "plan", None)
                    if isinstance(plan, L.PlanNode):
                        todo.append(plan)


def statement_table_refs(session, sql: str):
    """(privilege, catalog, schema, table) references of a statement,
    resolved through the planner (scans of the final plan, not raw AST
    names — CTEs/derived tables resolve first). DML adds a write ref on
    its target."""
    from ..planner import logical as L
    from ..sql import ast_nodes as A
    from ..sql.parser import parse
    stmt = parse(sql)
    refs = []

    def scan_refs(node):
        for n in _plan_scan_nodes(node):
            refs.append(("select", n.catalog, n.schema_name, n.table))

    def qualify(name_parts):
        parts = list(name_parts)
        if len(parts) == 3:
            return parts
        if len(parts) == 2:
            return [session.default_cat] + parts
        return [session.default_cat, session.default_schema] + parts

    if isinstance(stmt, (A.Query, A.SetOp, A.Values)):
        rel = session.planner().plan_query(stmt)
        scan_refs(rel.node)
    elif isinstance(stmt, (A.InsertInto, A.Update, A.Delete,
                           A.MergeInto, A.CreateTable, A.DropTable)):
        target = getattr(stmt, "table", None) or \
            getattr(stmt, "target", None)
        if target is not None:
            parts = qualify(target if isinstance(target, (list, tuple))
                            else str(target).split("."))
            refs.append(("write", *parts))
        inner = getattr(stmt, "query", None)
        if isinstance(inner, (A.Query, A.SetOp, A.Values)):
            rel = session.planner().plan_query(inner)
            scan_refs(rel.node)
        # UPDATE/DELETE read through their WHERE clause and SET
        # expressions (subqueries included): plan the statement's shadow
        # query over the target — the same query execute_dml runs — and
        # collect its ScanNodes as READ refs, exactly like the MERGE
        # USING fix. Without this, any write grant could exfiltrate a
        # denied table via `WHERE x IN (SELECT ... FROM denied)`.
        if isinstance(stmt, (A.Update, A.Delete)) and target is not None:
            tparts = [p.lower() for p in qualify(
                target if isinstance(target, (list, tuple))
                else str(target).split("."))]
            items = [A.SelectItem(A.NumberLit("1"), "$x")]
            if isinstance(stmt, A.Update):
                for j, (_col, expr) in enumerate(stmt.assignments):
                    items.append(A.SelectItem(expr, f"$v{j}"))
            shadow = A.Query(select=tuple(items), distinct=False,
                             relation=A.TableRef(tuple(tparts),
                                                 alias=tparts[-1]),
                             where=stmt.where, group_by=(), having=None,
                             order_by=(), limit=None)
            rel = session.planner().plan_query(shadow)
            for n in _plan_scan_nodes(rel.node):
                if (n.catalog, n.schema_name, n.table) != tuple(tparts):
                    # the target's own scan is implied by the write
                    # grant; every OTHER table the statement touches
                    # needs an explicit SELECT grant
                    refs.append(("select", n.catalog, n.schema_name,
                                 n.table))
        # MERGE's USING relation (and any relation AST) is READ: wrap it
        # in a trivial query so the planner resolves its table refs —
        # a denied table must not leak through the source side
        src = getattr(stmt, "source", None)
        if isinstance(src, A.Node) and not isinstance(src, (A.Query,)):
            if isinstance(src, A.TableRef):
                refs.append(("select", *qualify(src.name)))
            else:
                for n in _ast_subtree(src):
                    if isinstance(n, A.TableRef):
                        refs.append(("select", *qualify(n.name)))
        elif isinstance(src, A.Query):
            rel = session.planner().plan_query(src)
            scan_refs(rel.node)
    # SET SESSION / SHOW / EXPLAIN etc: no table privileges involved
    return refs


def _ast_subtree(node):
    import dataclasses
    yield node
    if dataclasses.is_dataclass(node):
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            items = v if isinstance(v, tuple) else (v,)
            for it in items:
                if dataclasses.is_dataclass(it):
                    yield from _ast_subtree(it)


def check_statement_access(access_control, session, sql: str,
                           user: str) -> None:
    """Dispatch-time authorization (DispatchManager.createQueryInternal's
    access-check step). Raises AccessDeniedError."""
    if isinstance(access_control, AllowAllAccessControl):
        return
    for privilege, cat, sch, tbl in statement_table_refs(session, sql):
        access_control.check(user, cat, sch, tbl, privilege)
