"""Authentication + access control (minimal production shape).

Reference: the layered security stack — password authenticators
(plugin/trino-password-authenticators), AccessControlManager dispatching
to system access controls (security/AccessControlManager.java), and the
file-based rules plugin (FileBasedSystemAccessControl). Here: a static
password/token authenticator on the coordinator's HTTP intake, and a
rule-list access control consulted at dispatch with the statement's
RESOLVED table references (post-planning, so views/CTEs can't smuggle
reads past the checker).
"""

from __future__ import annotations

import fnmatch
from dataclasses import dataclass
from typing import List, Optional, Tuple


class AccessDeniedError(RuntimeError):
    """Authorization failure — never retried, surfaced to the client
    (spi/security/AccessDeniedException.java)."""


class AuthenticationError(RuntimeError):
    """Credential failure — HTTP 401 at the protocol layer."""


class PasswordAuthenticator:
    """Static user -> secret map (the PasswordAuthenticator SPI shape;
    file/LDAP backends would subclass). Secrets compare in constant
    time."""

    def __init__(self, credentials: dict):
        self._creds = dict(credentials)

    def authenticate(self, user: str, secret: Optional[str]) -> str:
        import hmac
        want = self._creds.get(user)
        if want is None or secret is None or \
                not hmac.compare_digest(str(want), str(secret)):
            raise AuthenticationError(f"invalid credentials for {user!r}")
        return user


@dataclass(frozen=True)
class AccessRule:
    """One allow/deny rule; glob patterns per part
    (FileBasedSystemAccessControl's catalog/schema/table rules)."""
    user: str = "*"
    catalog: str = "*"
    schema: str = "*"
    table: str = "*"
    privileges: Tuple[str, ...] = ("select", "write")
    allow: bool = True

    def matches(self, user, catalog, schema, table, privilege) -> bool:
        return (fnmatch.fnmatchcase(user, self.user) and
                fnmatch.fnmatchcase(catalog, self.catalog) and
                fnmatch.fnmatchcase(schema, self.schema) and
                fnmatch.fnmatchcase(table, self.table) and
                privilege in self.privileges)


class AllowAllAccessControl:
    """Default: open cluster (AllowAllSystemAccessControl)."""

    def check(self, user, catalog, schema, table, privilege) -> None:
        pass


class RuleAccessControl:
    """First-match-wins rule list; NO match denies (the reference's
    file-based control denies whatever the rules don't grant)."""

    def __init__(self, rules: List[AccessRule]):
        self.rules = list(rules)

    def check(self, user, catalog, schema, table, privilege) -> None:
        for r in self.rules:
            if r.matches(user, catalog, schema, table, privilege):
                if r.allow:
                    return
                break
        raise AccessDeniedError(
            f"Access Denied: user {user!r} cannot {privilege} "
            f"{catalog}.{schema}.{table}")


def statement_table_refs(session, sql: str):
    """(privilege, catalog, schema, table) references of a statement,
    resolved through the planner (scans of the final plan, not raw AST
    names — CTEs/derived tables resolve first). DML adds a write ref on
    its target."""
    from ..planner import logical as L
    from ..planner.fragmenter import _subtree_nodes
    from ..sql import ast_nodes as A
    from ..sql.parser import parse
    stmt = parse(sql)
    refs = []

    def scan_refs(node):
        for n in _subtree_nodes(node):
            if isinstance(n, L.ScanNode):
                refs.append(("select", n.catalog, n.schema_name, n.table))

    def qualify(name_parts):
        parts = list(name_parts)
        if len(parts) == 3:
            return parts
        if len(parts) == 2:
            return [session.default_cat] + parts
        return [session.default_cat, session.default_schema] + parts

    if isinstance(stmt, (A.Query, A.SetOp, A.Values)):
        rel = session.planner().plan_query(stmt)
        scan_refs(rel.node)
    elif isinstance(stmt, (A.InsertInto, A.Update, A.Delete,
                           A.MergeInto, A.CreateTable, A.DropTable)):
        target = getattr(stmt, "table", None) or \
            getattr(stmt, "target", None)
        if target is not None:
            parts = qualify(target if isinstance(target, (list, tuple))
                            else str(target).split("."))
            refs.append(("write", *parts))
        inner = getattr(stmt, "query", None)
        if isinstance(inner, (A.Query, A.SetOp, A.Values)):
            rel = session.planner().plan_query(inner)
            scan_refs(rel.node)
        # MERGE's USING relation (and any relation AST) is READ: wrap it
        # in a trivial query so the planner resolves its table refs —
        # a denied table must not leak through the source side
        src = getattr(stmt, "source", None)
        if isinstance(src, A.Node) and not isinstance(src, (A.Query,)):
            if isinstance(src, A.TableRef):
                refs.append(("select", *qualify(src.name)))
            else:
                for n in _ast_subtree(src):
                    if isinstance(n, A.TableRef):
                        refs.append(("select", *qualify(n.name)))
        elif isinstance(src, A.Query):
            rel = session.planner().plan_query(src)
            scan_refs(rel.node)
    # SET SESSION / SHOW / EXPLAIN etc: no table privileges involved
    return refs


def _ast_subtree(node):
    import dataclasses
    yield node
    if dataclasses.is_dataclass(node):
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            items = v if isinstance(v, tuple) else (v,)
            for it in items:
                if dataclasses.is_dataclass(it):
                    yield from _ast_subtree(it)


def check_statement_access(access_control, session, sql: str,
                           user: str) -> None:
    """Dispatch-time authorization (DispatchManager.createQueryInternal's
    access-check step). Raises AccessDeniedError."""
    if isinstance(access_control, AllowAllAccessControl):
        return
    for privilege, cat, sch, tbl in statement_table_refs(session, sql):
        access_control.check(user, cat, sch, tbl, privilege)
