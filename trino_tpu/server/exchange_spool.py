"""Durable exchange spool: task outputs persisted across attempts.

Reference: the fault-tolerant execution exchange —
spi/exchange/ExchangeManager.java + FileSystemExchangeManager.java:40 spool
every task's output partitions durably, so a retry re-runs only failed
tasks and consumers deduplicate attempts
(DeduplicatingDirectExchangeBuffer.java:87,
spi/exchange/ExchangeSourceOutputSelector.java).

TPU runtime shape: the coordinator is the exchange consumer. Every drained
task's pages are written here keyed by the *work identity* — a digest of
(fragment, splits) — not the attempt, so any successful attempt satisfies
the key and later attempts of the same work are never re-dispatched: the
scheduler checks the spool before POSTing a task, which turns retry-policy
QUERY into task-granularity recovery (only unfinished work re-executes).
Local disk plays the object store's role (the SPI boundary to swap in a
real one is this class)."""

from __future__ import annotations

import hashlib
import os
import struct
import tempfile
import threading
from typing import List, Optional


class ExchangeSpool:
    def __init__(self, root: Optional[str] = None, injector=None):
        # default scope is one coordinator lifetime (fresh directory):
        # the recovery quantum is a retried attempt within it. Pass an
        # explicit root for durability across coordinator restarts.
        self.root = root or tempfile.mkdtemp(prefix="trino_tpu_exchange_")
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        self.injector = injector          # chaos hook (SPOOL_READ/WRITE)
        self.checksum_rejects = 0         # corrupt spool entries dropped
        self.write_skips = 0              # best-effort puts that failed

    @staticmethod
    def work_key(fragment_blob: str, splits) -> str:
        """Digest of the task's deterministic work identity."""
        h = hashlib.sha256()
        h.update(fragment_blob.encode())
        for s in splits:
            h.update(f"{s.catalog}.{s.schema_name}.{s.table}"
                     f":{s.start}+{s.count}".encode())
        return h.hexdigest()[:32]

    # container layout: b"TSPL" | npages u32 | per page: len u64 | frame
    _MAGIC = b"TSPL"

    def _path(self, key: str) -> str:
        return os.path.join(self.root, f"{key}.spool")

    def get(self, key: str) -> Optional[List[bytes]]:
        """Read spooled pages; a miss OR any integrity failure returns
        None so the scheduler re-dispatches the work — the spool is a
        recovery accelerator, never a correctness dependency. Every page
        frame is CRC32C-verified here (the reference verifies exchange
        source handles the same way); a corrupt container is deleted so
        the next attempt re-creates it from a live task."""
        from ..metrics import SPOOL_HITS, SPOOL_MISSES
        from .failureinjector import InjectedFailure
        from .pageserde import PageChecksumError, verify_page
        try:
            if self.injector is not None:
                self.injector.maybe_fail("SPOOL_READ", key)
            with open(self._path(key), "rb") as f:
                blob = f.read()
            if blob[:4] != self._MAGIC:
                SPOOL_MISSES.inc()
                return None
            (npages,) = struct.unpack_from("<I", blob, 4)
            off = 8
            pages = []
            for _ in range(npages):
                (ln,) = struct.unpack_from("<Q", blob, off)
                off += 8
                pages.append(blob[off:off + ln])
                off += ln
            for p in pages:
                verify_page(p)
            SPOOL_HITS.inc()
            return pages
        except PageChecksumError:
            self.checksum_rejects += 1
            SPOOL_MISSES.inc()
            try:
                os.unlink(self._path(key))
            except OSError:
                pass
            return None
        except (OSError, ValueError, struct.error, InjectedFailure):
            SPOOL_MISSES.inc()
            return None

    def put(self, key: str, pages: List[bytes]) -> None:
        """Persist one work unit's pages. Best-effort: persistence
        failures (disk full, injected faults) degrade to a spool miss on
        the next attempt, never a query failure."""
        from .failureinjector import InjectedFailure
        path = self._path(key)
        try:
            if self.injector is not None:
                self.injector.maybe_fail("SPOOL_WRITE", key)
                # payload corruption injected here is caught by get()'s
                # per-page CRC32C check — the write itself succeeds
                pages = [self.injector.corrupt_page("SPOOL_WRITE", key, p)
                         for p in pages]
            with self._lock:
                tmp = path + ".tmp"
                # write-then-rename: a crashed writer never leaves a torn
                # file a later attempt could read (exactly-one-attempt)
                with open(tmp, "wb") as f:
                    f.write(self._MAGIC + struct.pack("<I", len(pages)))
                    for p in pages:
                        f.write(struct.pack("<Q", len(p)))
                        f.write(p)
                os.replace(tmp, path)
        except (OSError, InjectedFailure):
            self.write_skips += 1

    def delete(self, key: str) -> None:
        """Drop one container (spill partitions are consumed once)."""
        try:
            os.unlink(self._path(key))
        except OSError:
            pass

    def clear(self) -> None:
        for f in os.listdir(self.root):
            if f.endswith((".json", ".spool")):
                try:
                    os.unlink(os.path.join(self.root, f))
                except OSError:
                    pass

    def sweep(self, keep=()) -> int:
        """Orphan sweep for a durable spool root after a coordinator
        failover: drop every container whose work key no live (ledger-
        known, non-terminal) query can claim. Returns the number of
        containers removed; leftover .tmp files from a crashed writer
        are always swept."""
        keep = set(keep)
        removed = 0
        try:
            names = os.listdir(self.root)
        except OSError:
            return 0
        for f in names:
            path = os.path.join(self.root, f)
            if f.endswith(".tmp"):
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            if not f.endswith(".spool") or f[:-len(".spool")] in keep:
                continue
            try:
                os.unlink(path)
                removed += 1
            except OSError:
                pass
        return removed
