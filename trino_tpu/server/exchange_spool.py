"""Durable exchange spool: task outputs persisted across attempts.

Reference: the fault-tolerant execution exchange —
spi/exchange/ExchangeManager.java + FileSystemExchangeManager.java:40 spool
every task's output partitions durably, so a retry re-runs only failed
tasks and consumers deduplicate attempts
(DeduplicatingDirectExchangeBuffer.java:87,
spi/exchange/ExchangeSourceOutputSelector.java).

TPU runtime shape: the coordinator is the exchange consumer. Every drained
task's pages are written here keyed by the *work identity* — a digest of
(fragment, splits) — not the attempt, so any successful attempt satisfies
the key and later attempts of the same work are never re-dispatched: the
scheduler checks the spool before POSTing a task, which turns retry-policy
QUERY into task-granularity recovery (only unfinished work re-executes).
Local disk plays the object store's role (the SPI boundary to swap in a
real one is this class)."""

from __future__ import annotations

import hashlib
import os
import struct
import tempfile
import threading
from typing import List, Optional


class ExchangeSpool:
    def __init__(self, root: Optional[str] = None):
        # default scope is one coordinator lifetime (fresh directory):
        # the recovery quantum is a retried attempt within it. Pass an
        # explicit root for durability across coordinator restarts.
        self.root = root or tempfile.mkdtemp(prefix="trino_tpu_exchange_")
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()

    @staticmethod
    def work_key(fragment_blob: str, splits) -> str:
        """Digest of the task's deterministic work identity."""
        h = hashlib.sha256()
        h.update(fragment_blob.encode())
        for s in splits:
            h.update(f"{s.catalog}.{s.schema_name}.{s.table}"
                     f":{s.start}+{s.count}".encode())
        return h.hexdigest()[:32]

    # container layout: b"TSPL" | npages u32 | per page: len u64 | frame
    _MAGIC = b"TSPL"

    def _path(self, key: str) -> str:
        return os.path.join(self.root, f"{key}.spool")

    def get(self, key: str) -> Optional[List[bytes]]:
        try:
            with open(self._path(key), "rb") as f:
                blob = f.read()
            if blob[:4] != self._MAGIC:
                return None
            (npages,) = struct.unpack_from("<I", blob, 4)
            off = 8
            pages = []
            for _ in range(npages):
                (ln,) = struct.unpack_from("<Q", blob, off)
                off += 8
                pages.append(blob[off:off + ln])
                off += ln
            return pages
        except (OSError, ValueError, struct.error):
            return None

    def put(self, key: str, pages: List[bytes]) -> None:
        # write-then-rename: a crashed writer never leaves a torn file a
        # later attempt could read (the exactly-one-attempt guarantee)
        path = self._path(key)
        with self._lock:
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(self._MAGIC + struct.pack("<I", len(pages)))
                for p in pages:
                    f.write(struct.pack("<Q", len(p)))
                    f.write(p)
            os.replace(tmp, path)

    def clear(self) -> None:
        for f in os.listdir(self.root):
            if f.endswith((".json", ".spool")):
                try:
                    os.unlink(os.path.join(self.root, f))
                except OSError:
                    pass
