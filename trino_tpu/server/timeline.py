"""Critical-path wall-time attribution for completed queries.

Reference: Trino's QueryStats carry queued/analysis/planning/execution
time splits and EXPLAIN ANALYZE prints per-stage wall; what it does not
do — and what the ≥5x device-speedup story needs — is a per-query
attribution that says WHERE elapsed wall went: admission queue, planner,
scheduler overhead, exchange waits, device compute, host compute,
compile, spill, retry overhead, write-commit.

The discipline is the round-10 device/host/compile invariant, applied to
the whole query: every phase estimate is clipped into the elapsed-wall
budget and the residual lands in `other`, so the reported phases ALWAYS
sum exactly to elapsed wall (tier-1 asserts it). Estimates come from the
best available source and degrade gracefully:

- queued:     state-machine timestamps (stamped on every transition), so
              admission holds show up even untraced;
- plan/retry/write-commit/schedule: coordinator spans when tracing is on
  (plan-distributed, per-attempt query spans, write-commit, stage spans);
- device/host/compile: the per-stage BLOCKING task (max wall) of the
  scheduler's TaskStats rollup — profiled runs split its wall into
  device + compile + host-rest, unprofiled runs ride in host;
- exchange-wait: the largest per-task sum of adopted worker
  `exchange-pull` spans (the blocking task's wait, not the overcounted
  concurrent total).

The blocking critical path across concurrent stages is computed from the
coordinator stage spans: overlapping intervals form a concurrency group
and the longest member of each group is charged (the classic
program-activity-graph reduction).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

PHASES = ("queued", "plan", "schedule", "exchange-wait", "device", "host",
          "compile", "spill", "retry", "write-commit", "other")

_PLAN_SPANS = ("plan", "optimize", "plan-distributed")
# coordinator stage spans -> the scheduler's task-rollup stage keys
_STAGE_SPANS = {"source-stage": "source",
                "partitioned-exchange": "partitioned",
                "final-stage": None,
                "distributed-write": None}


def _dur_s(span: dict) -> float:
    return max(0.0, float(span.get("durationMs", 0.0)) / 1000.0)


def _start_s(span: dict) -> float:
    return float(span.get("startTimeUnixNano", 0)) / 1e9


def _stage_key(span: dict) -> Optional[str]:
    name = span.get("name")
    if name == "build-stage":
        frag = (span.get("attributes") or {}).get("fragment")
        return f"build-{frag}"
    return _STAGE_SPANS.get(name)


def stage_intervals(spans: List[dict]) -> List[dict]:
    """Coordinator stage spans as [{'name','start','end'}] intervals on
    one clock (the coordinator's), the critical-path input."""
    out = []
    for s in spans or ():
        name = s.get("name")
        if name in _STAGE_SPANS or name == "build-stage":
            start = _start_s(s)
            label = name
            if name == "build-stage":
                frag = (s.get("attributes") or {}).get("fragment")
                label = f"build-stage[{frag}]"
            out.append({"name": label, "start": start,
                        "end": start + _dur_s(s)})
    return out


def critical_path(intervals: List[dict]) -> Tuple[float, List[dict]]:
    """Blocking path through possibly-concurrent intervals: transitively
    overlapping intervals form a concurrency group; each group charges
    only its LONGEST member (the blocker), sequential groups sum.
    Returns (total_seconds, [{'name','seconds'}] in time order)."""
    ivs = sorted((i for i in intervals or () if i["end"] >= i["start"]),
                 key=lambda i: (i["start"], i["end"]))
    picks: List[dict] = []
    total = 0.0
    group: List[dict] = []
    group_end = float("-inf")
    for iv in ivs + [None]:
        if iv is not None and (not group or iv["start"] < group_end):
            group.append(iv)
            group_end = max(group_end, iv["end"])
            continue
        if group:
            blocker = max(group, key=lambda i: i["end"] - i["start"])
            seconds = blocker["end"] - blocker["start"]
            picks.append({"name": blocker["name"],
                          "seconds": round(seconds, 6)})
            total += seconds
        if iv is not None:
            group = [iv]
            group_end = iv["end"]
        else:
            group = []
    return total, picks


def _exchange_wait_s(spans: List[dict]) -> float:
    """The blocking exchange wait: worker `exchange-pull` spans grouped
    by their parent (worker-task) span; the largest per-task sum is the
    wait the query could not overlap away."""
    groups: Dict[object, float] = {}
    for s in spans or ():
        if s.get("name") == "exchange-pull":
            groups[s.get("parentSpanId")] = \
                groups.get(s.get("parentSpanId"), 0.0) + _dur_s(s)
    return max(groups.values()) if groups else 0.0


def attribute_phases(wall_s: float, queued_s: float,
                     spans: Optional[List[dict]],
                     stage_stats: Optional[dict],
                     write_stats: Optional[dict] = None) -> Dict[str, float]:
    """Split `wall_s` into the PHASES dict. The invariant every caller
    (and tier-1) relies on: sum(result.values()) == wall_s exactly —
    estimates are proportionally scaled into the budget and the residual
    is `other`."""
    wall_s = max(0.0, wall_s)
    spans = spans or []
    lq = stage_stats or {}
    ph = {p: 0.0 for p in PHASES}
    ph["queued"] = min(max(0.0, queued_s), wall_s)

    for s in spans:
        if s.get("name") in _PLAN_SPANS:
            ph["plan"] += _dur_s(s)

    # retry overhead: every non-final per-attempt `query` span
    attempts = sorted((s for s in spans if s.get("name") == "query"),
                      key=_start_s)
    for s in attempts[:-1]:
        ph["retry"] += _dur_s(s)

    commit_spans = [s for s in spans if s.get("name") == "write-commit"]
    if commit_spans:
        ph["write-commit"] = sum(_dur_s(s) for s in commit_spans)
    elif write_stats and write_stats.get("commit_s"):
        ph["write-commit"] = max(0.0, float(write_stats["commit_s"]))

    # per-stage blocking-task attribution from the TaskStats rollup
    stages: Dict[str, List[dict]] = {}
    for rec in lq.get("tasks", ()):
        stages.setdefault(rec.get("stage") or "source", []).append(rec)
    span_by_stage: Dict[str, float] = {}
    for s in spans:
        key = _stage_key(s)
        if key is not None:
            span_by_stage[key] = span_by_stage.get(key, 0.0) + _dur_s(s)
    host_raw = 0.0
    for key, recs in stages.items():
        blocking = max(recs, key=lambda r: r.get("wall_ms", 0.0))
        bw = max(0.0, blocking.get("wall_ms", 0.0) / 1000.0)
        dev = max(0.0, blocking.get("device_ms", 0.0) / 1000.0)
        comp = max(0.0, blocking.get("compile_ms", 0.0) / 1000.0)
        ph["device"] += dev
        ph["compile"] += comp
        host_raw += max(0.0, bw - dev - comp)
        stage_span = span_by_stage.get(key)
        if stage_span is not None:
            ph["schedule"] += max(0.0, stage_span - bw)
    # final-stage / write orchestration wall with no task rollup behind
    # it is scheduler overhead too
    for s in spans:
        if s.get("name") == "final-stage":
            ph["schedule"] += _dur_s(s)

    exch = _exchange_wait_s(spans)
    ph["exchange-wait"] = exch
    # exchange pulls happen inside the blocking tasks' wall: subtract so
    # the wait is not double-counted against host
    ph["host"] = max(0.0, host_raw - exch)

    # clip the sub-phases into the busy budget, residual -> other
    busy = max(0.0, wall_s - ph["queued"])
    sub = [p for p in PHASES if p not in ("queued", "other")]
    total_sub = sum(ph[p] for p in sub)
    if total_sub > busy and total_sub > 0.0:
        factor = busy / total_sub
        for p in sub:
            ph[p] *= factor
    # exact-sum discipline: drive sum(ph.values()) to wall_s via the
    # residual, compensating float rounding until equality holds
    ph["other"] = 0.0
    for _ in range(8):
        diff = wall_s - sum(ph.values())
        if diff == 0.0:
            break
        ph["other"] += diff
    if ph["other"] < 0.0:
        # residual can only go negative by float dust after scaling;
        # fold it into the largest sub-phase so no phase is negative
        big = max(sub, key=lambda p: ph[p])
        ph[big] += ph["other"]
        ph["other"] = 0.0
        for _ in range(8):
            diff = wall_s - sum(ph.values())
            if diff == 0.0:
                break
            ph[big] += diff
    return ph


def dominant_phase(phases: Dict[str, float]) -> str:
    """The phase holding the most wall — `other` only wins when nothing
    attributable beats it (ties break toward the attributed phase)."""
    if not phases:
        return ""
    best = max((p for p in phases if p != "other"),
               key=lambda p: phases.get(p, 0.0), default="other")
    if phases.get("other", 0.0) > phases.get(best, 0.0):
        return "other"
    return best


def breakdown_line(phases: Dict[str, float], wall_s: float) -> str:
    """The EXPLAIN ANALYZE surface: `critical path: queued Q + ... = W`.
    Zero phases are elided (other always prints so the sum is visible)."""
    parts = [f"{p} {phases.get(p, 0.0) * 1000:.1f}ms"
             for p in PHASES if phases.get(p, 0.0) > 0.0 or p == "other"]
    return ("critical path: " + " + ".join(parts) +
            f" = {wall_s * 1000:.1f}ms")


def build_timeline(tq) -> dict:
    """Full timeline for a TrackedQuery: phase attribution (sums exactly
    to elapsed wall), the dominant phase, and the blocking critical path
    over coordinator stage spans. Works untraced (state-machine stamps +
    TaskStats rollup); spans only enrich it."""
    sm = tq.state_machine
    created = sm.created_at
    ended = sm.ended_at if sm.ended_at is not None else time.time()
    wall = max(0.0, ended - created)
    state_times = getattr(sm, "state_times", {}) or {}
    if "PLANNING" in state_times:
        queued = max(0.0, state_times["PLANNING"] - created)
    elif sm.is_done():
        # the query died while QUEUED (queued-time deadline, queue-full
        # rejection, cancel-before-dispatch): every second of its wall
        # was queue wait — charging zero here would silently launder
        # admission holds into `other`
        queued = wall
    else:
        queued = 0.0
    spans = tq.trace
    if spans is None and getattr(tq, "tracer", None) is not None:
        spans = tq.tracer.export()
    lq = getattr(tq, "stage_stats", None) or {}
    phases = attribute_phases(wall, queued, spans, lq, lq.get("write"))
    cp_total, cp = critical_path(stage_intervals(spans or []))
    return {"queryId": tq.query_id,
            "state": sm.state,
            "wall_s": wall,
            "phases": phases,
            "dominant": dominant_phase(phases),
            "criticalPath": cp,
            "criticalPathSeconds": round(cp_total, 6),
            "breakdown": breakdown_line(phases, wall)}
