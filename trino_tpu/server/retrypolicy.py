"""Shared retry policy: exponential backoff with decorrelated jitter.

Reference: the reference engine spreads its retry ceremony across
HttpRemoteTask's error trackers, the FTE scheduler's task-retry delays
(EventDrivenFaultTolerantQueryScheduler's retry backoff) and the client's
advance() loop. This runtime previously retried immediately at every one
of those sites, which under a flapping coordinator or a saturated worker
turns recovery into a synchronized retry storm. One policy object now
serves all of them: client nextUri polling, worker announce, the
scheduler's task-retry rounds, and the dispatcher's QUERY-retry loop.

The jitter is the decorrelated variant: each delay is drawn uniformly
from [base, prev * 3] and capped at max_delay, so expected growth stays
exponential while concurrent retriers decorrelate instead of herding.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Iterator, Optional, Tuple


@dataclass(frozen=True)
class RetryPolicy:
    """Backoff schedule + attempt/deadline budget.

    `max_attempts` counts total tries (first try included); `deadline_s`
    bounds the cumulative time `call()` may spend including the sleep it
    is about to take — whichever budget exhausts first stops retrying.
    A `seed` makes the jitter deterministic (chaos soak reproducibility).
    """

    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    max_attempts: int = 5
    deadline_s: float = float("inf")
    seed: Optional[int] = None
    # metrics label: which control-plane loop is retrying (announce,
    # dispatch, drain, ...) — rendered on /v1/metrics as
    # trino_tpu_retry_attempts_total{component=...}
    name: str = "retry"

    def delays(self) -> Iterator[float]:
        """Sleep durations between attempts (max_attempts - 1 entries)."""
        rng = random.Random(self.seed)
        prev = self.base_delay_s
        for _ in range(max(0, self.max_attempts - 1)):
            prev = min(self.max_delay_s,
                       rng.uniform(self.base_delay_s, max(self.base_delay_s,
                                                          prev * 3)))
            yield prev

    def call(self, fn: Callable, retry_on: Tuple = (OSError,),
             sleep: Callable[[float], None] = time.sleep,
             on_retry: Optional[Callable] = None):
        """Run `fn`, retrying on `retry_on` per the schedule.

        The final attempt's exception propagates unchanged so callers
        keep their existing error handling; `on_retry(attempt, delay, e)`
        is an observability hook (never raises into the retry loop).
        """
        t0 = time.monotonic()
        schedule = list(self.delays())
        for attempt in range(self.max_attempts):
            try:
                return fn()
            except retry_on as e:
                last_try = attempt >= self.max_attempts - 1
                delay = schedule[attempt] if not last_try else 0.0
                if last_try or \
                        time.monotonic() - t0 + delay > self.deadline_s:
                    raise
                from ..metrics import RETRY_ATTEMPTS
                RETRY_ATTEMPTS.inc(component=self.name)
                if on_retry is not None:
                    try:
                        on_retry(attempt, delay, e)
                    except Exception:   # noqa: BLE001 — hook must not mask
                        pass
                sleep(delay)
        raise AssertionError("unreachable")   # pragma: no cover
