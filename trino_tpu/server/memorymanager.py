"""Cluster memory arbitration: pooled accounting + the low-memory killer.

Reference: memory/ClusterMemoryManager.java:96 — the coordinator sums
every node's reported pool reservations, triggers memory revocation
(spill) when the cluster crosses its limit, and as the last resort runs a
LowMemoryKiller. The policy here is TotalReservationLowMemoryKiller.java's
total-reservation-dominant choice: kill the single query holding the most
reserved bytes, never a worker process.

TPU shape: every worker's /v1/status heartbeat carries its executor
pool's snapshot (reserved/revocable/limit/peak); the failure detector
records it on the node inventory as it pings. The manager's tick then:

1. sums cluster reserved + revocable bytes (workers + the coordinator's
   own session executor) and publishes them to the resource-group tree
   (memory-aware admission: groups above their soft_memory_limit_bytes
   keep their queued queries queued);
2. above the cluster limit, requests REVOCATION first — spillable
   holders (build caches, partial-aggregation state) move bytes to host;
3. if pressure persists for `kill_after_ticks` consecutive ticks, kills
   the dominant query: a MemoryKilledError is injected at the executor's
   next plan-node boundary and the state machine records a dedicated
   user-facing QUERY_EXCEEDED_MEMORY error — the query dies, the worker
   never does.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

log = logging.getLogger("trino_tpu.memory")


class ClusterMemoryManager:
    def __init__(self, state, cluster_limit_bytes: Optional[int] = None,
                 interval_s: float = 0.5, kill_after_ticks: int = 2):
        self.state = state                    # CoordinatorState
        self.cluster_limit_bytes = cluster_limit_bytes
        self.interval_s = interval_s
        self.kill_after_ticks = kill_after_ticks
        self.queries_killed = 0
        self.revocations = 0
        # elastic membership: announce() calls on_membership_change()
        # whenever a node joins/drains/leaves so arbitration re-runs
        # against the new node set immediately instead of waiting out
        # the polling interval
        self.membership_rearbitrations = 0
        self._membership_sig: tuple = ()
        self._pressure_ticks = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_snapshot: Dict[str, dict] = {}
        state.memory_manager = self

    # -- accounting --------------------------------------------------------

    def _local_pool(self):
        ex = getattr(self.state.session, "executor", None)
        return getattr(ex, "pool", None)

    def snapshot(self) -> dict:
        """Cluster memory view: the coordinator's own pool plus every
        worker's last heartbeat-reported pool."""
        nodes = {}
        pool = self._local_pool()
        if pool is not None:
            nodes["coordinator"] = pool.snapshot()
        with self.state.nodes_lock:
            for n in self.state.nodes.values():
                mem = getattr(n, "memory", None)
                if mem:
                    nodes[n.node_id] = mem
        total_reserved = sum(m.get("reserved", 0) for m in nodes.values())
        total_revocable = sum(m.get("revocable", 0)
                              for m in nodes.values())
        self.last_snapshot = nodes
        return {"nodes": nodes, "reserved": total_reserved,
                "revocable": total_revocable,
                "limit": self.cluster_limit_bytes}

    def _dominant_query(self):
        """The running query holding the most reserved bytes (the
        total-reservation-dominant policy). Attribution comes from the
        pool's per-holder ledger, tagged with query ids by the
        dispatcher; ties (or an empty ledger) fall back to the
        longest-running query, which holds the lock — and therefore the
        bytes — in this serialized-execution runtime."""
        running = [tq for tq in self.state.tracker.all()
                   if not tq.state_machine.is_done()
                   and tq.state == "RUNNING"]
        if not running:
            return None
        pool = self._local_pool()
        held = {tq.query_id: (pool.query_bytes(tq.query_id)
                              if pool is not None else 0)
                for tq in running}
        running.sort(key=lambda tq: (held[tq.query_id],
                                     -tq.state_machine.created_at),
                     reverse=True)
        return running[0]

    # -- arbitration -------------------------------------------------------

    def on_membership_change(self) -> None:
        """Immediate re-arbitration on a membership/lifecycle change
        (worker joined, started draining, or left): the cluster's
        capacity just moved, so the resource-group tree and the
        over-limit check must see the new node set now — a query
        admitted against capacity that left with a drained worker
        would otherwise run straight into the killer."""
        try:
            self.tick()
        except Exception:    # noqa: BLE001 — arbitration must not fail
            pass             # the announce that triggered it

    def on_promotion(self) -> None:
        """Failover re-arbitration: a promoted coordinator inherits no
        heartbeat-reported pool snapshots — every worker's `memory`
        view is stale-from-birth until its first announce lands here.
        Drop inherited per-node reports and re-arbitrate against
        whatever the re-announce wave has delivered so far, so the
        first post-failover admission decision never trusts numbers
        recorded by the dead primary."""
        with self.state.nodes_lock:
            for n in self.state.nodes.values():
                n.memory = None
        self.on_membership_change()

    def _note_membership(self) -> None:
        with self.state.nodes_lock:
            sig = tuple(sorted((n.node_id, n.state)
                               for n in self.state.nodes.values()))
        if sig != self._membership_sig:
            self._membership_sig = sig
            self.membership_rearbitrations += 1

    def tick(self) -> dict:
        self._note_membership()
        snap = self.snapshot()
        total = snap["reserved"] + snap["revocable"]
        # memory-aware admission: the resource-group tree sees the
        # cluster's usage; groups above soft_memory_limit_bytes keep
        # queued queries queued until it drops
        rgm = getattr(self.state.dispatcher, "resource_groups", None)
        if rgm is not None:
            runnable = rgm.set_cluster_memory(total)
            for run in runnable:
                run()
        limit = self.cluster_limit_bytes
        if limit is None or total <= limit:
            self._pressure_ticks = 0
            return snap
        # over the limit: revoke (spill) before killing
        deficit = total - limit
        pool = self._local_pool()
        if pool is not None and snap["revocable"] > 0:
            self.revocations += 1
            pool.request_revocation(deficit)
            snap = self.snapshot()
            if snap["reserved"] + snap["revocable"] <= limit:
                self._pressure_ticks = 0
                return snap
        self._pressure_ticks += 1
        if self._pressure_ticks >= self.kill_after_ticks:
            self._pressure_ticks = 0
            self.kill_dominant(
                f"cluster memory {total} bytes over limit {limit}")
        return snap

    def kill_dominant(self, why: str) -> Optional[str]:
        """Kill the dominant query with a user-facing
        QUERY_EXCEEDED_MEMORY — the Trino guarantee: under pressure a
        QUERY dies, never a worker."""
        tq = self._dominant_query()
        if tq is None:
            return None
        from ..exec.memory import ExceededMemoryLimitError
        msg = (f"Query killed by the cluster low-memory killer: {why} "
               f"(dominant reservation {tq.query_id})")
        # post-mortem context BEFORE the kill lands: snapshot the live
        # progress ratio and dominant stage (server/livestats.py) onto
        # the tracked query so history + QueryCompletedEvent record how
        # far the victim got and where it was when it died
        ls = getattr(self.state, "livestats", None)
        if ls is not None:
            progress = ls.progress(tq.query_id)
            if progress is not None and progress > tq.progress_ratio:
                tq.progress_ratio = progress
            stage = ls.dominant_stage(tq.query_id)
            if stage:
                tq.dominant_stage = stage
        ex = getattr(self.state.session, "executor", None)
        if ex is not None and hasattr(ex, "request_kill"):
            ex.request_kill(msg)      # stops the running plan promptly
        # the dispatcher's single termination path: taxonomy on the
        # state machine, worker task fan-out, cancel-propagation
        # accounting — an OOM kill of a distributed query must free its
        # remote buffers, not just the local plan
        term = getattr(self.state.dispatcher, "terminate", None)
        if term is not None:
            term(tq.query_id, reason="oom", message=msg)
        else:
            tq.state_machine.fail(
                msg, error_name=ExceededMemoryLimitError.error_name,
                error_code=ExceededMemoryLimitError.error_code)
        self.queries_killed += 1
        from ..metrics import QUERIES_KILLED_OOM
        QUERIES_KILLED_OOM.inc()
        from ..utils.log import tq_context
        log.warning("%skilled by the cluster low-memory killer: %s "
                    "(progress %.1f%%, dominant stage %s)",
                    tq_context(tq), why, 100 * tq.progress_ratio,
                    tq.dominant_stage or "?")
        return tq.query_id

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ClusterMemoryManager":
        self._thread = threading.Thread(target=self._loop,
                                        name="memory-manager", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:    # noqa: BLE001 — arbitration must not die
                pass
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
