"""Heartbeat failure detector.

Reference: failuredetector/HeartbeatFailureDetector.java:76 — the
coordinator pings every worker's /v1/status (ping:344) and keeps an
exponentially-decayed failure ratio per node; nodes above the threshold are
excluded from scheduling until they recover (:91, :377).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict
from urllib.request import urlopen

from .coordinator import CoordinatorState


class NodeStats:
    """Exponentially-decayed success/failure ratio for one node."""

    def __init__(self, decay: float = 0.8):
        self.decay = decay
        self.failure_ratio = 0.0
        self.last_seen = time.time()

    def record(self, success: bool) -> None:
        sample = 0.0 if success else 1.0
        self.failure_ratio = (self.decay * self.failure_ratio +
                              (1 - self.decay) * sample)
        if success:
            self.last_seen = time.time()


class HeartbeatFailureDetector:
    """Pings announced workers; marks nodes FAILED past the threshold and
    ACTIVE again when the decayed ratio drops back (same hysteresis as the
    reference's failure-detector.threshold, default 0.1)."""

    def __init__(self, state: CoordinatorState,
                 interval_s: float = 0.5, threshold: float = 0.1,
                 timeout_s: float = 2.0):
        self.state = state
        self.interval_s = interval_s
        self.threshold = threshold
        self.timeout_s = timeout_s
        self.stats: Dict[str, NodeStats] = {}
        self.injector = None          # chaos hook (HEARTBEAT_PING)
        self._stop = threading.Event()
        self._thread = None
        # registered on the coordinator state so the scheduler's
        # task-path failures feed the same decayed stats (a node whose
        # executor is wedged but whose /v1/status answers must not flip
        # straight back to ACTIVE) and announce() can consult the
        # hysteresis before resurrecting a FAILED node
        state.failure_detector = self

    def record_failure(self, node_id: str) -> None:
        """Fold a non-heartbeat failure observation (task create/drain
        error seen by the scheduler) into the node's decayed ratio. One
        sample pushes a healthy node past the default threshold, so it
        must then sustain several clean pings before rejoining."""
        self.stats.setdefault(node_id, NodeStats()).record(False)

    def start(self) -> "HeartbeatFailureDetector":
        self._thread = threading.Thread(target=self._loop,
                                        name="failure-detector", daemon=True)
        self._thread.start()
        return self

    def ping_all(self) -> None:
        with self.state.nodes_lock:
            nodes = list(self.state.nodes.values())
        for node in nodes:
            st = self.stats.setdefault(node.node_id, NodeStats())
            ok = False
            memory = None
            device = None
            reported = None
            try:
                if self.injector is not None:
                    # chaos: RAISE/DROP -> failed probe sample; DELAY ->
                    # slow status endpoint (sleeps, then pings normally)
                    self.injector.maybe_fail("HEARTBEAT_PING",
                                             node.node_id)
                with urlopen(f"{node.uri}/v1/status",
                             timeout=self.timeout_s) as resp:
                    ok = resp.status == 200
                    try:
                        # heartbeat payload carries the worker's memory
                        # pool snapshot for cluster arbitration plus its
                        # live device/HBM allocator stats
                        payload = json.loads(resp.read().decode())
                        memory = payload.get("memory")
                        device = payload.get("device")
                        reported = payload.get("state")
                    except Exception:    # noqa: BLE001 — old workers
                        memory = None
            except Exception:
                ok = False
            st.record(ok)
            with self.state.nodes_lock:
                live = self.state.nodes.get(node.node_id)
                if live is None:
                    continue
                if ok and memory is not None:
                    live.memory = memory
                if ok and device is not None:
                    live.device = device
                if st.failure_ratio > self.threshold:
                    # an unreachable node is FAILED even mid-drain: the
                    # crash path (retry machinery) takes over from the
                    # graceful one
                    live.state = "FAILED"
                elif ok and reported in ("DRAINING", "DRAINED"):
                    # lifecycle propagation: a healthy draining worker
                    # leaves placement/hedging without a detector penalty
                    live.state = reported
                elif live.state == "FAILED":
                    live.state = "ACTIVE"
                elif ok and reported == "ACTIVE" and \
                        live.state in ("DRAINING", "DRAINED"):
                    live.state = "ACTIVE"    # drain canceled

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.ping_all()
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
