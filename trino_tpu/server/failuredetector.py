"""Heartbeat failure detector.

Reference: failuredetector/HeartbeatFailureDetector.java:76 — the
coordinator pings every worker's /v1/status (ping:344) and keeps an
exponentially-decayed failure ratio per node; nodes above the threshold are
excluded from scheduling until they recover (:91, :377).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict
from urllib.request import urlopen

from .coordinator import CoordinatorState


class NodeStats:
    """Exponentially-decayed success/failure ratio for one node."""

    def __init__(self, decay: float = 0.8):
        self.decay = decay
        self.failure_ratio = 0.0
        self.last_seen = time.time()

    def record(self, success: bool) -> None:
        sample = 0.0 if success else 1.0
        self.failure_ratio = (self.decay * self.failure_ratio +
                              (1 - self.decay) * sample)
        if success:
            self.last_seen = time.time()


class HeartbeatFailureDetector:
    """Pings announced workers; marks nodes FAILED past the threshold and
    ACTIVE again when the decayed ratio drops back (same hysteresis as the
    reference's failure-detector.threshold, default 0.1)."""

    def __init__(self, state: CoordinatorState,
                 interval_s: float = 0.5, threshold: float = 0.1,
                 timeout_s: float = 2.0):
        self.state = state
        self.interval_s = interval_s
        self.threshold = threshold
        self.timeout_s = timeout_s
        self.stats: Dict[str, NodeStats] = {}
        self.injector = None          # chaos hook (HEARTBEAT_PING)
        self._stop = threading.Event()
        self._thread = None
        # registered on the coordinator state so the scheduler's
        # task-path failures feed the same decayed stats (a node whose
        # executor is wedged but whose /v1/status answers must not flip
        # straight back to ACTIVE) and announce() can consult the
        # hysteresis before resurrecting a FAILED node
        state.failure_detector = self

    def record_failure(self, node_id: str) -> None:
        """Fold a non-heartbeat failure observation (task create/drain
        error seen by the scheduler) into the node's decayed ratio. One
        sample pushes a healthy node past the default threshold, so it
        must then sustain several clean pings before rejoining."""
        self.stats.setdefault(node_id, NodeStats()).record(False)

    def start(self) -> "HeartbeatFailureDetector":
        self._thread = threading.Thread(target=self._loop,
                                        name="failure-detector", daemon=True)
        self._thread.start()
        return self

    def ping_all(self) -> None:
        with self.state.nodes_lock:
            nodes = list(self.state.nodes.values())
        for node in nodes:
            st = self.stats.setdefault(node.node_id, NodeStats())
            ok = False
            memory = None
            device = None
            reported = None
            try:
                if self.injector is not None:
                    # chaos: RAISE/DROP -> failed probe sample; DELAY ->
                    # slow status endpoint (sleeps, then pings normally)
                    self.injector.maybe_fail("HEARTBEAT_PING",
                                             node.node_id)
                with urlopen(f"{node.uri}/v1/status",
                             timeout=self.timeout_s) as resp:
                    ok = resp.status == 200
                    try:
                        # heartbeat payload carries the worker's memory
                        # pool snapshot for cluster arbitration plus its
                        # live device/HBM allocator stats
                        payload = json.loads(resp.read().decode())
                        memory = payload.get("memory")
                        device = payload.get("device")
                        reported = payload.get("state")
                    except Exception:    # noqa: BLE001 — old workers
                        memory = None
            except Exception:
                ok = False
            st.record(ok)
            with self.state.nodes_lock:
                live = self.state.nodes.get(node.node_id)
                if live is None:
                    continue
                if ok and memory is not None:
                    live.memory = memory
                if ok and device is not None:
                    live.device = device
                if st.failure_ratio > self.threshold:
                    # an unreachable node is FAILED even mid-drain: the
                    # crash path (retry machinery) takes over from the
                    # graceful one
                    live.state = "FAILED"
                elif ok and reported in ("DRAINING", "DRAINED"):
                    # lifecycle propagation: a healthy draining worker
                    # leaves placement/hedging without a detector penalty
                    live.state = reported
                elif live.state == "FAILED":
                    live.state = "ACTIVE"
                elif ok and reported == "ACTIVE" and \
                        live.state in ("DRAINING", "DRAINED"):
                    live.state = "ACTIVE"    # drain canceled

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.ping_all()
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)


class StandbyWatcher:
    """Standby-coordinator side of failover: the warm loop that (a)
    announces the standby to the primary with state=STANDBY so every
    announce response carries the failover address list, (b) tails the
    ledger to keep a warm replay view, and (c) counts consecutive
    probe failures against the primary — `fail_after` misses in a row
    is the detector-driven promotion trigger (`promote(reason=
    "detector")`). Admin promotion via PUT /v1/info/state works whether
    or not this watcher is running."""

    def __init__(self, state: CoordinatorState, own_uri: str,
                 primary_uri: str, interval_s: float = 0.25,
                 fail_after: int = 3, auto_promote: bool = True):
        self.state = state
        self.own_uri = own_uri
        self.primary_uri = primary_uri
        self.interval_s = interval_s
        self.fail_after = fail_after
        self.auto_promote = auto_promote
        self.failures = 0
        self.records_seen = 0
        self._tail_off = 0
        self._stop = threading.Event()
        self._thread = None

    def start(self) -> "StandbyWatcher":
        self._thread = threading.Thread(target=self._loop,
                                        name="standby-watcher",
                                        daemon=True)
        self._thread.start()
        return self

    def probe_once(self) -> bool:
        """One announce-as-probe round trip to the primary."""
        from urllib.request import Request
        from .security import internal_headers
        body = json.dumps({"nodeId": self.state.node_id,
                           "uri": self.own_uri, "state": "STANDBY",
                           "now": time.time()}).encode()
        req = Request(f"{self.primary_uri}/v1/announce", data=body,
                      headers={"Content-Type": "application/json",
                               **internal_headers()}, method="POST")
        try:
            with urlopen(req, timeout=2.0):
                pass
            return True
        except Exception:  # noqa: BLE001 — any probe error is a miss
            return False

    def tail_ledger(self) -> None:
        """Consume newly-durable ledger records so promotion starts
        from a warm view (the full replay at promote() is idempotent
        on top of this — the tail is a latency optimization and a
        liveness signal, never a correctness dependency)."""
        led = self.state.ledger
        if led is None:
            return
        recs, self._tail_off = led.tail_records(self._tail_off)
        self.records_seen += len(recs)

    def _loop(self) -> None:
        while not self._stop.is_set():
            if self.state.role == "PRIMARY":
                return                      # promoted out from under us
            if self.probe_once():
                self.failures = 0
            else:
                self.failures += 1
            self.tail_ledger()
            if self.auto_promote and self.failures >= self.fail_after:
                self.state.promote(reason="detector")
                return
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
