"""Query state machine and query tracking.

Reference: execution/QueryState.java:21 (QUEUED -> WAITING_FOR_RESOURCES ->
DISPATCHING -> PLANNING -> STARTING -> RUNNING -> FINISHING -> FINISHED /
FAILED), the generic CAS StateMachine (execution/StateMachine.java:43) and
QueryTracker (execution/QueryTracker.java:51). Python edition: a lock-guarded
state holder with listeners, plus a registry with expiry.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

TERMINAL = ("FINISHED", "FAILED", "CANCELED")
ORDER = ("QUEUED", "PLANNING", "STARTING", "RUNNING", "FINISHING",
         "FINISHED", "FAILED", "CANCELED")


class QueryStateMachine:
    """CAS-style state transitions; listeners fire outside the lock."""

    def __init__(self, query_id: str):
        self.query_id = query_id
        self._state = "QUEUED"
        self._lock = threading.Lock()
        self._listeners: List[Callable[[str], None]] = []
        # set once the terminal transition's listeners have all run:
        # the protocol layer holds a terminal page until then, so a
        # fast-polling client can never observe FINISHED before the
        # completion pipeline (events, ledger record, metrics) fires
        self.settled = threading.Event()
        self.error: Optional[str] = None
        # error taxonomy (the reference's ErrorCode): user errors like
        # QUERY_EXCEEDED_MEMORY carry their own name/code so clients can
        # distinguish them from GENERIC_INTERNAL_ERROR
        self.error_name: str = "GENERIC_INTERNAL_ERROR"
        self.error_code: int = 1
        self.created_at = time.time()
        self.ended_at: Optional[float] = None
        # entry timestamp per state reached — the timeline analyzer's
        # queued/plan attribution input (server/timeline.py); QUEUED is
        # stamped at creation so queued time exists even for queries
        # failed before their first transition
        self.state_times: Dict[str, float] = {"QUEUED": self.created_at}

    @property
    def state(self) -> str:
        return self._state

    def is_done(self) -> bool:
        return self._state in TERMINAL

    def transition(self, new_state: str) -> bool:
        """Advance to new_state; never moves backward or out of terminal."""
        to_fire = []
        with self._lock:
            if self._state in TERMINAL:
                return False
            if ORDER.index(new_state) <= ORDER.index(self._state):
                return False
            self._state = new_state
            self.state_times.setdefault(new_state, time.time())
            if new_state in TERMINAL:
                self.ended_at = time.time()
            to_fire = list(self._listeners)
        try:
            for fn in to_fire:
                fn(new_state)
        finally:
            if new_state in TERMINAL:
                self.settled.set()
        return True

    def fail(self, message: str,
             error_name: str = "GENERIC_INTERNAL_ERROR",
             error_code: int = 1) -> bool:
        with self._lock:
            if self._state in TERMINAL:
                return False
            self.error = message
            self.error_name = error_name
            self.error_code = error_code
            self._state = "FAILED"
            self.state_times.setdefault("FAILED", time.time())
            self.ended_at = time.time()
            to_fire = list(self._listeners)
        try:
            for fn in to_fire:
                fn("FAILED")
        finally:
            self.settled.set()
        return True

    def cancel(self) -> bool:
        with self._lock:
            if self._state in TERMINAL:
                return False
            self._state = "CANCELED"
            # stamped exactly like FAILED above, and carrying the same
            # error taxonomy the payload serves — so timeline
            # attribution and ledger replay treat canceled and failed
            # queries identically
            self.state_times.setdefault("CANCELED", time.time())
            self.error = "Query was canceled"
            self.error_name = "USER_CANCELED"
            self.error_code = 2
            self.ended_at = time.time()
            to_fire = list(self._listeners)
        try:
            for fn in to_fire:
                fn("CANCELED")
        finally:
            self.settled.set()
        return True

    def add_listener(self, fn: Callable[[str], None]) -> None:
        with self._lock:
            self._listeners.append(fn)

    def adopt_times(self, times: Dict[str, float]) -> None:
        """Merge recorded state-entry stamps (ledger replay): earliest
        wins per state, so a resumed query's queued/plan attribution
        spans from its ORIGINAL admission, not from the resume."""
        with self._lock:
            for st, ts in (times or {}).items():
                if st not in ORDER:
                    continue
                cur = self.state_times.get(st)
                if cur is None or ts < cur:
                    self.state_times[st] = ts
            q0 = self.state_times.get("QUEUED")
            if q0 is not None and q0 < self.created_at:
                self.created_at = q0

    @classmethod
    def restored(cls, query_id: str, state: str,
                 state_times: Optional[Dict[str, float]] = None,
                 error: Optional[str] = None,
                 error_name: str = "GENERIC_INTERNAL_ERROR",
                 error_code: int = 1) -> "QueryStateMachine":
        """Rebuild a state machine from ledger records. Recorded stamps
        land in state_times byte-for-byte as the live transitions set
        them — FAILED and CANCELED included — so post-replay timeline
        attribution sums exactly as it did before the crash."""
        sm = cls(query_id)
        sm.adopt_times(state_times or {})
        sm._state = state if state in ORDER else "FAILED"
        if sm._state in TERMINAL:
            sm.ended_at = sm.state_times.get(sm._state) or time.time()
            sm.state_times.setdefault(sm._state, sm.ended_at)
            if sm._state == "FAILED":
                sm.error = error or "Query failed before coordinator " \
                                    "restart"
                sm.error_name = error_name
                sm.error_code = error_code
            elif sm._state == "CANCELED":
                sm.error = error or "Query was canceled"
                sm.error_name = "USER_CANCELED"
                sm.error_code = 2
            # terminal from birth: there is no completion pipeline to
            # wait for, so the protocol layer must not block on it
            sm.settled.set()
        return sm


@dataclass
class TrackedQuery:
    """One query's full lifecycle record (QueryInfo essentials)."""
    query_id: str
    sql: str
    session_user: str
    state_machine: QueryStateMachine
    result: Optional[object] = None       # exec.session.QueryResult
    plan_text: Optional[str] = None
    rows_returned: int = 0
    cpu_time_s: float = 0.0
    elapsed_s: float = 0.0
    retries: int = 0
    distributed: bool = False             # ran via the stage scheduler
    # why the stage scheduler declined (None when distributed/local-only
    # coordinator): surfaced in /v1/query info so `SET SESSION
    # distributed = true` degrading to local is never silent
    fallback_reason: Optional[str] = None
    # observability: W3C trace context from the client's POST, the
    # per-query tracer (live while executing), the stitched trace
    # exported at completion (GET /v1/query/{id}/trace), and the
    # scheduler's per-query stage/task rollup (events + system tables)
    traceparent: Optional[str] = None
    tracer: Optional[object] = None       # utils.tracing.Tracer
    trace: Optional[list] = None          # exported span dicts
    stage_stats: Optional[dict] = None
    # spill-tier activations during this query (executor stats delta) —
    # one of the regression detector's inputs (server/history.py)
    spills: int = 0
    # serving-layer verdicts (server/serving.py): where the query ran
    # ('host' | 'device' | 'cache' | 'microbatch') and the router's
    # reasoning — surfaced in /v1/query info
    route: Optional[str] = None
    route_reason: Optional[str] = None
    # resource-group tenant (the principal's selected leaf group):
    # labels metrics, history records, and audit events so per-tenant
    # isolation is observable, not just enforced
    tenant: str = "default"
    # critical-path timeline (server/timeline.py): phase attribution
    # summing exactly to elapsed wall, built at terminal transition and
    # served at GET /v1/query/{id}/timeline + system.runtime.query_timeline
    timeline: Optional[dict] = None
    # live observability (server/livestats.py): the last computed
    # split-weighted progress (monotonic; survives into OOM-kill
    # post-mortems via history + QueryCompletedEvent), the dominant
    # in-flight stage behind it, and the stuck-query diagnosis the
    # live-stats fold attached (None when the query never stalled)
    progress_ratio: float = 0.0
    dominant_stage: str = ""
    live_diagnosis: Optional[dict] = None
    # query-lifetime enforcement: absolute wall-clock deadlines stamped
    # at admission (coordinator time.time()). `deadline` bounds total
    # run time (query_max_run_time_s), `queued_deadline` bounds how long
    # the query may sit QUEUED (query_max_queued_time_s); None = no cap.
    # The remaining budget rides every task dispatch, clock-skew
    # normalized per node, and the deadline enforcer terminates the
    # query cluster-wide once either expires.
    deadline: Optional[float] = None
    queued_deadline: Optional[float] = None
    # why terminate() fired ("user" | "deadline" | "queued_deadline" |
    # "oom" | "stuck"); None when the query ended on its own
    terminate_reason: Optional[str] = None

    @property
    def state(self) -> str:
        return self.state_machine.state


class QueryTracker:
    """Registry of live + recently finished queries (QueryTracker.java:51;
    expiry mirrors query.min-expire-age). The cap is configurable via
    TRINO_TPU_QUERY_HISTORY, and evicted queries flush through the
    `on_evict` hook (the coordinator wires it to the persistent history
    store) so completed-query stats outlive the in-memory ring."""

    def __init__(self, max_history: Optional[int] = None):
        self._queries: Dict[str, TrackedQuery] = {}
        self._lock = threading.Lock()
        self._seq = 0
        if max_history is None:
            try:
                max_history = int(
                    os.environ.get("TRINO_TPU_QUERY_HISTORY", 100))
            except ValueError:
                max_history = 100
        self.max_history = max_history
        self.on_evict: Optional[Callable[[TrackedQuery], None]] = None

    def next_query_id(self) -> str:
        with self._lock:
            self._seq += 1
            # Trino ids look like 20240101_000000_00000_abcde
            return time.strftime("%Y%m%d_%H%M%S") + f"_{self._seq:05d}_tpu"

    def reserve_seq(self, seq: int) -> None:
        """Advance the id sequence past `seq`. A promoted coordinator
        calls this with the highest sequence found in the replayed
        ledger: its ids are minted by a FRESH counter in the same
        wall-second format, so without the bump a sub-second failover
        could re-issue an id the dead primary already handed out."""
        with self._lock:
            self._seq = max(self._seq, seq)

    def register(self, q: TrackedQuery) -> None:
        with self._lock:
            self._queries[q.query_id] = q
            evicted = self._expire_locked()
        # the flush runs OUTSIDE the lock: the history store may hit disk,
        # and a listener calling back into the tracker must not deadlock
        if self.on_evict is not None:
            for old in evicted:
                try:
                    self.on_evict(old)
                except Exception:  # noqa: BLE001 — eviction never fails
                    pass

    def get(self, query_id: str) -> Optional[TrackedQuery]:
        with self._lock:
            return self._queries.get(query_id)

    def all(self) -> List[TrackedQuery]:
        with self._lock:
            return list(self._queries.values())

    def _expire_locked(self) -> List[TrackedQuery]:
        done = [q for q in self._queries.values()
                if q.state_machine.is_done()]
        excess = len(done) - self.max_history
        evicted: List[TrackedQuery] = []
        if excess > 0:
            done.sort(key=lambda q: q.state_machine.ended_at or 0)
            for q in done[:excess]:
                del self._queries[q.query_id]
                evicted.append(q)
        return evicted
