"""Hierarchical resource groups — admission control.

Reference: execution/resourcegroups/InternalResourceGroup.java:76 —
groups form a tree; each group has a hard concurrency limit and a queue
bound; selectors route queries to groups by user; FIFO within a group.
Config is pluggable in the reference (file/DB managers,
plugin/trino-resource-group-managers) — here a plain dataclass tree.
"""

from __future__ import annotations

import re
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


class QueryQueueFullError(RuntimeError):
    pass


@dataclass
class ResourceGroupConfig:
    name: str
    hard_concurrency_limit: int = 4
    max_queued: int = 100
    sub_groups: tuple = ()


@dataclass
class Selector:
    user_pattern: str             # regex over the session user
    group: str                    # dot path, e.g. "root.adhoc"


class ResourceGroup:
    def __init__(self, config: ResourceGroupConfig,
                 parent: Optional["ResourceGroup"] = None):
        self.config = config
        self.parent = parent
        self.running = 0
        self.queue: deque = deque()
        self.sub_groups: Dict[str, ResourceGroup] = {
            sub.name: ResourceGroup(sub, self)
            for sub in config.sub_groups}
        self.stats_total_admitted = 0
        self.stats_peak_queued = 0

    @property
    def path(self) -> str:
        return self.config.name if self.parent is None else \
            f"{self.parent.path}.{self.config.name}"

    def can_run(self) -> bool:
        """A query may start when every group up the chain has headroom
        (the reference's canRunMore walk)."""
        g: Optional[ResourceGroup] = self
        while g is not None:
            if g.running >= g.config.hard_concurrency_limit:
                return False
            g = g.parent
        return True

    def acquire(self) -> None:
        g: Optional[ResourceGroup] = self
        while g is not None:
            g.running += 1
            g = g.parent
        self.stats_total_admitted += 1

    def release(self) -> None:
        g: Optional[ResourceGroup] = self
        while g is not None:
            g.running = max(0, g.running - 1)
            g = g.parent


class ResourceGroupManager:
    """Routes queries to leaf groups and gates execution: run now, queue,
    or reject (Too many queued queries)."""

    def __init__(self, root: ResourceGroupConfig,
                 selectors: Optional[List[Selector]] = None):
        self.root = ResourceGroup(root)
        self.selectors = selectors or []
        self._lock = threading.Lock()

    def _find(self, path: str) -> ResourceGroup:
        parts = path.split(".")
        g = self.root
        assert parts[0] == self.root.config.name, path
        for p in parts[1:]:
            g = g.sub_groups[p]
        return g

    def select(self, user: str) -> ResourceGroup:
        for sel in self.selectors:
            if re.fullmatch(sel.user_pattern, user):
                return self._find(sel.group)
        return self.root

    def submit(self, user: str, run: Callable[[], None]) -> str:
        """Admit or queue `run`; returns the chosen group path. Raises
        QueryQueueFullError past the queue bound."""
        with self._lock:
            group = self.select(user)
            if group.can_run():
                group.acquire()
                to_run = run
            elif len(group.queue) < group.config.max_queued:
                group.queue.append(run)
                group.stats_peak_queued = max(group.stats_peak_queued,
                                              len(group.queue))
                return group.path
            else:
                raise QueryQueueFullError(
                    f"Too many queued queries for {group.path!r}")
        to_run()
        return group.path

    def finished(self, group_path: str) -> Optional[Callable[[], None]]:
        """Release a slot; returns the next queued query to start (the
        caller runs it outside the lock), if any."""
        with self._lock:
            group = self._find(group_path)
            group.release()
            if group.queue and group.can_run():
                group.acquire()
                return group.queue.popleft()
        return None

    def info(self) -> List[dict]:
        out = []

        def walk(g: ResourceGroup):
            out.append({"group": g.path, "running": g.running,
                        "queued": len(g.queue),
                        "hardConcurrencyLimit":
                            g.config.hard_concurrency_limit,
                        "totalAdmitted": g.stats_total_admitted})
            for sub in g.sub_groups.values():
                walk(sub)
        walk(self.root)
        return out
