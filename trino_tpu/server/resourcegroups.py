"""Hierarchical resource groups — admission control.

Reference: execution/resourcegroups/InternalResourceGroup.java:76 —
groups form a tree; each group has a hard concurrency limit and a queue
bound; selectors route queries to groups by user; FIFO within a group.
Config is pluggable in the reference (file/DB managers,
plugin/trino-resource-group-managers) — here a plain dataclass tree.

Round-9 growth — memory-aware admission + queue-wait accounting:

- `soft_memory_limit_bytes` (InternalResourceGroup.softMemoryLimitBytes):
  while a group's observed memory usage exceeds its soft limit, queued
  queries STAY queued (admission gates on bytes, not just concurrency).
  The ClusterMemoryManager publishes the cluster's reserved+revocable
  total each tick via `set_cluster_memory`, which also drains any queues
  that became runnable as memory dropped.
- queue-wait accounting: every queued entry records its enqueue time;
  admission (via `finished` or the memory tick) folds the wait into the
  group's stats, exposed in info() and system.runtime.resource_groups —
  the old code admitted queued queries without ever recording how long
  they waited.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


class QueryQueueFullError(RuntimeError):
    """Admission rejected past the queue bound. Retryable by design:
    the query never started, so a client backing off and resubmitting
    is always safe — overload degrades to fast rejection, not collapse."""
    error_name = "QUERY_QUEUE_FULL"
    error_code = 5
    retryable = True


class QueryQueuedTimeExceededError(RuntimeError):
    """query_max_queued_time_s expired while the query was still
    QUEUED. Retryable like the queue-full rejection — nothing executed,
    the cluster was simply too busy to start it in time."""
    error_name = "QUERY_EXCEEDED_QUEUED_TIME"
    error_code = 6
    retryable = True


@dataclass
class ResourceGroupConfig:
    name: str
    hard_concurrency_limit: int = 4
    max_queued: int = 100
    # memory-aware admission: while the group's observed usage exceeds
    # this, queued queries stay queued (None = no memory gate)
    soft_memory_limit_bytes: Optional[int] = None
    sub_groups: tuple = ()


@dataclass
class Selector:
    user_pattern: str             # regex over the session user
    group: str                    # dot path, e.g. "root.adhoc"


class ResourceGroup:
    def __init__(self, config: ResourceGroupConfig,
                 parent: Optional["ResourceGroup"] = None):
        self.config = config
        self.parent = parent
        self.running = 0
        # (run callable, enqueue monotonic time)
        self.queue: deque = deque()
        self.sub_groups: Dict[str, ResourceGroup] = {
            sub.name: ResourceGroup(sub, self)
            for sub in config.sub_groups}
        self.stats_total_admitted = 0
        self.stats_peak_queued = 0
        self.stats_total_queue_wait_s = 0.0
        self.stats_dequeued = 0          # admissions that waited in queue
        self.memory_usage_bytes = 0      # last published observation

    @property
    def path(self) -> str:
        return self.config.name if self.parent is None else \
            f"{self.parent.path}.{self.config.name}"

    def can_run(self) -> bool:
        """A query may start when every group up the chain has headroom
        (the reference's canRunMore walk) — concurrency AND memory."""
        g: Optional[ResourceGroup] = self
        while g is not None:
            if g.running >= g.config.hard_concurrency_limit:
                return False
            soft = g.config.soft_memory_limit_bytes
            if soft is not None and g.memory_usage_bytes > soft:
                return False
            g = g.parent
        return True

    def acquire(self) -> None:
        g: Optional[ResourceGroup] = self
        while g is not None:
            g.running += 1
            g = g.parent
        self.stats_total_admitted += 1

    def release(self) -> None:
        g: Optional[ResourceGroup] = self
        while g is not None:
            g.running = max(0, g.running - 1)
            g = g.parent


def tenant_tree(tenants: Dict[str, dict],
                hard_concurrency_limit: int = 4,
                max_queued: int = 100) -> "ResourceGroupManager":
    """Build a per-tenant manager: one sub-group per tenant under root,
    each with its own concurrency/queue/soft-memory knobs, and a
    selector routing `<tenant>` and `<tenant>-*` principals to it.
    `tenants` maps tenant name -> overrides (any ResourceGroupConfig
    field). The elastic soak uses this shape; production configs build
    the same tree from whatever config source they like."""
    subs = tuple(
        ResourceGroupConfig(
            name,
            hard_concurrency_limit=ov.get("hard_concurrency_limit",
                                          hard_concurrency_limit),
            max_queued=ov.get("max_queued", max_queued),
            soft_memory_limit_bytes=ov.get("soft_memory_limit_bytes"))
        for name, ov in tenants.items())
    selectors = [Selector(rf"{re.escape(name)}(-.*)?", f"root.{name}")
                 for name in tenants]
    root = ResourceGroupConfig(
        "root",
        hard_concurrency_limit=max(
            hard_concurrency_limit,
            sum(s.hard_concurrency_limit for s in subs)),
        sub_groups=subs)
    return ResourceGroupManager(root, selectors)


class ResourceGroupManager:
    """Routes queries to leaf groups and gates execution: run now, queue,
    or reject (Too many queued queries)."""

    def __init__(self, root: ResourceGroupConfig,
                 selectors: Optional[List[Selector]] = None):
        self.root = ResourceGroup(root)
        self.selectors = selectors or []
        self._lock = threading.Lock()

    def _find(self, path: str) -> ResourceGroup:
        parts = path.split(".")
        g = self.root
        assert parts[0] == self.root.config.name, path
        for p in parts[1:]:
            g = g.sub_groups[p]
        return g

    def _groups(self):
        out = []

        def walk(g: ResourceGroup):
            out.append(g)
            for sub in g.sub_groups.values():
                walk(sub)
        walk(self.root)
        return out

    def select(self, user: str) -> ResourceGroup:
        for sel in self.selectors:
            if re.fullmatch(sel.user_pattern, user):
                return self._find(sel.group)
        return self.root

    def tenant_of(self, user: str) -> str:
        """The principal's tenant label: the leaf name of its selected
        group ('default' for unselected users landing on root). Labels
        per-tenant metrics, history records, and audit events."""
        group = self.select(user)
        return "default" if group is self.root \
            else group.config.name

    def submit(self, user: str, run: Callable[[], None],
               is_dead: Optional[Callable[[], bool]] = None) -> str:
        """Admit or queue `run`; returns the chosen group path. Raises
        QueryQueueFullError past the queue bound. `is_dead` (optional)
        lets admission skip entries that died while QUEUED (queued-time
        deadline, user cancel) instead of running a terminal query."""
        with self._lock:
            group = self.select(user)
            self._prune_dead_locked(group)
            if group.can_run():
                group.acquire()
                to_run = run
            elif len(group.queue) < group.config.max_queued:
                group.queue.append((run, time.monotonic(), is_dead))
                group.stats_peak_queued = max(group.stats_peak_queued,
                                              len(group.queue))
                return group.path
            else:
                raise QueryQueueFullError(
                    f"Too many queued queries for {group.path!r}")
        to_run()
        return group.path

    @staticmethod
    def _prune_dead_locked(group: ResourceGroup) -> None:
        """Drop queue entries whose query reached a terminal state while
        waiting — their slot frees immediately, so a wave of expired/
        canceled queued queries cannot wedge admission."""
        if any(dead is not None and dead() for _, _, dead in group.queue):
            group.queue = deque(e for e in group.queue
                                if e[2] is None or not e[2]())

    def _pop_runnable_locked(self, group: ResourceGroup) \
            -> Optional[Callable[[], None]]:
        """Admit the group's next queued query if it can run now,
        recording its queue wait (the accounting `finished()` used to
        skip entirely)."""
        self._prune_dead_locked(group)
        if group.queue and group.can_run():
            run, t0, _dead = group.queue.popleft()
            group.acquire()
            group.stats_total_queue_wait_s += time.monotonic() - t0
            group.stats_dequeued += 1
            return run
        return None

    def prune_dead(self) -> None:
        """Sweep every group's queue for dead entries (the coordinator's
        deadline enforcer calls this after failing queued queries)."""
        with self._lock:
            for g in self._groups():
                self._prune_dead_locked(g)

    def total_queued(self) -> int:
        """Cluster-wide queued-query count — the load-shed gate's queue-
        depth signal."""
        with self._lock:
            return sum(len(g.queue) for g in self._groups())

    def finished(self, group_path: str) -> Optional[Callable[[], None]]:
        """Release a slot; returns the next queued query to start (the
        caller runs it outside the lock), if any."""
        with self._lock:
            group = self._find(group_path)
            group.release()
            return self._pop_runnable_locked(group)

    def set_cluster_memory(self, total_bytes: int) \
            -> List[Callable[[], None]]:
        """Publish the cluster's observed memory usage to every group
        and return any queued queries that became admittable (memory
        dropped below a soft limit). The caller runs them outside the
        lock. Group-level attribution collapses to the cluster total —
        one engine session per coordinator means every group observes
        the same pressure (the reference attributes per-group via
        per-query contexts; the ledger tags exist for that refinement)."""
        runnable: List[Callable[[], None]] = []
        with self._lock:
            groups = self._groups()
            for g in groups:
                g.memory_usage_bytes = total_bytes
            for g in groups:
                while True:
                    run = self._pop_runnable_locked(g)
                    if run is None:
                        break
                    runnable.append(run)
        return runnable

    def info(self) -> List[dict]:
        with self._lock:
            groups = self._groups()
            out = []
            for g in groups:
                waited = g.stats_dequeued
                out.append({
                    "group": g.path, "running": g.running,
                    "queued": len(g.queue),
                    "hardConcurrencyLimit":
                        g.config.hard_concurrency_limit,
                    "totalAdmitted": g.stats_total_admitted,
                    "softMemoryLimitBytes":
                        g.config.soft_memory_limit_bytes,
                    "memoryUsageBytes": g.memory_usage_bytes,
                    "totalQueueWaitSeconds":
                        round(g.stats_total_queue_wait_s, 6),
                    "avgQueueWaitSeconds":
                        round(g.stats_total_queue_wait_s / waited, 6)
                        if waited else 0.0,
                    "peakQueued": g.stats_peak_queued})
        return out
