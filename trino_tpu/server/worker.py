"""Worker server + announcer.

Reference: the worker role of Server.java (ServerMainModule.java:200
WorkerModule) — a worker exposes /v1/status for liveness and /v1/task for
fragment execution, and announces itself to discovery (node/Announcer.java).

In the TPU runtime a "worker" owns a slice of the device mesh within the
host process; across hosts each worker process owns its host's chips and
the coordinator drives them over this control plane. The data plane between
co-located workers is ICI collectives inside the jitted stage programs, so
/v1/task here accepts work descriptors rather than serialized pages.

Routes live in the module-level ROUTES table (server/routes.py): every
request is counted in the process metrics registry, and /v1/metrics serves
the registry in Prometheus text format. Task POSTs carry the coordinator's
W3C `traceparent`, which the task manager adopts so worker spans stitch
into the query trace.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.request import Request, urlopen

from .routes import STAR, dispatch, register_routes

SERVER_NAME = "worker"

# (METHOD, pattern, handler method, needs_auth) — see server/routes.py.
# The task/exchange data plane is cluster-internal: with
# TRINO_TPU_INTERNAL_SECRET set, callers without the shared-secret
# header get 401 (anyone with network reach could otherwise pull result
# pages or inject work). Liveness/metrics stay open.
ROUTES = (
    ("GET", ("v1", "status"), "_get_status", False),
    ("GET", ("v1", "info"), "_get_info", False),
    ("GET", ("v1", "metrics"), "_get_metrics", False),
    ("GET", ("v1", "task", STAR), "_get_task", "internal"),
    ("GET", ("v1", "task", STAR, "results", STAR), "_get_results",
     "internal"),
    ("GET", ("v1", "task", STAR, "results", STAR, STAR), "_get_results",
     "internal"),
    ("POST", ("v1", "task", STAR), "_post_task", "internal"),
    ("DELETE", ("v1", "task", STAR), "_delete_task", "internal"),
    ("PUT", ("v1", "info", "state"), "_put_state", "internal"),
)

register_routes(SERVER_NAME, ROUTES)


class _WorkerHandler(BaseHTTPRequestHandler):
    worker: "WorkerServer" = None
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def _send(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str,
                   content_type: str = "text/plain; version=0.0.4") -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_page(self, frame: bytes, headers: dict) -> None:
        """Binary data-plane response: the page frame raw in the body,
        pull-protocol metadata in headers (PagesSerde over HTTP — the
        reference's TaskResource results route with
        application/x-trino-pages)."""
        self.send_response(200)
        self.send_header("Content-Type", "application/x-trino-pages")
        self.send_header("Content-Length", str(len(frame)))
        for k, v in headers.items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(frame)

    def _not_found(self, path: str) -> None:
        self._send(404, {"error": f"no route {path}"})

    # -- dispatch ----------------------------------------------------------

    def do_GET(self):
        dispatch(self, "GET", ROUTES, SERVER_NAME)

    def do_POST(self):
        dispatch(self, "POST", ROUTES, SERVER_NAME)

    def do_DELETE(self):
        dispatch(self, "DELETE", ROUTES, SERVER_NAME)

    def do_PUT(self):
        dispatch(self, "PUT", ROUTES, SERVER_NAME)

    # -- routes -----------------------------------------------------------

    def _get_status(self, parts, user):
        if self.worker.fail_status:          # fault injection hook
            self._send(500, {"error": "injected failure"})
            return
        from ..exec.profiler import device_memory_stats
        self._send(200, {"nodeId": self.worker.node_id,
                         "state": self.worker.state,
                         "uptime": time.time() - self.worker.started_at,
                         # heartbeat memory report: the failure
                         # detector's pings carry this to the
                         # coordinator's ClusterMemoryManager
                         "memory":
                             self.worker.task_manager.memory_info(),
                         # live accelerator/HBM allocator stats (zeros
                         # off-TPU) — surfaced in system.runtime.nodes
                         "device": device_memory_stats()})

    def _get_info(self, parts, user):
        self._send(200, {"nodeVersion": {"version": "trino-tpu-0.1"},
                         "coordinator": False})

    def _get_metrics(self, parts, user):
        from ..metrics import REGISTRY
        self._send_text(200, REGISTRY.render())

    def _task_or_404(self, task_id: str):
        task = self.worker.task_manager.get(task_id)
        if task is None:
            self._send(404, {"error": f"unknown task {task_id}"})
        return task

    # GET /v1/task/{id} — TaskStatus long-poll target
    # (server/remotetask/ContinuousTaskStatusFetcher's endpoint)
    def _get_task(self, parts, user):
        task = self._task_or_404(parts[2])
        if task is not None:
            self._send(200, self.worker.task_manager.status_json(task))

    # GET /v1/task/{id}/results/{token}            — buffer 0
    # GET /v1/task/{id}/results/{buffer}/{token}   — partitioned
    # (server/TaskResource.java:332; buffers are the partitioned
    # output of the worker<->worker exchange)
    def _get_results(self, parts, user):
        task = self._task_or_404(parts[2])
        if task is None:
            return
        if self.worker.fail_results:         # fault injection hook
            self._send(500, {"error": "injected results failure"})
            return
        buffer = int(parts[4]) if len(parts) == 6 else 0
        token = int(parts[-1])
        binary = "x-trino-pages" in self.headers.get("Accept", "")
        # only bookkeeping under the lock: P concurrent consumer
        # pulls + the producer's _emit all contend on it, so socket
        # writes must happen after release
        frame = None
        envelope = None
        with task.cond:
            pages = task.buffers.setdefault(buffer, [])
            acked = task.acked.get(buffer, 0)
            # Advancing to `token` acknowledges every page below it
            # (TaskResource.java:372's implicit-ack contract) — drop
            # drained pages so a long-lived worker's memory stays flat;
            # same-token retries after a fetch failure still succeed.
            drained = 0
            while acked < token and pages:
                drained += len(pages.pop(0))
                acked += 1
            task.acked[buffer] = acked
            if drained:
                # acks free staged bytes: wake a producer paused on a
                # full output buffer (exchange backpressure)
                task.buffered_bytes = max(0, task.buffered_bytes - drained)
                task.cond.notify_all()
            idx = token - acked
            total = acked + len(pages)
            if 0 <= idx < len(pages):
                frame = pages[idx]
            else:
                done = task.state in ("FINISHED", "FAILED",
                                      "CANCELED")
                envelope = {"token": token,
                            "complete": done and token >= total,
                            "state": task.state,
                            "error": task.error, "page": None}
        if frame is not None:
            if binary:
                self._send_page(frame, {"X-Trino-Token": token,
                                        "X-Trino-Complete": "false"})
            else:
                import base64
                self._send(200, {
                    "token": token, "complete": False,
                    "page": {"b64": base64.b64encode(
                        frame).decode()}})
        else:
            self._send(200, envelope)

    # POST /v1/task/{id} — create/update with fragment + splits
    # (server/TaskResource.java:146 createOrUpdateTask)
    def _post_task(self, parts, user):
        if self.worker.fail_tasks:           # fault injection hook
            self._send(500, {"error": "injected task failure"})
            return
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n).decode())
        from .failureinjector import InjectedFailure
        from .tasks import Split
        splits = [Split(**s) for s in body.get("splits", [])]
        try:
            task = self.worker.task_manager.create_or_update(
                parts[2], body["fragment"], splits,
                partition=body.get("partition"),
                sources=body.get("sources"),
                traceparent=self.headers.get("traceparent"))
        except InjectedFailure as e:
            # chaos at task intake (crash/drop/raise all surface to
            # the coordinator as a failed POST -> split reassignment)
            self._send(500, {"error": str(e)})
            return
        self._send(200, self.worker.task_manager.status_json(task))

    # DELETE /v1/task/{id} — cancel/abort (TaskResource.java:319's
    # fail route collapsed with delete)
    def _delete_task(self, parts, user):
        self.worker.task_manager.cancel(parts[2])
        self._send(204, {})

    def _put_state(self, parts, user):       # graceful shutdown / drain
        n = int(self.headers.get("Content-Length", 0))
        state = json.loads(self.rfile.read(n).decode())
        self.worker.state = state
        self._send(200, {"state": self.worker.state})


class WorkerServer:
    """One worker process stand-in: HTTP status endpoint + announcer loop."""

    def __init__(self, node_id: str, coordinator_uri: str, port: int = 0,
                 announce_interval_s: float = 1.0, catalog=None):
        self.node_id = node_id
        self.coordinator_uri = coordinator_uri
        self.state = "ACTIVE"
        self.fail_status = False
        self.fail_tasks = False          # inject: task creation fails
        self.fail_results = False        # inject: result fetch fails
        self.started_at = time.time()
        from ..catalog import default_catalog
        from .tasks import TaskManager
        self.catalog = catalog if catalog is not None else default_catalog()
        self.task_manager = TaskManager(self.catalog, node_id=node_id)
        handler = type("BoundWorkerHandler", (_WorkerHandler,),
                       {"worker": self})
        from .coordinator import ClusterHTTPServer
        self.httpd = ClusterHTTPServer(("127.0.0.1", port), handler)
        self.port = self.httpd.server_address[1]
        self.uri = f"http://127.0.0.1:{self.port}"
        self.announce_interval_s = announce_interval_s
        self._stop = threading.Event()
        self._threads = []

    def start(self) -> "WorkerServer":
        t1 = threading.Thread(target=self.httpd.serve_forever,
                              name=f"worker-{self.node_id}", daemon=True)
        t1.start()
        t2 = threading.Thread(target=self._announce_loop,
                              name=f"announcer-{self.node_id}", daemon=True)
        t2.start()
        self._threads = [t1, t2]
        return self

    def announce_once(self, attempts: int = 5) -> None:
        """Announce to the coordinator, retrying transient failures with
        backoff + decorrelated jitter — a worker that boots before the
        coordinator (or across a coordinator restart) must not fail its
        announcement permanently on one refused connection."""
        from .retrypolicy import RetryPolicy

        def post():
            from .security import internal_headers
            body = json.dumps({"nodeId": self.node_id,
                               "uri": self.uri}).encode()
            req = Request(f"{self.coordinator_uri}/v1/announce", data=body,
                          headers={"Content-Type": "application/json",
                                   **internal_headers()})
            with urlopen(req, timeout=5):
                pass

        RetryPolicy(base_delay_s=0.1, max_delay_s=1.0,
                    max_attempts=max(1, attempts),
                    name="announce").call(
            post, retry_on=(OSError,),
            sleep=lambda d: self._stop.wait(d))

    def _announce_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.announce_once()
            except Exception:
                pass                      # coordinator down: keep trying
            self._stop.wait(self.announce_interval_s)

    def stop(self) -> None:
        self._stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()
