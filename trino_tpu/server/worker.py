"""Worker server + announcer.

Reference: the worker role of Server.java (ServerMainModule.java:200
WorkerModule) — a worker exposes /v1/status for liveness and /v1/task for
fragment execution, and announces itself to discovery (node/Announcer.java).

In the TPU runtime a "worker" owns a slice of the device mesh within the
host process; across hosts each worker process owns its host's chips and
the coordinator drives them over this control plane. The data plane between
co-located workers is ICI collectives inside the jitted stage programs, so
/v1/task here accepts work descriptors rather than serialized pages.

Routes live in the module-level ROUTES table (server/routes.py): every
request is counted in the process metrics registry, and /v1/metrics serves
the registry in Prometheus text format. Task POSTs carry the coordinator's
W3C `traceparent`, which the task manager adopts so worker spans stitch
into the query trace.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.request import Request, urlopen

from .routes import STAR, dispatch, register_routes

SERVER_NAME = "worker"

# (METHOD, pattern, handler method, needs_auth) — see server/routes.py.
# The task/exchange data plane is cluster-internal: with
# TRINO_TPU_INTERNAL_SECRET set, callers without the shared-secret
# header get 401 (anyone with network reach could otherwise pull result
# pages or inject work). Liveness/metrics stay open.
ROUTES = (
    ("GET", ("v1", "status"), "_get_status", False),
    ("GET", ("v1", "info"), "_get_info", False),
    ("GET", ("v1", "info", "state"), "_get_state", False),
    ("GET", ("v1", "metrics"), "_get_metrics", False),
    ("GET", ("v1", "task", STAR), "_get_task", "internal"),
    # incremental live TaskStats (round-21): ?since=<seq> returns the
    # bounded live record only when the task changed past the cursor
    ("GET", ("v1", "task", STAR, "status"), "_get_task_status",
     "internal"),
    ("GET", ("v1", "task", STAR, "results", STAR), "_get_results",
     "internal"),
    ("GET", ("v1", "task", STAR, "results", STAR, STAR), "_get_results",
     "internal"),
    ("POST", ("v1", "task", STAR), "_post_task", "internal"),
    ("DELETE", ("v1", "task", STAR), "_delete_task", "internal"),
    ("PUT", ("v1", "info", "state"), "_put_state", "internal"),
    # flight-recorder scrape (server/telemetry.py): the coordinator
    # federates worker rings from here. Internal: metric keys carry
    # tenant/route labels a stranger shouldn't map
    ("GET", ("v1", "telemetry"), "_get_telemetry", "internal"),
)

register_routes(SERVER_NAME, ROUTES)


class _WorkerHandler(BaseHTTPRequestHandler):
    worker: "WorkerServer" = None
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def _send(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str,
                   content_type: str = "text/plain; version=0.0.4") -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_page(self, frame: bytes, headers: dict) -> None:
        """Binary data-plane response: the page frame raw in the body,
        pull-protocol metadata in headers (PagesSerde over HTTP — the
        reference's TaskResource results route with
        application/x-trino-pages)."""
        self.send_response(200)
        self.send_header("Content-Type", "application/x-trino-pages")
        self.send_header("Content-Length", str(len(frame)))
        for k, v in headers.items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(frame)

    def _not_found(self, path: str) -> None:
        self._send(404, {"error": f"no route {path}"})

    # -- dispatch ----------------------------------------------------------

    def do_GET(self):
        dispatch(self, "GET", ROUTES, SERVER_NAME)

    def do_POST(self):
        dispatch(self, "POST", ROUTES, SERVER_NAME)

    def do_DELETE(self):
        dispatch(self, "DELETE", ROUTES, SERVER_NAME)

    def do_PUT(self):
        dispatch(self, "PUT", ROUTES, SERVER_NAME)

    # -- routes -----------------------------------------------------------

    def _get_status(self, parts, user):
        if self.worker.fail_status:          # fault injection hook
            self._send(500, {"error": "injected failure"})
            return
        from ..exec.prewarm import compile_cache_stats
        from ..exec.profiler import device_memory_stats
        payload = {"nodeId": self.worker.node_id,
                   "state": self.worker.state,
                   "uptime": time.time() - self.worker.started_at,
                   # heartbeat memory report: the failure
                   # detector's pings carry this to the
                   # coordinator's ClusterMemoryManager
                   "memory":
                       self.worker.task_manager.memory_info(),
                   # live accelerator/HBM allocator stats (zeros
                   # off-TPU) — surfaced in system.runtime.nodes
                   "device": device_memory_stats(),
                   # persistent compile-cache report: operators verify
                   # cache-dir sharing across workers from here
                   "compileCache": compile_cache_stats()}
        if self.worker.prewarm is not None:
            payload["prewarm"] = self.worker.prewarm.stats()
        self._send(200, payload)

    def _get_info(self, parts, user):
        self._send(200, {"nodeVersion": {"version": "trino-tpu-0.1"},
                         "coordinator": False,
                         "state": self.worker.state})

    # GET /v1/info/state — the read side of the drain request (the
    # reference's NodeState resource); open like the other liveness
    # routes so operators can watch a drain without the secret
    def _get_state(self, parts, user):
        self._send(200, {"state": self.worker.state})

    def _get_metrics(self, parts, user):
        from ..metrics import REGISTRY
        self._send_text(200, REGISTRY.render())

    # GET /v1/telemetry?since=<ts> — incremental flight-recorder scrape
    def _get_telemetry(self, parts, user):
        from urllib.parse import parse_qs, urlparse
        try:
            since = float(parse_qs(urlparse(self.path).query)
                          .get("since", ["0"])[0])
        except ValueError:
            since = 0.0
        rec = self.worker.telemetry
        self._send(200, {"nodeId": self.worker.node_id,
                         "samples": rec.since(since)})

    def _task_or_404(self, task_id: str):
        task = self.worker.task_manager.get(task_id)
        if task is None:
            self._send(404, {"error": f"unknown task {task_id}"})
        else:
            # every coordinator pull is a liveness signal for the
            # orphan reaper: a referenced task is never abandoned
            self.worker.task_manager.touch(task_id)
        return task

    # GET /v1/task/{id} — TaskStatus long-poll target
    # (server/remotetask/ContinuousTaskStatusFetcher's endpoint)
    def _get_task(self, parts, user):
        task = self._task_or_404(parts[2])
        if task is not None:
            self._send(200, self.worker.task_manager.status_json(task))

    # GET /v1/task/{id}/status?since=<seq> — the pull twin of the
    # announce-piggybacked heartbeat: a bounded live TaskStats record
    # when the task's change sequence advanced past `since`, a
    # fixed-size unchanged ack otherwise. Unlike GET /v1/task/{id} this
    # never ships operators/spans, so polling it is O(1) per task.
    def _get_task_status(self, parts, user):
        task = self._task_or_404(parts[2])
        if task is None:
            return
        from urllib.parse import parse_qs, urlparse
        try:
            since = int(parse_qs(urlparse(self.path).query)
                        .get("since", ["0"])[0])
        except ValueError:
            since = 0
        live = self.worker.task_manager.live_status(task)
        if live["seq"] <= since:
            self._send(200, {"taskId": task.task_id,
                             "seq": live["seq"], "changed": False})
        else:
            self._send(200, {"taskId": task.task_id,
                             "seq": live["seq"], "changed": True,
                             "task": live})

    # GET /v1/task/{id}/results/{token}            — buffer 0
    # GET /v1/task/{id}/results/{buffer}/{token}   — partitioned
    # (server/TaskResource.java:332; buffers are the partitioned
    # output of the worker<->worker exchange)
    def _get_results(self, parts, user):
        task = self._task_or_404(parts[2])
        if task is None:
            return
        if self.worker.fail_results:         # fault injection hook
            self._send(500, {"error": "injected results failure"})
            return
        buffer = int(parts[4]) if len(parts) == 6 else 0
        token = int(parts[-1])
        binary = "x-trino-pages" in self.headers.get("Accept", "")
        # ?ack=0: serve without the implicit-ack page drop — write-stage
        # consumers use it so a retried or hedged attempt re-reads the
        # whole buffer (an acked page is gone for every later attempt)
        from urllib.parse import parse_qs, urlparse
        ack = parse_qs(urlparse(self.path).query).get(
            "ack", ["1"])[0] != "0"
        # only bookkeeping under the lock: P concurrent consumer
        # pulls + the producer's _emit all contend on it, so socket
        # writes must happen after release
        frame = None
        envelope = None
        with task.cond:
            pages = task.buffers.setdefault(buffer, [])
            acked = task.acked.get(buffer, 0)
            # Advancing to `token` acknowledges every page below it
            # (TaskResource.java:372's implicit-ack contract) — drop
            # drained pages so a long-lived worker's memory stays flat;
            # same-token retries after a fetch failure still succeed.
            drained = 0
            while ack and acked < token and pages:
                drained += len(pages.pop(0))
                acked += 1
            task.acked[buffer] = acked
            if drained:
                # acks free staged bytes: wake a producer paused on a
                # full output buffer (exchange backpressure)
                task.buffered_bytes = max(0, task.buffered_bytes - drained)
                task.cond.notify_all()
            idx = token - acked
            total = acked + len(pages)
            if 0 <= idx < len(pages):
                frame = pages[idx]
            else:
                done = task.state in ("FINISHED", "FAILED",
                                      "CANCELED")
                envelope = {"token": token,
                            "complete": done and token >= total,
                            "state": task.state,
                            "error": task.error, "page": None}
        if frame is not None:
            if binary:
                self._send_page(frame, {"X-Trino-Token": token,
                                        "X-Trino-Complete": "false"})
            else:
                import base64
                self._send(200, {
                    "token": token, "complete": False,
                    "page": {"b64": base64.b64encode(
                        frame).decode()}})
        else:
            self._send(200, envelope)

    # POST /v1/task/{id} — create/update with fragment + splits
    # (server/TaskResource.java:146 createOrUpdateTask)
    def _post_task(self, parts, user):
        if self.worker.fail_tasks:           # fault injection hook
            self._send(500, {"error": "injected task failure"})
            return
        if self.worker.state != "ACTIVE":
            # a draining/drained worker accepts NO new work; 409 tells
            # the scheduler this is a lifecycle handoff (the splits
            # migrate to survivors), not a node failure
            self._send(409, {"error": f"node is {self.worker.state}",
                             "errorName": "NODE_DRAINING"})
            return
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n).decode())
        from .failureinjector import InjectedFailure
        from .tasks import Split
        splits = [Split(**s) for s in body.get("splits", [])]
        try:
            task = self.worker.task_manager.create_or_update(
                parts[2], body["fragment"], splits,
                partition=body.get("partition"),
                sources=body.get("sources"),
                traceparent=self.headers.get("traceparent"),
                deadline=body.get("deadline"))
        except InjectedFailure as e:
            # chaos at task intake (crash/drop/raise all surface to
            # the coordinator as a failed POST -> split reassignment)
            self._send(500, {"error": str(e)})
            return
        self._send(200, self.worker.task_manager.status_json(task))

    # DELETE /v1/task/{id} — cancel/abort (TaskResource.java:319's
    # fail route collapsed with delete)
    def _delete_task(self, parts, user):
        self.worker.task_manager.cancel(parts[2])
        self._send(204, {})

    # PUT /v1/info/state — the admin drain request
    # (server/ServerInfoResource.java updateState's SHUTTING_DOWN path):
    # "DRAINING" starts the graceful-drain sequence asynchronously;
    # "ACTIVE" cancels a not-yet-completed drain (the node resumes
    # accepting work and re-announces).
    def _put_state(self, parts, user):
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n).decode())
        requested = body.get("state") if isinstance(body, dict) else body
        if requested not in ("DRAINING", "ACTIVE"):
            self._send(400, {"error": f"cannot request state "
                                      f"{requested!r} (valid: DRAINING, "
                                      f"ACTIVE)"})
            return
        if requested == "DRAINING":
            self.worker.request_drain()
        else:
            self.worker.cancel_drain()
        self._send(200, {"state": self.worker.state})


class WorkerServer:
    """One worker process stand-in: HTTP status endpoint + announcer loop.

    Lifecycle: ACTIVE -> DRAINING -> DRAINED -> LEFT. A drain (admin
    `PUT /v1/info/state` or a graceful `stop()`) stops task intake,
    finishes in-flight splits, keeps output buffers pullable until
    consumers drain them, then deregisters with a final LEFT announce.
    Every announce carries the state, so the coordinator's scheduler
    stops placing splits here the moment DRAINING lands."""

    def __init__(self, node_id: str, coordinator_uri: str, port: int = 0,
                 announce_interval_s: float = 1.0, catalog=None,
                 drain_timeout_s: float = 30.0,
                 flush_grace_s: float = 1.0,
                 telemetry_interval_s: Optional[float] = None,
                 heartbeat_interval_s: Optional[float] = None):
        self.node_id = node_id
        self.coordinator_uri = coordinator_uri
        # coordinator failover address list: seeded with the boot uri,
        # refreshed from every announce response (the serving
        # coordinator echoes itself + its standbys), rotated through
        # when a full announce round fails — this is how a worker finds
        # the promoted standby after the primary dies without a goodbye
        self.coordinators = [coordinator_uri]
        self._coord_lock = threading.Lock()
        # terminal task reports the coordinator couldn't take (dead or
        # mid-failover); re-delivered after the next successful announce
        self._pending_reports: deque = deque(maxlen=256)
        self.state = "ACTIVE"
        self.drain_timeout_s = drain_timeout_s
        # bounded wait for FINISHED tasks' unpulled output buffers
        # before DRAINED: consumers normally drain within this; buffers
        # abandoned by failed/hedge-lost queries must not hold the
        # drain hostage (they stay pullable until the process stops)
        self.flush_grace_s = flush_grace_s
        self.fail_status = False
        self.fail_tasks = False          # inject: task creation fails
        self.fail_results = False        # inject: result fetch fails
        self.started_at = time.time()
        # joining-worker prewarm handshake (exec/prewarm.py): with
        # TRINO_TPU_PREWARM set, the announcer thread first pulls the
        # coordinator's warm-manifest and compiles the canonical shape
        # lattice, so the node is warm BEFORE its first ACTIVE announce
        # puts it in the scheduler's placement set
        from ..exec.prewarm import prewarm_enabled_by_env
        self.prewarm_enabled = prewarm_enabled_by_env()
        self.prewarm = None              # PrewarmEngine after handshake
        self.prewarm_manifest: Optional[dict] = None
        from ..catalog import default_catalog
        from .tasks import TaskManager
        self.catalog = catalog if catalog is not None else default_catalog()
        self.task_manager = TaskManager(self.catalog, node_id=node_id)
        self.task_manager.on_terminal = self._task_terminal
        handler = type("BoundWorkerHandler", (_WorkerHandler,),
                       {"worker": self})
        from .coordinator import ClusterHTTPServer
        self.httpd = ClusterHTTPServer(("127.0.0.1", port), handler)
        self.port = self.httpd.server_address[1]
        self.uri = f"http://127.0.0.1:{self.port}"
        self.announce_interval_s = announce_interval_s
        self._stop = threading.Event()
        self._state_lock = threading.Lock()
        self._drain_thread: Optional[threading.Thread] = None
        self._drain_cancel = threading.Event()
        self._threads = []
        # per-node flight recorder; interval<=0 (the default) records
        # only on demand and spawns no sampler thread
        from .telemetry import FlightRecorder
        self.telemetry = FlightRecorder(node_id,
                                        interval_s=telemetry_interval_s)
        # live-stats heartbeat (round-21): when set, every announce
        # piggybacks delta-encoded live task stats + a pool snapshot and
        # the announce loop ticks at min(announce, heartbeat) interval.
        # Unset (the default): NO extra thread, the announce body stays
        # byte-identical to the heartbeat-less wire form, and terminal
        # task status is untouched — the telemetry zero-overhead
        # contract applied to the task-status path.
        self.heartbeat_interval_s = heartbeat_interval_s
        self._live_cursor = 0             # last DELIVERED change seq
        self._busy_prev = None            # (monotonic, busy_ms) sample
        # orphan-reaper failover fence (round-22): the announce loop
        # only reaps after a successful announce to a PRIMARY
        # coordinator, and not until this monotonic stamp passes. A
        # failed announce round, a coordinator rotation, or an answer
        # from a still-RECONCILING promotee all push the fence forward —
        # a promoted standby reattaching to this worker's live tasks
        # must never find them reaped out from under it.
        self.reap_fence_s = 30.0
        self._reap_fence_until = 0.0
        self._last_announce_role = "PRIMARY"

    def start(self) -> "WorkerServer":
        t1 = threading.Thread(target=self.httpd.serve_forever,
                              name=f"worker-{self.node_id}", daemon=True)
        t1.start()
        t2 = threading.Thread(target=self._announce_loop,
                              name=f"announcer-{self.node_id}", daemon=True)
        t2.start()
        self._threads = [t1, t2]
        self.telemetry.start()
        return self

    def announce_once(self, attempts: int = 5,
                      state: Optional[str] = None) -> None:
        """Announce to the coordinator, retrying transient failures with
        backoff + decorrelated jitter — a worker that boots before the
        coordinator (or across a coordinator restart) must not fail its
        announcement permanently on one refused connection. The announce
        body carries the lifecycle state so membership transitions reach
        the coordinator without waiting for a heartbeat round trip."""
        from .retrypolicy import RetryPolicy

        # heartbeat piggyback (round-21): computed ONCE per announce so
        # retries re-ship the same delta; the cursor commits only after
        # the announce lands, so a failed round loses nothing
        hb_cursor = hb = None
        if self.heartbeat_interval_s is not None:
            hb_cursor, hb = self._heartbeat_payload()

        def post():
            from .security import internal_headers
            # "now" lets the coordinator estimate this node's clock
            # offset (announce RTT is sub-ms in-process, so the send
            # stamp ~= receive time on a synchronized clock); the task
            # inventory lets a freshly-promoted coordinator reconcile
            # ledger-assigned work against what actually survived here
            doc = {"nodeId": self.node_id,
                   "uri": self.uri,
                   "state": state or self.state,
                   "now": time.time(),
                   "tasks": self.task_manager.inventory()}
            if hb is not None:
                doc["liveStats"] = hb
                # pool snapshot between failure-detector pings: shrinks
                # the memory manager's staleness window
                doc["memory"] = self.task_manager.memory_info()
            body = json.dumps(doc).encode()
            req = Request(f"{self.coordinator_uri}/v1/announce", data=body,
                          headers={"Content-Type": "application/json",
                                   **internal_headers()})
            with urlopen(req, timeout=5) as r:
                try:
                    resp = json.loads(r.read().decode())
                except ValueError:
                    resp = {}
            self._adopt_coordinators(resp.get("coordinators"))
            self._last_announce_role = resp.get("role", "PRIMARY")

        RetryPolicy(base_delay_s=0.1, max_delay_s=1.0,
                    max_attempts=max(1, attempts),
                    name="announce").call(
            post, retry_on=(OSError,),
            sleep=lambda d: self._stop.wait(d))
        if hb_cursor is not None:
            self._live_cursor = hb_cursor
        # the announce landed, so the coordinator at this address is
        # alive: drain any terminal reports it (or its dead predecessor)
        # missed
        self._flush_reports()

    def _heartbeat_payload(self) -> tuple:
        """(cursor, payload): delta-encoded live task stats — only
        tasks whose change sequence moved past the last DELIVERED
        cursor ship, with absolute counter values so folds are
        idempotent — plus this node's per-interval device/host busy
        fractions (sampled into the node_busy_fraction gauges so the
        flight recorder picks them up)."""
        from ..metrics import (LIVE_STATS_BYTES, NODE_BUSY_FRACTION,
                               NODE_BUSY_MS, TASK_HEARTBEATS)
        cursor, entries = self.task_manager.live_delta(self._live_cursor)
        now = time.monotonic()
        busy = self.task_manager.busy_ms()
        util = {}
        if self._busy_prev is not None:
            prev_t, prev_busy = self._busy_prev
            wall_ms = max(1e-9, (now - prev_t) * 1000)
            for tier, key in (("device", "deviceMs"), ("host", "hostMs")):
                delta = max(0.0, busy[key] - prev_busy[key])
                frac = min(1.0, delta / wall_ms)
                util[tier] = round(frac, 4)
                NODE_BUSY_FRACTION.set(round(frac, 4), tier=tier)
                # cumulative form: a delta-encoding scraper (the flight
                # recorder) turns this into per-interval busy time,
                # which survives several in-process workers sharing one
                # registry where the instantaneous gauge is last-writer-
                # wins
                if delta:
                    NODE_BUSY_MS.inc(delta, tier=tier)
        self._busy_prev = (now, busy)
        payload = {"seq": cursor, "tasks": entries, "busy": busy,
                   "utilization": util}
        TASK_HEARTBEATS.inc()
        LIVE_STATS_BYTES.inc(
            len(json.dumps(payload, separators=(",", ":"))))
        return cursor, payload

    def _adopt_coordinators(self, uris) -> None:
        """Refresh the failover address list from an announce response
        (serving coordinator first, standbys after). The current target
        is kept while still listed so the worker doesn't flap between
        equally-healthy addresses."""
        if not uris:
            return
        with self._coord_lock:
            self.coordinators = list(dict.fromkeys(uris))
            if self.coordinator_uri not in self.coordinators:
                self.coordinator_uri = self.coordinators[0]

    def _rotate_coordinator(self) -> None:
        """Point announces at the next address after a failed round."""
        with self._coord_lock:
            if len(self.coordinators) < 2:
                return
            try:
                i = self.coordinators.index(self.coordinator_uri)
            except ValueError:
                i = -1
            self.coordinator_uri = self.coordinators[
                (i + 1) % len(self.coordinators)]

    # -- terminal-status delivery ------------------------------------------

    def _task_terminal(self, task) -> None:
        """Push a task's final report the moment it completes. An
        undeliverable report — coordinator dead or mid-failover — is
        buffered and re-delivered after the next successful announce
        instead of dropped, so a promoted coordinator hears about work
        that finished while nobody was listening."""
        report = self.task_manager.status_json(task)
        if not self._post_report(report):
            self._pending_reports.append(report)

    def _post_report(self, report: dict) -> bool:
        from .security import internal_headers
        body = json.dumps(report).encode()
        req = Request(f"{self.coordinator_uri}/v1/task-status", data=body,
                      headers={"Content-Type": "application/json",
                               **internal_headers()})
        try:
            with urlopen(req, timeout=5):
                pass
            return True
        except Exception:  # noqa: BLE001 — buffered for re-delivery
            return False

    def _flush_reports(self) -> None:
        while self._pending_reports:
            report = self._pending_reports.popleft()
            if not self._post_report(report):
                self._pending_reports.appendleft(report)
                return

    def prewarm_handshake(self) -> bool:
        """Pull the coordinator's warm-manifest and compile the
        canonical shape lattice before this node announces ACTIVE.
        Best-effort: a missing/denied manifest must never keep a worker
        out of the cluster."""
        from ..exec.prewarm import PrewarmEngine
        from .security import internal_headers
        try:
            req = Request(f"{self.coordinator_uri}/v1/prewarm",
                          headers=internal_headers())
            with urlopen(req, timeout=5) as r:
                manifest = json.loads(r.read().decode())
        except Exception:     # noqa: BLE001 — handshake is best-effort
            return False
        self.prewarm_manifest = manifest
        if self.prewarm is None:
            self.prewarm = PrewarmEngine(enabled=True)
        shapes = [int(c) for c in manifest.get("shapes", ())]
        self.prewarm.warm_shapes(shapes)
        return True

    def _announce_loop(self) -> None:
        if self.prewarm_enabled:
            try:
                self.prewarm_handshake()
            except Exception:
                pass                      # warm-up is best-effort
        while not self._stop.is_set():
            try:
                self.announce_once()
                now = time.monotonic()
                if self._last_announce_role != "PRIMARY":
                    # mid-failover: the promotee is still reconciling
                    # our inventory against its replayed ledger
                    self._reap_fence_until = now + self.reap_fence_s
                elif now >= self._reap_fence_until:
                    try:
                        self.task_manager.reap_orphans()
                    except Exception:  # noqa: BLE001 — reap best-effort
                        pass
            except Exception:
                # coordinator down: rotate to the next address in the
                # failover list for the following round and keep trying;
                # fence the reaper — the silence may be a failover, and
                # the promotee must find our tasks intact
                self._reap_fence_until = \
                    time.monotonic() + self.reap_fence_s
                self._rotate_coordinator()
            interval = self.announce_interval_s
            if self.heartbeat_interval_s is not None:
                # heartbeats ride the announcer thread (no new thread):
                # tick at the faster of the two cadences
                interval = min(interval, self.heartbeat_interval_s)
            self._stop.wait(interval)

    # -- lifecycle state machine -------------------------------------------

    def _transition(self, new_state: str) -> bool:
        """ACTIVE -> DRAINING -> DRAINED -> LEFT (DRAINING may revert to
        ACTIVE when an admin cancels the drain). Invalid edges no-op."""
        allowed = {"ACTIVE": ("DRAINING",),
                   "DRAINING": ("DRAINED", "ACTIVE"),
                   "DRAINED": ("LEFT",),
                   "LEFT": ()}
        with self._state_lock:
            if new_state not in allowed.get(self.state, ()):
                return False
            self.state = new_state
        from ..metrics import NODE_LIFECYCLE_TRANSITIONS
        NODE_LIFECYCLE_TRANSITIONS.inc(state=new_state)
        return True

    def request_drain(self) -> bool:
        """Start the graceful-drain sequence asynchronously: stop
        accepting task POSTs now (state flips before this returns),
        finish/flush in flight, then deregister."""
        if not self._transition("DRAINING"):
            return self.state in ("DRAINING", "DRAINED", "LEFT")
        self._drain_cancel.clear()
        self._drain_thread = threading.Thread(
            target=self._drain_sequence, name=f"drain-{self.node_id}",
            daemon=True)
        self._drain_thread.start()
        return True

    def cancel_drain(self) -> bool:
        """Abort a DRAINING worker back to ACTIVE (no-op once DRAINED:
        the handoff already happened, rejoining takes a fresh announce
        anyway — which `_transition` forbids to keep the ratchet
        one-way per drain request)."""
        self._drain_cancel.set()
        if self._transition("ACTIVE"):
            self._announce_now()
            return True
        return False

    def _announce_now(self, state: Optional[str] = None) -> None:
        try:
            self.announce_once(attempts=2, state=state)
        except Exception:     # noqa: BLE001 — coordinator may be gone
            pass

    def _drain_sequence(self) -> None:
        """The drain body: announce DRAINING immediately, finish every
        in-flight task (bounded by drain_timeout_s), give finished
        tasks' output buffers a flush grace for downstream consumers to
        pull, then DRAINED, then the deregistering LEFT announce.
        Anything the deadline cuts off re-runs on survivors via the
        scheduler's retry machinery (durable-spool dedup keeps that
        bit-exact); buffers stay pullable even after LEFT, until the
        process actually stops — hedge losers and failed queries
        abandon FINISHED buffers nobody will ever pull, so the flush
        wait is a grace period, not a completion requirement."""
        self._announce_now()
        deadline = time.monotonic() + self.drain_timeout_s
        while self.task_manager.inflight() and \
                time.monotonic() < deadline and \
                not self._drain_cancel.is_set():
            time.sleep(0.02)
        flush_deadline = min(deadline,
                             time.monotonic() + self.flush_grace_s)
        while self.task_manager.unflushed() and \
                time.monotonic() < flush_deadline and \
                not self._drain_cancel.is_set():
            time.sleep(0.02)
        if self._drain_cancel.is_set():
            return                        # admin reverted to ACTIVE
        if self._transition("DRAINED"):
            self._announce_now()
        if self._transition("LEFT"):
            self._announce_now()

    def drained(self) -> bool:
        """True once the drain sequence fully quiesced (no in-flight
        tasks, no unflushed buffers) and the worker deregistered."""
        return self.state == "LEFT"

    def stop(self, graceful: bool = True,
             timeout_s: Optional[float] = None) -> None:
        """Graceful by default: run the same bounded drain an admin
        `PUT /v1/info/state` triggers (SIGTERM in the soak harness is
        indistinguishable from an admin drain), then shut the HTTP
        server down. `graceful=False` is the hard-crash path tests use
        to simulate worker death."""
        if graceful and self.state == "ACTIVE":
            budget = self.drain_timeout_s if timeout_s is None \
                else timeout_s
            if self.request_drain():
                deadline = time.monotonic() + budget
                while self.state != "LEFT" and \
                        time.monotonic() < deadline:
                    time.sleep(0.02)
        self.telemetry.stop()
        self._stop.set()
        # shutdown() handshakes with serve_forever — skip it when
        # start() was never called or it would block forever
        if self._threads:
            self.httpd.shutdown()
        self.httpd.server_close()

    def kill(self) -> None:
        """Ungraceful death (no drain, no deregister) — the crash the
        failure detector and retry machinery exist for."""
        self.stop(graceful=False)
