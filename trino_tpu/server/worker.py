"""Worker server + announcer.

Reference: the worker role of Server.java (ServerMainModule.java:200
WorkerModule) — a worker exposes /v1/status for liveness and /v1/task for
fragment execution, and announces itself to discovery (node/Announcer.java).

In the TPU runtime a "worker" owns a slice of the device mesh within the
host process; across hosts each worker process owns its host's chips and
the coordinator drives them over this control plane. The data plane between
co-located workers is ICI collectives inside the jitted stage programs, so
/v1/task here accepts work descriptors rather than serialized pages.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import urlparse
from urllib.request import Request, urlopen


class _WorkerHandler(BaseHTTPRequestHandler):
    worker: "WorkerServer" = None
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def _send(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        path = urlparse(self.path).path
        if path == "/v1/status":
            if self.worker.fail_status:      # fault injection hook
                self._send(500, {"error": "injected failure"})
                return
            self._send(200, {"nodeId": self.worker.node_id,
                             "state": self.worker.state,
                             "uptime": time.time() - self.worker.started_at})
            return
        if path == "/v1/info":
            self._send(200, {"nodeVersion": {"version": "trino-tpu-0.1"},
                             "coordinator": False})
            return
        self._send(404, {"error": f"no route {path}"})

    def do_PUT(self):
        path = urlparse(self.path).path
        if path == "/v1/info/state":         # graceful shutdown / drain
            n = int(self.headers.get("Content-Length", 0))
            state = json.loads(self.rfile.read(n).decode())
            self.worker.state = state
            self._send(200, {"state": self.worker.state})
            return
        self._send(404, {"error": f"no route {path}"})


class WorkerServer:
    """One worker process stand-in: HTTP status endpoint + announcer loop."""

    def __init__(self, node_id: str, coordinator_uri: str, port: int = 0,
                 announce_interval_s: float = 1.0):
        self.node_id = node_id
        self.coordinator_uri = coordinator_uri
        self.state = "ACTIVE"
        self.fail_status = False
        self.started_at = time.time()
        handler = type("BoundWorkerHandler", (_WorkerHandler,),
                       {"worker": self})
        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self.httpd.server_address[1]
        self.uri = f"http://127.0.0.1:{self.port}"
        self.announce_interval_s = announce_interval_s
        self._stop = threading.Event()
        self._threads = []

    def start(self) -> "WorkerServer":
        t1 = threading.Thread(target=self.httpd.serve_forever,
                              name=f"worker-{self.node_id}", daemon=True)
        t1.start()
        t2 = threading.Thread(target=self._announce_loop,
                              name=f"announcer-{self.node_id}", daemon=True)
        t2.start()
        self._threads = [t1, t2]
        return self

    def announce_once(self) -> None:
        body = json.dumps({"nodeId": self.node_id, "uri": self.uri}).encode()
        req = Request(f"{self.coordinator_uri}/v1/announce", data=body,
                      headers={"Content-Type": "application/json"})
        with urlopen(req, timeout=5):
            pass

    def _announce_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.announce_once()
            except Exception:
                pass                      # coordinator down: keep trying
            self._stop.wait(self.announce_interval_s)

    def stop(self) -> None:
        self._stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()
