"""Coordinator HTTP server — the statement protocol front end.

Reference: the queued->executing REST protocol
(dispatcher/QueuedStatementResource.java:109 `POST /v1/statement`,
server/protocol/ExecutingStatementResource.java:67 with `nextUri` paging),
DispatchManager.createQuery (dispatcher/DispatchManager.java:175), query
info at /v1/query/{id} (server/QueryResource.java), node inventory
(node/CoordinatorNodeManager.java) fed by worker announcements
(node/Announcer.java), and /v1/status liveness used by the heartbeat
failure detector (failuredetector/HeartbeatFailureDetector.java:344).

stdlib http.server only — the protocol layer is host-side control plane;
the TPU data plane stays inside the jitted stage programs.

Observability: routes live in the ROUTES table (server/routes.py) so every
request lands in trino_tpu_http_requests_total; /v1/metrics serves the
process registry in Prometheus text; `enable_tracing` sessions run each
query under a propagating tracer whose stitched trace (coordinator +
worker spans) is served at GET /v1/query/{id}/trace.
"""

from __future__ import annotations

import json
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from ..exec.session import Session
from .routes import STAR, dispatch, register_routes
from .statemachine import QueryStateMachine, QueryTracker, TrackedQuery

PAGE_ROWS = 1000          # rows per protocol page (target-result-size analog)

SERVER_NAME = "coordinator"

# (METHOD, pattern, handler method, needs_auth) — see server/routes.py.
# /v1/info, /v1/status and /v1/metrics stay open (liveness + scrape
# surface, no query data); everything that exposes query text/results
# authenticates.
ROUTES = (
    ("POST", ("v1", "statement"), "_post_statement", True),
    # worker registration is cluster-internal: guarded by the shared
    # secret (TRINO_TPU_INTERNAL_SECRET) so a rogue process with network
    # reach cannot join the cluster and absorb splits
    ("POST", ("v1", "announce"), "_post_announce", "internal"),
    # buffered terminal-status push from workers: tasks that finished
    # while the coordinator was unreachable re-deliver here after the
    # next successful announce (possibly to a promoted standby)
    ("POST", ("v1", "task-status"), "_post_task_status", "internal"),
    ("GET", ("v1", "info"), "_get_info", False),
    # coordinator role probe (PRIMARY | PASSIVE | RECONCILING) — the
    # health/ready surface a standby serves while tailing the ledger
    ("GET", ("v1", "info", "state"), "_get_info_state", False),
    # admin promotion (the coordinator mirror of the worker drain
    # route): PUT {"state": "PRIMARY"} promotes a standby
    ("PUT", ("v1", "info", "state"), "_put_info_state", "internal"),
    ("GET", ("v1", "status"), "_get_status", False),
    ("GET", ("v1", "metrics"), "_get_metrics", False),
    ("GET", ("v1", "jit"), "_get_jit", False),
    # warm-manifest for joining workers (exec/prewarm.py): top
    # historical fingerprints + the canonical shape lattice. Internal:
    # it exposes query text
    ("GET", ("v1", "prewarm"), "_get_prewarm", "internal"),
    ("GET", ("v1", "spooled", "segments", STAR), "_get_segment", True),
    ("GET", ("v1", "resourceGroup"), "_get_resource_group", True),
    ("GET", ("v1", "memory"), "_get_memory", True),
    ("GET", ("v1", "node"), "_get_nodes", True),
    ("GET", ("v1", "query"), "_get_queries", True),
    ("GET", ("v1", "query", STAR), "_get_query", True),
    ("GET", ("v1", "query", STAR, "trace"), "_get_query_trace", True),
    ("GET", ("v1", "query", STAR, "timeline"), "_get_query_timeline",
     True),
    # the coordinator's own flight-recorder ring — same contract the
    # workers serve, so the federation scrape path is uniform. Internal:
    # metric keys carry tenant/route labels a stranger shouldn't map
    ("GET", ("v1", "telemetry"), "_get_telemetry", "internal"),
    ("GET", ("v1", "statement", "executing", STAR), "_get_executing",
     True),
    ("GET", ("v1", "statement", "executing", STAR, STAR),
     "_get_executing", True),
    ("DELETE", ("v1", "spooled", "segments", STAR), "_delete_segment",
     True),
    ("DELETE", ("v1", "statement", "executing", STAR),
     "_delete_executing", True),
    ("DELETE", ("v1", "statement", "executing", STAR, STAR),
     "_delete_executing", True),
)

register_routes(SERVER_NAME, ROUTES)


class ClusterHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a serving-grade accept backlog: the
    stdlib default (request_queue_size=5) resets connections under a
    thundering herd of concurrent clients — the exact load the serving
    layer exists to absorb."""
    request_queue_size = 256


class QueryDeclinedError(RuntimeError):
    """Deterministic user-configuration decline (require_distributed on a
    shape the cluster can't take) — never retried."""


def _is_retryable(e: Exception) -> bool:
    """User errors (bad SQL, missing columns) never retry; runtime/injected
    failures do — the reference draws the same line via error categories
    (USER_ERROR vs INTERNAL_ERROR/EXTERNAL). Memory kills are user
    errors too: retrying an OOM reproduces it. Deadline expiry and
    termination never retry (a rerun restarts the clock the user
    bounded), and an exhausted task-amplification budget means retrying
    is exactly what the budget forbade."""
    from ..exec.executor import QueryDeadlineError, QueryTerminatedError
    from ..exec.memory import ExceededMemoryLimitError
    from ..planner.analyzer import AnalysisError
    from ..sql.tokenizer import SqlSyntaxError
    from .scheduler import RetryBudgetExhaustedError
    return not isinstance(e, (AnalysisError, SqlSyntaxError,
                              AssertionError, QueryDeclinedError,
                              ExceededMemoryLimitError,
                              QueryDeadlineError, QueryTerminatedError,
                              RetryBudgetExhaustedError))


class RegisteredNode:
    """One announced worker (node/InternalNodeManager inventory entry)."""

    def __init__(self, node_id: str, uri: str):
        self.node_id = node_id
        self.uri = uri
        self.last_announce = time.time()
        # lifecycle: ACTIVE | DRAINING | DRAINED | FAILED (a LEFT
        # announce removes the entry from the inventory entirely)
        self.state = "ACTIVE"
        # last heartbeat-reported memory pool snapshot (cluster
        # arbitration input; scheduler placement prefers low-memory nodes)
        self.memory: Optional[dict] = None
        # last heartbeat-reported device/HBM allocator stats
        # (system.runtime.nodes surface)
        self.device: Optional[dict] = None
        # estimated clock skew (worker clock minus coordinator clock),
        # refreshed from the `now` stamp each announce carries; adopted
        # worker spans are rebased by it so stitched-trace intervals
        # cannot go negative under skewed wall clocks
        self.clock_offset: float = 0.0
        # live task inventory from the last announce ([{taskId, state}])
        # — a promoted coordinator reconciles the ledger against this
        # before deciding re-attach vs re-execute
        self.tasks: Optional[list] = None


class Dispatcher:
    """Admission + async execution (DispatchManager + SqlQueryManager).

    `max_concurrency` plays the resource-group concurrency limit
    (InternalResourceGroup.java hardConcurrencyLimit); queries past it sit
    QUEUED. Execution itself is serialized per engine session via an
    executor lock (the single-process mesh is one 'cluster').
    """

    def __init__(self, session: Session, tracker: QueryTracker,
                 max_concurrency: int = 4, retry_policy: str = "NONE",
                 max_retries: int = 3):
        self.session = session
        self.tracker = tracker
        self.pool = ThreadPoolExecutor(max_workers=max_concurrency,
                                       thread_name_prefix="dispatch")
        # RLock: traced attempts hold it across the whole attempt while
        # the serving layer re-acquires for its device-path execution
        self.exec_lock = threading.RLock()
        self.failure_injector = None      # FailureInjector (tests/ops)
        # retry-policy QUERY (admin/fault-tolerant-execution.md): rerun the
        # whole query on failure; deterministic kernels + the dedup of
        # serving only the final attempt's result give identical output
        # (DeduplicatingDirectExchangeBuffer.java:87's role)
        self.retry_policy = retry_policy  # NONE | QUERY
        self.max_retries = max_retries
        self.scheduler = None             # StageScheduler (cluster mode)
        # ClusterMemoryManager back-ref (set by CoordinatorState): the
        # load-shed admission gate reads its pressure snapshot
        self.memory_manager = None
        # lazy deadline-enforcer sweep: started by the first admission
        # that carries a run/queued deadline, so deadline-free sessions
        # never pay for the thread
        self._enforcer: Optional[threading.Thread] = None
        self._enforcer_lock = threading.Lock()
        # durable query ledger (server/ledger.py): set by
        # CoordinatorState when a ledger path is configured. None keeps
        # the pre-failover behavior bit-for-bit (no appends, no fsyncs).
        self.ledger = None
        from ..events import EventListenerManager
        self.event_listeners = EventListenerManager()
        from .resourcegroups import (ResourceGroupConfig,
                                     ResourceGroupManager)
        self.resource_groups = ResourceGroupManager(
            ResourceGroupConfig("root",
                                hard_concurrency_limit=max_concurrency))
        # security hooks (AccessControlManager's seat): authn gates the
        # HTTP intake, authz runs at dispatch with resolved table refs
        from .security import AllowAllAccessControl
        self.authenticator = None            # None = open cluster
        self.access_control = AllowAllAccessControl()
        # high-concurrency serving layer (server/serving.py): plan +
        # result caches, CPU/TPU cost routing, micro-batched point
        # queries. Host-routed and cache-served statements bypass the
        # exec lock entirely; device executions still take it inside
        # ServingLayer.run_routed.
        from .serving import ServingLayer
        self.serving = ServingLayer(session, self.exec_lock)

    def submit(self, sql: str, user: str,
               traceparent: Optional[str] = None) -> TrackedQuery:
        qid = self.tracker.next_query_id()
        tq = TrackedQuery(qid, sql, user, QueryStateMachine(qid),
                          traceparent=traceparent)
        return self._admit(tq)

    def resume(self, q: dict, mode: str) -> TrackedQuery:
        """Re-admit a non-terminal query reconstructed from the ledger,
        under its ORIGINAL query id — the client's nextUri keeps working
        against the resumed execution. `mode` is the resumption-mode
        label: replayed (pre-execution), reattached (spooled output
        survives), reexecuted (re-run; writes dedup via the commit
        journal)."""
        from ..metrics import QUERIES_RESUMED
        qid = q["query_id"]
        sm = QueryStateMachine(qid)
        sm.adopt_times(q.get("state_times") or {})
        tq = TrackedQuery(qid, q.get("sql") or "", q.get("user")
                          or "anonymous", sm)
        tq.resumed = mode
        QUERIES_RESUMED.inc(mode=mode)
        return self._admit(tq, resumed=True)

    def restore_terminal(self, q: dict) -> TrackedQuery:
        """Register a query the ledger shows as already terminal —
        byte-for-byte state reconstruction (state, stamps, error
        taxonomy, row/elapsed stats), with no listeners and no
        re-execution: it already completed and already counted. A
        restored FINISHED query carries result=None; the executing
        route lazily re-executes on the first data poll."""
        qid = q["query_id"]
        sm = QueryStateMachine.restored(
            qid, q["terminal"], q.get("state_times"),
            error=q.get("error"),
            error_name=q.get("error_name") or "GENERIC_INTERNAL_ERROR",
            error_code=q.get("error_code") or 1)
        tq = TrackedQuery(qid, q.get("sql") or "", q.get("user")
                          or "anonymous", sm)
        tq.tenant = q.get("tenant") or \
            self.resource_groups.tenant_of(tq.session_user)
        tq.rows_returned = q.get("rows") or 0
        tq.elapsed_s = q.get("elapsed_s") or 0.0
        tq.resumed = "restored"
        self.tracker.register(tq)
        return tq

    def _admit(self, tq: TrackedQuery,
               resumed: bool = False) -> TrackedQuery:
        # tenant = the principal's resource-group leaf; labels metrics,
        # history records, and audit events for per-tenant isolation
        tq.tenant = self.resource_groups.tenant_of(tq.session_user)
        self.tracker.register(tq)
        self.event_listeners.query_created(tq)
        led = self.ledger
        if led is not None:
            if not resumed:
                # the admission record is durable BEFORE the client sees
                # a query id: any id a client holds survives replay
                from .history import plan_fingerprint
                led.admit(tq.query_id, tq.sql, tq.session_user,
                          tq.tenant, plan_fingerprint(tq.sql),
                          getattr(self.session, "properties", {}))
            sm_led = tq.state_machine

            def on_ledger(state, _tq=tq, _sm=sm_led):
                ts = _sm.state_times.get(state, time.time())
                if state in ("FINISHED", "FAILED", "CANCELED"):
                    led.terminal(
                        _tq.query_id, state, ts, error=_sm.error,
                        error_name=_sm.error_name,
                        error_code=_sm.error_code,
                        rows=_tq.rows_returned, elapsed_s=_tq.elapsed_s,
                        catalog_version=getattr(self.session.catalog,
                                                "version", 0))
                else:
                    led.state(_tq.query_id, state, ts)

            sm_led.add_listener(on_ledger)

        def on_terminal(state):
            if state in ("FINISHED", "FAILED", "CANCELED"):
                from ..metrics import (QUERIES, QUERY_SECONDS,
                                       TENANT_QUERIES,
                                       TENANT_QUERY_SECONDS)
                QUERIES.inc(state=state)
                TENANT_QUERIES.inc(tenant=tq.tenant)
                QUERY_SECONDS.observe(tq.elapsed_s)
                TENANT_QUERY_SECONDS.observe(tq.elapsed_s,
                                             tenant=tq.tenant)
                # critical-path attribution BEFORE the completion event
                # fires, so listeners (history store, event sinks) see
                # the dominant phase
                try:
                    from ..metrics import (CRITICAL_PATH_SECONDS,
                                           TIMELINE_QUERIES)
                    from .timeline import build_timeline
                    tq.timeline = build_timeline(tq)
                    TIMELINE_QUERIES.inc()
                    for p, v in tq.timeline["phases"].items():
                        if v > 0:
                            CRITICAL_PATH_SECONDS.inc(v, phase=p)
                except Exception:  # noqa: BLE001 — attribution never
                    pass           # fails a query
                self.event_listeners.query_completed(tq)

        tq.state_machine.add_listener(on_terminal)
        # absolute wall deadlines stamped AT ADMISSION: every downstream
        # hop (scheduler dispatch, worker split loops, exchange drains,
        # retry backoffs) budgets against these, and the enforcer sweep
        # is the backstop for work stuck where no cooperative check runs
        props = getattr(self.session, "properties", {})
        now = time.time()
        max_run = float(props.get("query_max_run_time_s", 0) or 0)
        if max_run > 0 and tq.deadline is None:
            tq.deadline = now + max_run
        max_queued = float(props.get("query_max_queued_time_s", 0) or 0)
        if max_queued > 0 and tq.queued_deadline is None:
            tq.queued_deadline = now + max_queued
        if tq.deadline is not None or tq.queued_deadline is not None:
            self._ensure_enforcer()
        from ..metrics import QUERIES_REJECTED
        from .resourcegroups import QueryQueueFullError
        if self._should_shed(tq):
            QUERIES_REJECTED.inc(reason="load_shed")
            tq.state_machine.fail(
                "Query rejected: coordinator overloaded (load shed; "
                f"tenant {tq.tenant!r} is above its fair share) — "
                "retry when load drops",
                error_name=QueryQueueFullError.error_name,
                error_code=QueryQueueFullError.error_code)
            return tq
        try:
            self.resource_groups.submit(
                tq.session_user,
                lambda: self.pool.submit(self._run_admitted, tq),
                is_dead=tq.state_machine.is_done)
        except QueryQueueFullError as e:
            QUERIES_REJECTED.inc(reason="queue_full")
            tq.state_machine.fail(str(e), error_name=e.error_name,
                                  error_code=e.error_code)
        return tq

    # ---- termination / deadlines / overload ------------------------------

    def terminate(self, query_id: str, reason: str = "user",
                  message: Optional[str] = None) -> bool:
        """The single cancellation path: user DELETE, deadline expiry,
        the low-memory killer and the stuck-diagnoser all converge here.
        Moves the state machine to the terminal state the reason's
        taxonomy demands, interrupts a locally-executing attempt at its
        next cooperative check point, fans best-effort task DELETEs out
        to every live remote task (hedge twins included), and prunes
        dead queue entries so a terminated queued query never runs.
        Returns True when this call performed the termination."""
        tq = self.tracker.get(query_id)
        if tq is None:
            return False
        sm = tq.state_machine
        if sm.is_done():
            return False
        tq.terminate_reason = reason
        from ..metrics import (CANCEL_PROPAGATIONS,
                               QUERIES_DEADLINE_EXCEEDED)
        if reason == "user":
            did = sm.cancel()
        elif reason == "deadline":
            did = sm.fail(
                message or "Query exceeded the maximum run time "
                           "(query_max_run_time_s)",
                error_name="QUERY_EXCEEDED_RUN_TIME", error_code=4)
            if did:
                QUERIES_DEADLINE_EXCEEDED.inc()
        elif reason == "queued_deadline":
            from .resourcegroups import QueryQueuedTimeExceededError
            did = sm.fail(
                message or "Query exceeded the maximum queued time "
                           "(query_max_queued_time_s) — retry when "
                           "load drops",
                error_name=QueryQueuedTimeExceededError.error_name,
                error_code=QueryQueuedTimeExceededError.error_code)
            if did:
                QUERIES_DEADLINE_EXCEEDED.inc()
        elif reason == "oom":
            from ..exec.memory import ExceededMemoryLimitError
            did = sm.fail(
                message or "Query killed by the cluster low-memory "
                           "killer",
                error_name=ExceededMemoryLimitError.error_name,
                error_code=ExceededMemoryLimitError.error_code)
        else:                       # "stuck" and future reasons
            did = sm.fail(message or f"Query terminated ({reason})")
        if not did:
            return False            # lost the race to another terminator
        CANCEL_PROPAGATIONS.inc(reason=reason)
        # a locally-executing attempt holds the exec lock: request a
        # cooperative cancel so the next chunk/partition/prefetch
        # boundary raises and frees the lock within a bounded grace
        ex = getattr(self.session, "executor", None)
        pool = getattr(ex, "pool", None)
        if ex is not None and pool is not None and \
                getattr(pool, "_current_tag", "") == query_id:
            ex.request_cancel(
                f"query {query_id} terminated ({reason})")
        # fan out best-effort DELETEs to every live remote task — the
        # worker side frees buffers, pool reservations and wakes its
        # backpressure waiters
        if self.scheduler is not None:
            try:
                self.scheduler.cancel_query_tasks(query_id)
            except Exception:  # noqa: BLE001 — fan-out is best-effort
                pass
        try:
            self.resource_groups.prune_dead()
        except Exception:  # noqa: BLE001
            pass
        return True

    def _should_shed(self, tq: TrackedQuery) -> bool:
        """Overload admission gate: once cluster-wide queue depth (or
        reported memory pressure) crosses the shed threshold, new work
        from tenants already holding the most in-flight device work —
        the ones with the least remaining fair-share claim — is rejected
        with a retryable QUERY_QUEUE_FULL instead of queued into a
        pile-up. Disabled unless TRINO_TPU_LOAD_SHED_QUEUE_DEPTH is
        set."""
        import os
        try:
            depth_cap = int(os.environ.get(
                "TRINO_TPU_LOAD_SHED_QUEUE_DEPTH", "0"))
        except ValueError:
            depth_cap = 0
        if depth_cap <= 0:
            return False
        overloaded = self.resource_groups.total_queued() >= depth_cap
        mm = self.memory_manager
        if not overloaded and mm is not None and \
                mm.cluster_limit_bytes is not None:
            reserved = sum(m.get("reserved", 0)
                           for m in mm.last_snapshot.values())
            overloaded = reserved >= mm.cluster_limit_bytes
        if not overloaded:
            return False
        fair = getattr(getattr(self, "serving", None), "fair_share",
                       None)
        infl = fair.inflight() if fair is not None else {}
        mine = infl.get(tq.tenant, 0)
        # the least-loaded tenant keeps admission even under overload —
        # shedding it would starve exactly the principal fair share
        # exists to protect
        return bool(infl) and mine > min(infl.values())

    def _ensure_enforcer(self) -> None:
        if self._enforcer is not None:
            return
        with self._enforcer_lock:
            if self._enforcer is not None:
                return
            t = threading.Thread(target=self._deadline_loop,
                                 name="deadline-enforcer", daemon=True)
            self._enforcer = t
            t.start()

    def _deadline_loop(self) -> None:
        while True:
            time.sleep(0.1)
            try:
                self.enforce_deadlines()
            except Exception:  # noqa: BLE001 — the sweep must survive
                pass

    def enforce_deadlines(self) -> int:
        """One enforcement sweep over every live query: expire run
        deadlines (any state) and queued-time deadlines (QUEUED only),
        then prune the dead queue entries. Returns the number of queries
        terminated — exposed so tests and ops can tick synchronously."""
        n = 0
        now = time.time()
        for tq in self.tracker.all():
            sm = tq.state_machine
            if sm.is_done():
                continue
            if tq.deadline is not None and now >= tq.deadline:
                if self.terminate(tq.query_id, reason="deadline"):
                    n += 1
            elif tq.queued_deadline is not None and \
                    now >= tq.queued_deadline and sm.state == "QUEUED":
                if self.terminate(tq.query_id,
                                  reason="queued_deadline"):
                    n += 1
        return n

    def _run_admitted(self, tq: TrackedQuery) -> None:
        group_path = self.resource_groups.select(tq.session_user).path
        try:
            self._run(tq)
        finally:
            nxt = self.resource_groups.finished(group_path)
            if nxt is not None:
                nxt()

    def _run(self, tq: TrackedQuery) -> None:
        sm = tq.state_machine
        attempts = 1 + (self.max_retries
                        if self.retry_policy == "QUERY" else 0)
        if not sm.transition("PLANNING"):
            return                        # canceled while queued
        # authorization BEFORE any execution, with resolved table refs
        # (DispatchManager.createQueryInternal's access-check step)
        from .security import AccessDeniedError, check_statement_access
        try:
            check_statement_access(self.access_control, self.session,
                                   tq.sql, tq.session_user)
        except AccessDeniedError as e:
            sm.fail(str(e))
            return
        except Exception:     # noqa: BLE001 — malformed SQL fails later
            pass              # with its real parse/analysis error
        # per-query tracer (enable_tracing sessions): adopts the client's
        # traceparent when present so the query trace continues the
        # caller's trace; exported to tq.trace at the end either way
        tracer = None
        if self.session.properties.get("enable_tracing"):
            from ..utils.tracing import Tracer
            tracer = Tracer.from_traceparent(tq.traceparent,
                                             service="coordinator")
            tq.tracer = tracer
        last_error: Optional[str] = None
        last_exc: Optional[Exception] = None
        # backoff between QUERY-retry attempts (shared RetryPolicy,
        # decorrelated jitter): failed queries re-admitting immediately
        # compound whatever overload/flap failed them the first time
        from .retrypolicy import RetryPolicy
        retry_waits = RetryPolicy(base_delay_s=0.05, max_delay_s=1.0,
                                  max_attempts=attempts).delays()
        try:
            for attempt in range(attempts):
                if sm.is_done():
                    return
                if attempt > 0:
                    from ..metrics import RETRY_ATTEMPTS
                    RETRY_ATTEMPTS.inc(component="dispatch")
                    time.sleep(next(retry_waits, 1.0))
                try:
                    if attempt > 0:
                        tq.retries = attempt
                    if self.failure_injector is not None:
                        self.failure_injector.maybe_fail("DISPATCH",
                                                         tq.sql)
                    if tracer is not None:
                        # tracing swaps the SHARED session tracer, so a
                        # traced attempt serializes end-to-end like the
                        # pre-serving coordinator did
                        with self.exec_lock:
                            if sm.is_done():
                                return
                            sm.transition("RUNNING")
                            if self.failure_injector is not None:
                                self.failure_injector.maybe_fail(
                                    "EXECUTION", tq.sql)
                            saved_tracer = self.session.tracer
                            self.session.tracer = tracer
                            try:
                                with tracer.span("query",
                                                 queryId=tq.query_id,
                                                 user=tq.session_user,
                                                 attempt=attempt):
                                    self._execute_attempt(tq)
                            finally:
                                self.session.tracer = saved_tracer
                    else:
                        # untraced path: the exec lock moves INSIDE the
                        # attempt (serving layer) so host-routed and
                        # cache-served queries run concurrently while
                        # device executions still serialize
                        if sm.is_done():
                            return
                        sm.transition("RUNNING")
                        if self.failure_injector is not None:
                            self.failure_injector.maybe_fail(
                                "EXECUTION", tq.sql)
                        # restore the session tracer afterwards even
                        # untraced: a SET SESSION enable_tracing=true
                        # must not leave a live session-level tracer
                        # soaking up every later query's spans (the
                        # per-query tracer swap above is the only way
                        # spans reach a protocol query)
                        saved_tracer = self.session.tracer
                        try:
                            self._execute_attempt(tq)
                        finally:
                            self.session.tracer = saved_tracer
                    sm.transition("FINISHING")
                    sm.transition("FINISHED")
                    return
                except Exception as e:  # noqa: BLE001 — retry boundary
                    last_error = f"{type(e).__name__}: {e}"
                    last_exc = e
                    tq.plan_text = traceback.format_exc()
                    if not _is_retryable(e):
                        break
            # user-error taxonomy: memory kills fail with their own
            # errorName (QUERY_EXCEEDED_MEMORY) instead of the generic
            # internal-failure envelope
            sm.fail(last_error or "query failed",
                    error_name=getattr(last_exc, "error_name",
                                       "GENERIC_INTERNAL_ERROR")
                    if last_error else "GENERIC_INTERNAL_ERROR",
                    error_code=getattr(last_exc, "error_code", 1)
                    if last_error else 1)
        finally:
            if tracer is not None:
                tq.trace = tracer.export()

    def _execute_attempt(self, tq: TrackedQuery) -> None:
        """One execution attempt under the exec lock: cluster path first,
        local fallback second (Trino's coordinator-only path)."""
        t0 = time.monotonic()
        result = None
        # tag the pool ledger with the query id so the LowMemoryKiller's
        # total-reservation-dominant policy can attribute bytes
        pool = getattr(getattr(self.session, "executor", None),
                       "pool", None)
        if pool is not None:
            pool.set_current_tag(tq.query_id)
        try:
            self._execute_attempt_inner(tq, t0)
        finally:
            if pool is not None:
                pool.set_current_tag("")

    def _spill_counter(self) -> int:
        """Cumulative spill-tier activations of the session executor —
        diffed around an attempt so the completion event (and history
        store) carry a per-query spill count."""
        st = getattr(getattr(self.session, "executor", None), "stats",
                     None)
        if st is None:
            return 0
        return (st.spilled_joins + st.spilled_aggregations +
                st.spilled_sorts)

    def _committed_write_result(self, tq: TrackedQuery):
        """Exactly-once guard for resumed writes: if a pre-crash attempt
        of this very query id already published parts (the commit
        journal's INTENT was durable), return its committed result
        instead of re-executing — re-running a committed CTAS locally
        would double-write or trip on the existing table."""
        import os as _os
        from ..sql import ast_nodes as A
        from ..sql.parser import parse
        from . import writeprotocol as wp
        try:
            stmt = parse(tq.sql)
        except Exception:  # noqa: BLE001 — not parseable here: let the
            return None    # normal path raise the canonical error
        if not isinstance(stmt, (A.CreateTable, A.InsertInto)) or \
                getattr(stmt, "query", None) is None:
            return None
        try:
            cat, sch, tbl = self.session.resolve_table(stmt.table)
            conn = self.session.catalog.connector(cat)
        except Exception:  # noqa: BLE001
            return None
        if not getattr(conn, "supports_staged_writes", False):
            return None
        table_dir = _os.path.abspath(conn._table_dir(sch, tbl))
        already = wp.published_rows_for(table_dir, tq.query_id)
        if already is None:
            return None
        wp.recover_table_dir(table_dir)
        conn._cache.pop((sch, tbl), None)
        self.session.catalog.bump_version()
        self.session.executor.invalidate_scan_cache()
        from ..exec.session import QueryResult
        return QueryResult(["rows"], [(already,)], 0.0)

    def _execute_attempt_inner(self, tq: TrackedQuery, t0: float) -> None:
        result = None
        spills0 = self._spill_counter()
        if getattr(tq, "resumed", None):
            result = self._committed_write_result(tq)
            if result is not None:
                tq.elapsed_s = time.monotonic() - t0
                tq.result = result
                tq.rows_returned = len(result.rows)
                return
            result = None
        serving = getattr(self, "serving", None)
        if serving is not None:
            # FINISHED page straight from the result cache: no lock, no
            # planning, no scheduler round trip
            result = serving.lookup_cached(tq)
        no_workers = self.scheduler is not None and \
            not self.scheduler.state.active_nodes()
        if result is None and self.scheduler is not None and no_workers:
            # no cluster: skip the exec-lock round trip entirely so
            # host-routed queries stay lock-free on a plain coordinator
            tq.fallback_reason = "no active workers"
        elif result is None and self.scheduler is not None:
            # cluster path: fragment + dispatch to workers; None = not
            # eligible (coordinator executes locally)
            from .scheduler import TaskFailedError
            # distributed execution occupies the exec lock like a device
            # run: register it with the tenant fair-share tracker so a
            # scan-heavy tenant's cluster queries count as device
            # contention for everyone else's routing decisions
            fair = getattr(serving, "fair_share", None)
            if fair is not None:
                fair.device_begin(getattr(tq, "tenant", "default"))
            try:
                with self.exec_lock:
                    result = self.scheduler.execute(tq.sql,
                                                    query_id=tq.query_id)
                tq.fallback_reason = self.scheduler.fallback_reason \
                    if result is None else None
            except TaskFailedError as te:
                from .scheduler import RetryBudgetExhaustedError
                if isinstance(te, RetryBudgetExhaustedError):
                    raise    # the budget forbade more attempts: fail,
                             # don't silently degrade to local re-run
                result = None   # degrade to local execution
                tq.fallback_reason = f"task failure: {te}"
            finally:
                if fair is not None:
                    fair.device_end(getattr(tq, "tenant", "default"))
            tq.distributed = result is not None
            if tq.distributed:
                # per-query stage/task rollup for events +
                # system.runtime tables + /v1/query info
                tq.stage_stats = getattr(self.scheduler,
                                         "last_query", None)
        if result is None and getattr(
                self.session, "properties", {}).get(
                "require_distributed") and \
                tq.fallback_reason != "coordinator-only statement":
            # SET SESSION/SHOW and friends never distribute by design —
            # erroring on them would brick the very statement that turns
            # the property off
            raise QueryDeclinedError(
                "require_distributed: cluster declined the "
                f"query ({tq.fallback_reason})")
        if result is None:
            if serving is not None:
                # local path through the serving layer: plan cache,
                # micro-batching, CPU/TPU routing (device executions
                # take the exec lock inside)
                result = serving.execute_local(tq)
            else:
                with self.exec_lock:
                    result = self.session.execute(tq.sql)
        tq.elapsed_s = time.monotonic() - t0
        tq.result = result
        tq.rows_returned = len(result.rows)
        tq.spills = max(0, self._spill_counter() - spills0)


class CoordinatorState:
    def __init__(self, session: Session, max_concurrency: int = 4,
                 retry_policy: str = "NONE",
                 telemetry_interval_s: Optional[float] = None,
                 ledger_path: Optional[str] = None,
                 node_id: str = "coordinator", role: str = "primary",
                 peer_uri: Optional[str] = None,
                 spool_root: Optional[str] = None):
        import os
        self.session = session
        self.tracker = QueryTracker()
        self.dispatcher = Dispatcher(session, self.tracker, max_concurrency,
                                     retry_policy)
        self.nodes: Dict[str, RegisteredNode] = {}
        self.nodes_lock = threading.Lock()
        self.failure_detector = None   # set by HeartbeatFailureDetector
        self.started_at = time.time()
        # ---- coordinator crash recovery (server/ledger.py) ----
        self.node_id = node_id
        self.uri: Optional[str] = None      # set by CoordinatorServer
        self.peer_uri = peer_uri
        self.standbys: Dict[str, float] = {}   # standby uri -> last seen
        self.task_reports: Dict[str, dict] = {}  # worker terminal push
        self._promote_lock = threading.Lock()
        self._reexec_lock = threading.Lock()
        self._reexec_started: set = set()
        ledger_path = ledger_path or os.environ.get(
            "TRINO_TPU_LEDGER_PATH")
        self.ledger = None
        if ledger_path:
            from .ledger import QueryLedger
            self.ledger = QueryLedger(ledger_path, node_id=node_id)
        self.dispatcher.ledger = self.ledger
        # PRIMARY serves traffic; PASSIVE tails the ledger (a standby,
        # or a fenced ex-primary); RECONCILING is the promotion window
        if role == "standby":
            self.role = "PASSIVE"
        elif self.ledger is not None:
            epoch, owner = self.ledger.read_epoch()
            if epoch > 0 and owner != node_id:
                # another instance holds the ledger epoch: a resurrected
                # old primary must NOT split-brain — boot fenced
                self.role = "PASSIVE"
            else:
                self.role = "PRIMARY"
                self.ledger.claim_epoch()
        else:
            self.role = "PRIMARY"
        from .scheduler import StageScheduler
        # a durable spool root survives coordinator restarts: resumed
        # queries re-attach to completed task output instead of
        # re-running it (exchange_spool.py's durability contract)
        spool_root = spool_root or os.environ.get("TRINO_TPU_SPOOL_ROOT")
        spool = None
        if spool_root:
            from .exchange_spool import ExchangeSpool
            spool = ExchangeSpool(root=spool_root)
        self.scheduler = StageScheduler(self, session, spool=spool)
        self.dispatcher.scheduler = self.scheduler
        from .spooling import SpoolingManager
        self.spooling = SpoolingManager()
        # cluster memory arbitration: pooled accounting over worker
        # heartbeat reports + the low-memory killer; start() its loop (or
        # tick() on demand) to enforce a cluster limit
        from .memorymanager import ClusterMemoryManager
        self.memory_manager = ClusterMemoryManager(self)
        # the dispatcher's load-shed admission gate reads the manager's
        # last pressure snapshot
        self.dispatcher.memory_manager = self.memory_manager
        # query history + regression detection (server/history.py): fed
        # from QueryCompletedEvent, flushed-to on tracker eviction, and
        # served as system.runtime.query_history
        from .history import HistoryEventListener, QueryHistoryStore
        self.history = QueryHistoryStore()
        self.dispatcher.event_listeners.register(
            HistoryEventListener(self.history))
        self.tracker.on_evict = self.history.record_tracked
        # the cost router's history baseline input + EXPLAIN's routing
        # annotation both read per-fingerprint medians from this store
        self.dispatcher.serving.history = self.history
        session.history_store = self.history
        # cold-start elimination (exec/prewarm.py): AOT-warm the top
        # historical fingerprints at startup and feed the router's
        # compile-aware cold signal. Off unless TRINO_TPU_PREWARM is
        # set — disabled, serving/routing behave exactly as before.
        from ..exec.prewarm import PrewarmEngine
        self.prewarm = PrewarmEngine(session, history=self.history,
                                     exec_lock=self.dispatcher.exec_lock)
        self.dispatcher.serving.prewarm = self.prewarm
        self.prewarm.maybe_start()
        # the timeline analyzer's EXPLAIN ANALYZE hook: the scheduler
        # looks up the running TrackedQuery (state-machine stamps) to
        # print queued time in the critical-path breakdown line
        self.scheduler.tracked_lookup = self.tracker.get
        # live query observability (server/livestats.py): the fold of
        # heartbeat-streamed worker TaskStats into live per-stage
        # rollups, split-weighted progress, stuck/skew diagnosis and
        # per-node utilization. Pure fold state — only mutated when a
        # heartbeat arrives or the scheduler registers a task launch,
        # so the heartbeat-off path costs nothing.
        from .livestats import LiveStatsStore
        self.livestats = LiveStatsStore(tracked_lookup=self.tracker.get)
        self.scheduler.livestats = self.livestats
        # stuck-query escalation routes through the dispatcher's single
        # termination path (off unless TRINO_TPU_STUCK_ESCALATE_FOLDS)
        self.livestats.terminate = self.dispatcher.terminate
        # cluster flight recorder (server/telemetry.py): the local ring
        # plus coordinator-scrape federation of worker rings. The sampler
        # thread only runs when an interval is configured
        # (TRINO_TPU_TELEMETRY_INTERVAL_S or the constructor arg); the
        # default path creates the recorder but no thread and no samples.
        from .telemetry import ClusterTelemetry, FlightRecorder
        self.telemetry = ClusterTelemetry(
            FlightRecorder("coordinator",
                           interval_s=telemetry_interval_s),
            lambda: [(n.node_id, n.uri) for n in self.active_nodes()])
        # system.runtime.{queries,nodes,tasks,operator_stats,jit_cache,
        # query_history,query_timeline,metrics_history} backed by this
        # coordinator's state
        from .system_connector import SystemConnector
        session.catalog.register("system", SystemConnector(self))
        # boot-time recovery: a primary with a ledger replays it before
        # the HTTP server ever binds — queued/running queries resume
        # under their original ids, terminal ones are restored
        if self.role == "PRIMARY" and self.ledger is not None:
            self._replay_ledger()

    # ---- crash recovery / failover ---------------------------------------

    def accepting(self) -> bool:
        """May this coordinator serve statement traffic? PRIMARY only —
        and a primary that lost the ledger epoch (a newer promotion
        fenced it) demotes itself here, on the serving path, before it
        can hand out state a newer primary owns."""
        if self.role != "PRIMARY":
            return False
        if self.ledger is not None and not self.ledger.owns_epoch():
            self.role = "PASSIVE"
            return False
        return True

    def coordinator_uris(self) -> List[str]:
        """The failover address list carried in announce responses:
        this coordinator first, then every fresh standby."""
        uris = [self.uri] if self.uri else []
        cutoff = time.time() - 10.0
        for u, seen in sorted(self.standbys.items()):
            if seen >= cutoff and u not in uris:
                uris.append(u)
        if self.peer_uri and self.peer_uri not in uris:
            uris.append(self.peer_uri)
        return uris

    def promote(self, reason: str = "admin",
                wait_workers_s: float = 1.5) -> dict:
        """Standby -> primary: claim the ledger epoch (fencing every
        previous holder), wait briefly for workers to re-announce,
        reconcile ledger state against live task inventories, sweep
        orphaned spool/staging artifacts, then resume every
        non-terminal query and start accepting traffic."""
        from ..metrics import COORDINATOR_FAILOVERS
        with self._promote_lock:
            if self.role == "PRIMARY":
                return {"role": self.role, "promoted": False}
            self.role = "RECONCILING"
            epoch = 0
            if self.ledger is not None:
                epoch = self.ledger.claim_epoch()
            # workers re-announce to the standby address they learned
            # from announce responses; give the first wave a moment so
            # resumed queries can go distributed / re-attach
            deadline = time.monotonic() + wait_workers_s
            while not self.active_nodes() and \
                    time.monotonic() < deadline:
                time.sleep(0.02)
            view = None
            if self.ledger is not None:
                view, _ = self.ledger.replay()
                self._sweep_orphans(view)
            self.memory_manager.on_promotion()
            if view is not None:
                self._replay_ledger(view)
            self.role = "PRIMARY"
            COORDINATOR_FAILOVERS.inc()
            return {"role": "PRIMARY", "promoted": True, "epoch": epoch,
                    "reason": reason}

    def _replay_ledger(self, view=None) -> int:
        """Fold the ledger into live coordinator state: catalog version,
        terminal-query registry (with recorded stamps and error
        taxonomy), and resumption of every non-terminal query. Safe to
        run twice — already-tracked query ids are skipped, and the
        view itself is an idempotent fold."""
        if self.ledger is None:
            return 0
        if view is None:
            view, _ = self.ledger.replay()
        cat = self.session.catalog
        while getattr(cat, "version", 0) < view.catalog_version:
            cat.bump_version()
        # fence the id namespace: never re-mint a sequence number the
        # dead primary already issued (ids share the wall-second prefix)
        for qid in view.queries:
            parts = qid.split("_")
            if len(parts) >= 3 and parts[2].isdigit():
                self.tracker.reserve_seq(int(parts[2]))
        resumed = 0
        for qid, q in sorted(view.queries.items()):
            if self.tracker.get(qid) is not None:
                continue                 # already live: double replay
            if q["terminal"] is not None:
                self.dispatcher.restore_terminal(q)
            else:
                self.dispatcher.resume(q, self._resume_mode(q))
                # live progress re-derivation: re-register the ledger's
                # task assignments so reattached tasks' next heartbeat
                # folds back into THIS coordinator's progress estimate
                self.livestats.begin(qid)
                for tid in q.get("assigned", ()):
                    self.livestats.register_task(qid, tid)
                resumed += 1
        return resumed

    def _resume_mode(self, q: dict) -> str:
        """Resumption-mode classification: pre-execution states replay
        from admission; mid-execution queries re-attach when spooled
        output or a surviving assigned task exists, else re-execute."""
        if q["state"] in ("QUEUED", "PLANNING"):
            return "replayed"
        if q["spooled"]:
            return "reattached"
        live_tasks = set(self.task_reports)
        with self.nodes_lock:
            for n in self.nodes.values():
                for t in getattr(n, "tasks", None) or ():
                    tid = t.get("taskId") if isinstance(t, dict) else t
                    if tid:
                        live_tasks.add(tid)
        if any(tid in live_tasks for tid in q["assigned"]):
            return "reattached"
        return "reexecuted"

    def _sweep_orphans(self, view) -> None:
        """Promotion-time hygiene: drop result-spool entries no live
        query can claim, and roll forward / sweep staged-write state in
        every staged-write catalog (a durable commit INTENT finishes
        publishing; everything else is swept — re-executed writes then
        dedup against the published parts)."""
        keep = set()
        for q in view.live():
            keep.update(q["spooled"])
        try:
            self.scheduler.spool.sweep(keep=keep)
        except Exception:  # noqa: BLE001 — sweep is best-effort hygiene
            pass
        from . import writeprotocol as wp
        for conn in self.session.catalog._connectors.values():
            root = getattr(conn, "root", None)
            if root and getattr(conn, "supports_staged_writes", False):
                try:
                    wp.sweep_root(root)
                except Exception:  # noqa: BLE001
                    pass

    def reexecute_restored(self, tq: TrackedQuery) -> TrackedQuery:
        """A ledger-restored FINISHED query got polled for data it no
        longer holds: re-run it under the original id. Reads are pure
        (bit-exact result); writes short-circuit through the commit
        journal's published parts. Triggered at most once per id."""
        with self._reexec_lock:
            if tq.query_id in self._reexec_started:
                return self.tracker.get(tq.query_id) or tq
            self._reexec_started.add(tq.query_id)
        times = {k: v for k, v in tq.state_machine.state_times.items()
                 if k not in ("FINISHED", "FAILED", "CANCELED")}
        q = {"query_id": tq.query_id, "sql": tq.sql,
             "user": tq.session_user, "state_times": times}
        return self.dispatcher.resume(q, "reexecuted")

    def announce(self, node_id: str, uri: str,
                 state: str = "ACTIVE",
                 now: Optional[float] = None,
                 tasks: Optional[list] = None,
                 live_stats: Optional[dict] = None,
                 memory: Optional[dict] = None) -> None:
        """Register/refresh a worker, honoring its reported lifecycle
        state. LEFT deregisters (the graceful mirror of a failure-
        detector eviction); DRAINING/DRAINED pull the node out of
        placement without the detector penalty; ACTIVE restores a node
        from a canceled drain (FAILED→ACTIVE recovery still goes
        through the detector-ratio gate). Any membership or state
        change triggers an immediate cluster-memory re-arbitration.

        STANDBY announces come from a peer coordinator, not a worker:
        they only refresh the failover address list. `tasks` is the
        worker's live task inventory — the promoted coordinator's
        reconciliation input."""
        from ..metrics import NODE_LIFECYCLE_TRANSITIONS
        if state == "STANDBY":
            if uri:
                self.standbys[uri] = time.time()
            return
        changed = False
        # clock-skew estimate: the worker stamped `now` at send time and
        # we read our clock at receive time — the send/recv midpoint of a
        # sub-millisecond local POST, so offset ≈ worker_clock - ours.
        # Adopted worker spans are rebased by it (utils/tracing.py).
        offset = (now - time.time()) if now is not None else None
        with self.nodes_lock:
            node = self.nodes.get(node_id)
            if offset is not None and node is not None and state != "LEFT":
                node.clock_offset = offset
            if state == "LEFT":
                if node is not None:
                    del self.nodes[node_id]
                    changed = True
            elif node is None or node.uri != uri:
                self.nodes[node_id] = RegisteredNode(node_id, uri)
                self.nodes[node_id].state = \
                    state if state in ("DRAINING", "DRAINED") else "ACTIVE"
                if offset is not None:
                    self.nodes[node_id].clock_offset = offset
                changed = True
                state = self.nodes[node_id].state
            else:
                node.last_announce = time.time()
                if state in ("DRAINING", "DRAINED"):
                    # drain overrides FAILED: the worker is reachable
                    # and winding down, not dead
                    if node.state != state:
                        node.state = state
                        changed = True
                elif node.state in ("DRAINING", "DRAINED"):
                    node.state = "ACTIVE"    # drain canceled
                    changed = True
                elif node.state == "FAILED" and \
                        self._recovery_allowed(node_id):
                    node.state = "ACTIVE"    # recovered
                    changed = True
            survivor = self.nodes.get(node_id)
            if survivor is not None and tasks is not None:
                survivor.tasks = tasks
            if survivor is not None and memory is not None:
                # heartbeat pool snapshot: refreshes the same field the
                # failure detector's pings write, shrinking the memory
                # manager's staleness window between status polls
                survivor.memory = memory
        if changed:
            NODE_LIFECYCLE_TRANSITIONS.inc(state=state)
            # outside nodes_lock: tick() re-reads the inventory itself
            self.memory_manager.on_membership_change()
        if live_stats is not None:
            # fold the piggybacked live task stats outside nodes_lock
            # (the fold takes its own lock and may log)
            self.livestats.fold(node_id, live_stats)

    def _recovery_allowed(self, node_id: str) -> bool:
        """A FAILED node may only rejoin on announce when the failure
        detector's decayed ratio has dropped back under the threshold
        (or no detector is attached). Without this gate, a node whose
        task executor is wedged but whose announcer still runs flips
        straight back to ACTIVE and reabsorbs splits every round."""
        det = self.failure_detector
        if det is None:
            return True
        st = det.stats.get(node_id)
        return st is None or st.failure_ratio <= det.threshold

    def active_nodes(self) -> List[RegisteredNode]:
        with self.nodes_lock:
            return [n for n in self.nodes.values() if n.state == "ACTIVE"]


def _column_json(result) -> List[dict]:
    cols = []
    for name in result.column_names:
        cols.append({"name": name, "type": "unknown"})
    return cols


def _rows_json(rows: List[tuple]) -> List[list]:
    out = []
    for r in rows:
        vals = []
        for v in r:
            if v is None or isinstance(v, (int, float, str, bool)):
                vals.append(v)
            else:
                vals.append(str(v))      # Decimal, date -> text like Trino
        out.append(vals)
    return out


class _Handler(BaseHTTPRequestHandler):
    state: CoordinatorState = None       # injected by make_server
    protocol_version = "HTTP/1.1"

    # -- helpers ----------------------------------------------------------

    def _send(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str,
                   content_type: str = "text/plain; version=0.0.4") -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _not_found(self, path: str) -> None:
        self._send(404, {"error": {"message": f"no route {path}"}})

    def _base(self) -> str:
        host = self.headers.get("Host", "localhost")
        return f"http://{host}"

    def log_message(self, fmt, *args):   # quiet
        pass

    def _read_body(self) -> str:
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n).decode()

    def _query_payload(self, tq: TrackedQuery, token: int) -> dict:
        """One protocol page: state + columns + data + nextUri while more."""
        sm = tq.state_machine
        if sm.is_done():
            # terminal pages wait for the completion pipeline (event
            # listeners, ledger terminal record, metrics) to finish, so
            # the client's view of "done" is never ahead of the server's
            sm.settled.wait(5.0)
        base = self._base()
        # split-weighted live progress (server/livestats.py): monotonic
        # per query (the store high-waters, TrackedQuery remembers),
        # 1.0 exactly at FINISHED. Queries the store never saw (local
        # execution, heartbeats off) ride their remembered ratio — 0.0
        # until terminal, so the CLI progress line still behaves.
        ls = self.state.livestats
        progress = ls.progress(tq.query_id)
        if progress is not None and progress > tq.progress_ratio:
            tq.progress_ratio = progress
        stage = ls.dominant_stage(tq.query_id)
        if stage:
            tq.dominant_stage = stage
        if sm.state == "FINISHED":
            tq.progress_ratio = 1.0
        payload = {
            "id": tq.query_id,
            "infoUri": f"{base}/v1/query/{tq.query_id}",
            "stats": {
                "state": tq.state,
                "queued": tq.state == "QUEUED",
                "elapsedTimeMillis": int(tq.elapsed_s * 1000),
                "rows": tq.rows_returned,
                "progressRatio": round(tq.progress_ratio, 6),
                "stage": tq.dominant_stage,
            },
        }
        if sm.state == "FAILED":
            payload["error"] = {"message": sm.error,
                                "errorCode": sm.error_code,
                                "errorName": sm.error_name}
            if sm.error_name in ("QUERY_QUEUE_FULL",
                                 "QUERY_EXCEEDED_QUEUED_TIME"):
                # overload rejections are safe to retry later/elsewhere
                # — the statement-level mirror of the 503 contract the
                # client's failover loop already keys on
                payload["error"]["retryable"] = True
            return payload
        if sm.state == "CANCELED":
            payload["error"] = {"message": "Query was canceled",
                                "errorCode": 2, "errorName": "USER_CANCELED"}
            return payload
        if sm.state != "FINISHED":
            payload["nextUri"] = (f"{base}/v1/statement/executing/"
                                  f"{tq.query_id}/{token}")
            return payload
        result = tq.result
        payload["columns"] = _column_json(result)
        # spooled protocol: opted-in clients get segment descriptors for
        # large results instead of inline pages (spi/spool/ role)
        if self.headers.get("X-Trino-Spooled") == "true" and \
                len(result.rows) > PAGE_ROWS and token == 0:
            segments = self.state.spooling.spool(_rows_json(result.rows))
            payload["segments"] = [
                {**s, "uri": f"{base}{s['uri']}"} for s in segments]
            return payload
        start = token * PAGE_ROWS
        chunk = result.rows[start:start + PAGE_ROWS]
        payload["data"] = _rows_json(chunk)
        if start + PAGE_ROWS < len(result.rows):
            payload["nextUri"] = (f"{base}/v1/statement/executing/"
                                  f"{tq.query_id}/{token + 1}")
        return payload

    def _authenticate(self):
        """Returns the authenticated user, or None after sending 401.
        Open clusters (no authenticator) pass the header user through."""
        user = self.headers.get("X-Trino-User", "anonymous")
        auth = self.state.dispatcher.authenticator
        if auth is None:
            return user
        from .security import AuthenticationError
        secret = self.headers.get("X-Trino-Password")
        if secret is None:
            bearer = self.headers.get("Authorization", "")
            if bearer.startswith("Bearer "):
                secret = bearer[len("Bearer "):]
        try:
            return auth.authenticate(user, secret)
        except AuthenticationError as e:
            self.send_response(401)
            body = json.dumps(
                {"error": {"message": str(e),
                           "errorName": "AUTHENTICATION_FAILED"}}).encode()
            self.send_header("Content-Type", "application/json")
            self.send_header("WWW-Authenticate", "Basic")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return None

    # -- dispatch ----------------------------------------------------------

    def do_POST(self):
        dispatch(self, "POST", ROUTES, SERVER_NAME)

    def do_GET(self):
        dispatch(self, "GET", ROUTES, SERVER_NAME)

    def do_DELETE(self):
        dispatch(self, "DELETE", ROUTES, SERVER_NAME)

    def do_PUT(self):
        dispatch(self, "PUT", ROUTES, SERVER_NAME)

    def _unavailable(self) -> bool:
        """503 on statement traffic while not PRIMARY — the retryable
        signal the client's failover poll loop keys on."""
        if self.state.accepting():
            return False
        self._send(503, {"error": {
            "message": f"coordinator is {self.state.role}",
            "errorName": "COORDINATOR_UNAVAILABLE",
            "retryable": True}})
        return True

    # -- routes -----------------------------------------------------------

    def _post_statement(self, parts, user):
        if self._unavailable():
            return
        sql = self._read_body()
        if not sql.strip():
            self._send(400, {"error": {"message": "empty statement"}})
            return
        tq = self.state.dispatcher.submit(
            sql, user, traceparent=self.headers.get("traceparent"))
        self._send(200, self._query_payload(tq, 0))

    def _post_announce(self, parts, user):
        body = json.loads(self._read_body() or "{}")
        st = self.state
        st.announce(body.get("nodeId", "unknown"),
                    body.get("uri", ""),
                    state=body.get("state", "ACTIVE"),
                    now=body.get("now"),
                    tasks=body.get("tasks"),
                    live_stats=body.get("liveStats"),
                    memory=body.get("memory"))
        # the failover contract: every announce response carries the
        # coordinator address list (primary first, fresh standbys after)
        # so workers and clients always know where to re-announce
        resp = {"ok": True, "role": st.role,
                "coordinators": st.coordinator_uris()}
        if st.ledger is not None:
            resp["epoch"] = st.ledger.read_epoch()[0]
        self._send(202, resp)

    def _post_task_status(self, parts, user):
        # buffered terminal-status re-delivery from workers (possibly
        # reports the old primary never saw) — reconciliation input
        body = json.loads(self._read_body() or "{}")
        tid = body.get("taskId")
        if tid:
            self.state.task_reports[tid] = body
        self._send(202, {"ok": True})

    def _get_info_state(self, parts, user):
        st = self.state
        payload = {"state": st.role, "nodeId": st.node_id,
                   "ready": st.role == "PRIMARY",
                   "coordinators": st.coordinator_uris()}
        if st.ledger is not None:
            epoch, owner = st.ledger.read_epoch()
            payload["epoch"] = epoch
            payload["epochOwner"] = owner
        self._send(200, payload)

    def _put_info_state(self, parts, user):
        body = json.loads(self._read_body() or "{}")
        want = str(body.get("state", "")).upper()
        if want in ("PRIMARY", "ACTIVE"):
            self._send(200, self.state.promote(reason="admin"))
            return
        self._send(400, {"error": {
            "message": f"unsupported coordinator state {want!r} "
                       f"(PUT PRIMARY/ACTIVE to promote)"}})

    def _get_info(self, parts, user):
        self._send(200, {
            "nodeVersion": {"version": "trino-tpu-0.1"},
            "coordinator": True, "starting": False,
            "uptime": time.time() - self.state.started_at})

    def _get_status(self, parts, user):
        # liveness for load balancers / the failure detector: open
        # even on a secured cluster (no query data exposed)
        from ..exec.prewarm import compile_cache_stats
        from ..exec.profiler import device_memory_stats
        self._send(200, {"nodeId": "coordinator", "state": "ACTIVE",
                         "device": device_memory_stats(),
                         "compileCache": compile_cache_stats(),
                         "prewarm": self.state.prewarm.stats()})

    def _get_metrics(self, parts, user):
        from ..metrics import REGISTRY
        self._send_text(200, REGISTRY.render())

    def _get_jit(self, parts, user):
        # JIT-compile observability (exec/profiler.py): per-(site,
        # fingerprint) compile/hit aggregates plus process totals — the
        # scrape twin of system.runtime.jit_cache (no query data, so it
        # stays open like /v1/metrics)
        from ..exec.profiler import RECORDER
        self._send(200, {"totals": RECORDER.totals(),
                         "entries": RECORDER.snapshot(),
                         # shape-canonicalization signal + prewarm view:
                         # entries carry prewarmed/prewarm_hits columns,
                         # distinctShapes is the per-site shape count
                         "distinctShapes": RECORDER.site_shape_counts(),
                         "prewarm": self.state.prewarm.stats()})

    def _get_prewarm(self, parts, user):
        # the joining-worker warm-manifest handshake (server/worker.py
        # pulls this before its first ACTIVE announce)
        self._send(200, self.state.prewarm.manifest())

    def _get_segment(self, parts, user):
        data = self.state.spooling.read(parts[3])
        if data is None:
            self._send(404, {"error": {"message": "unknown segment"}})
            return
        self._send(200, {"data": data})

    def _get_resource_group(self, parts, user):
        self._send(200, self.state.dispatcher.resource_groups.info())

    def _get_memory(self, parts, user):
        # cluster memory view (memory/ClusterMemoryManager's JMX beans,
        # flattened): coordinator pool + per-worker heartbeat reports
        self._send(200, self.state.memory_manager.snapshot())

    def _get_nodes(self, parts, user):
        nodes = [{"nodeId": n.node_id, "uri": n.uri, "state": n.state}
                 for n in self.state.nodes.values()]
        self._send(200, nodes)

    def _get_queries(self, parts, user):
        out = []
        for tq in self.state.tracker.all():
            out.append({"queryId": tq.query_id, "state": tq.state,
                        "query": tq.sql, "user": tq.session_user})
        self._send(200, out)

    def _get_query(self, parts, user):
        tq = self.state.tracker.get(parts[2])
        if tq is None:
            self._send(404, {"error": {"message": "unknown query"}})
            return
        sm = tq.state_machine
        st = tq.stage_stats or {}
        # live observability (server/livestats.py): high-water the
        # heartbeat-fed progress onto the tracked query, then serve the
        # in-flight per-stage rollup + stuck diagnosis alongside the
        # terminal stage stats — mid-flight GETs see real numbers
        ls = self.state.livestats
        progress = ls.progress(tq.query_id)
        if progress is not None and progress > tq.progress_ratio:
            tq.progress_ratio = progress
        dom = ls.dominant_stage(tq.query_id)
        if dom:
            tq.dominant_stage = dom
        if sm.state == "FINISHED":
            tq.progress_ratio = 1.0
        rollup = ls.query_rollup(tq.query_id)
        self._send(200, {
            "queryId": tq.query_id, "state": tq.state, "query": tq.sql,
            "user": tq.session_user, "error": sm.error,
            "elapsedSeconds": tq.elapsed_s,
            "rows": tq.rows_returned, "retries": tq.retries,
            "distributed": tq.distributed,
            "fallbackReason": tq.fallback_reason,
            "route": tq.route, "routeReason": tq.route_reason,
            "progressRatio": round(tq.progress_ratio, 6),
            "dominantStage": tq.dominant_stage,
            "liveStats": rollup,
            "diagnosis": tq.live_diagnosis,
            "stageStats": {
                "stages": st.get("stages", 0),
                "tasks": len(st.get("tasks", ())),
                "bytesShuffled": st.get("bytes_shuffled", 0),
                "taskRetries": st.get("task_retries", 0),
                "hedgedTasks": st.get("hedged_tasks", 0),
                "hedgeWins": st.get("hedge_wins", 0),
                "faultsSurvived": st.get("faults_survived", 0)},
            # exactly-once write rollup (empty for reads)
            "writtenRows": (st.get("write") or {}).get("rows", 0),
            "writtenBytes": (st.get("write") or {}).get("bytes", 0),
            "commitPhase": (st.get("write") or {}).get("phase", "")})

    def _get_query_trace(self, parts, user):
        """Stitched query trace (coordinator + adopted worker spans) as
        OTLP-like JSON — the reference exports the same shape over OTLP."""
        tq = self.state.tracker.get(parts[2])
        if tq is None:
            self._send(404, {"error": {"message": "unknown query"}})
            return
        spans = tq.trace
        if spans is None and tq.tracer is not None:
            spans = tq.tracer.export()    # still executing: live view
        tracer = tq.tracer
        self._send(200, {
            "queryId": tq.query_id,
            "traceId": tracer.trace_id if tracer is not None else None,
            "spans": spans or []})

    def _get_query_timeline(self, parts, user):
        """Critical-path wall-time attribution (server/timeline.py):
        phase intervals summing exactly to elapsed wall, the dominant
        phase, and the blocking critical path over stage spans."""
        tq = self.state.tracker.get(parts[2])
        if tq is None:
            self._send(404, {"error": {"message": "unknown query"}})
            return
        tl = tq.timeline
        if tl is None:                    # still executing: live view
            from .timeline import build_timeline
            tl = build_timeline(tq)
        self._send(200, tl)

    def _get_telemetry(self, parts, user):
        from urllib.parse import parse_qs, urlparse
        try:
            since = float(parse_qs(urlparse(self.path).query)
                          .get("since", ["0"])[0])
        except ValueError:
            since = 0.0
        rec = self.state.telemetry.recorder
        self._send(200, {"nodeId": rec.node_id,
                         "samples": rec.since(since)})

    def _get_executing(self, parts, user):
        if self._unavailable():
            return
        qid = parts[3]
        token = int(parts[4]) if len(parts) > 4 else 0
        tq = self.state.tracker.get(qid)
        if tq is None:
            self._send(404, {"error": {"message": "unknown query"}})
            return
        if tq.state_machine.state == "FINISHED" and tq.result is None:
            # ledger-restored FINISHED query without its result pages:
            # re-run under the original id (pure reads are bit-exact;
            # writes short-circuit on the published commit)
            tq = self.state.reexecute_restored(tq)
        # long-poll lite: give the dispatcher a moment before answering
        # (ExecutingStatementResource waits up to ~1s the same way)
        deadline = time.time() + 0.5
        while not tq.state_machine.is_done() and time.time() < deadline:
            time.sleep(0.01)
        self._send(200, self._query_payload(tq, token))

    def _delete_segment(self, parts, user):
        self.state.spooling.ack(parts[3])
        self._send(204, {})

    def _delete_executing(self, parts, user):
        # route through the dispatcher's single termination path: a bare
        # state_machine.cancel() here used to leave every in-flight
        # worker task running to completion (and its buffers pinned)
        tq = self.state.tracker.get(parts[3])
        if tq is not None:
            self.state.dispatcher.terminate(tq.query_id, reason="user")
        self._send(204, {})


class CoordinatorServer:
    """In-process coordinator (TestingTrinoServer.java:155 pattern: real
    HTTP, embeddable in one process for tests)."""

    def __init__(self, session: Optional[Session] = None, port: int = 0,
                 max_concurrency: int = 4, retry_policy: str = "NONE",
                 telemetry_interval_s: Optional[float] = None,
                 ledger_path: Optional[str] = None,
                 node_id: str = "coordinator", role: str = "primary",
                 peer_uri: Optional[str] = None,
                 spool_root: Optional[str] = None,
                 standby_interval_s: float = 0.25,
                 auto_promote: bool = True):
        self.state = CoordinatorState(session or Session(),
                                      max_concurrency, retry_policy,
                                      telemetry_interval_s,
                                      ledger_path=ledger_path,
                                      node_id=node_id, role=role,
                                      peer_uri=peer_uri,
                                      spool_root=spool_root)
        handler = type("BoundHandler", (_Handler,), {"state": self.state})
        self.httpd = ClusterHTTPServer(("127.0.0.1", port), handler)
        self.port = self.httpd.server_address[1]
        self.uri = f"http://127.0.0.1:{self.port}"
        self.state.uri = self.uri
        self._thread: Optional[threading.Thread] = None
        self._watcher = None
        self._standby_interval_s = standby_interval_s
        self._auto_promote = auto_promote

    def start(self) -> "CoordinatorServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="coordinator-http",
                                        daemon=True)
        self._thread.start()
        # no-op unless a telemetry interval is configured
        self.state.telemetry.start()
        # warm standby: announce ourselves to the primary (so announce
        # responses carry our address), tail the ledger, and promote on
        # primary death (detector-driven) — failuredetector.py
        if self.state.role != "PRIMARY" and self.state.peer_uri:
            from .failuredetector import StandbyWatcher
            self._watcher = StandbyWatcher(
                self.state, self.uri, self.state.peer_uri,
                interval_s=self._standby_interval_s,
                auto_promote=self._auto_promote)
            self._watcher.start()
        return self

    def stop(self) -> None:
        if self._watcher is not None:
            self._watcher.stop()
        self.state.telemetry.stop()
        # shutdown() blocks until serve_forever acknowledges — which
        # never happens if start() was never called, so only wave at a
        # loop that actually exists
        if self._thread is not None:
            self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def kill(self) -> None:
        """Crash model (the coordinator twin of WorkerServer.kill):
        stop serving instantly with no drain or goodbye, and seal the
        ledger so the dead instance can never append another record —
        in-flight dispatch threads keep running but their world is
        write-protected, exactly like a machine losing power."""
        if self.state.ledger is not None:
            self.state.ledger.seal()
        self.state.role = "PASSIVE"
        if self._watcher is not None:
            self._watcher.stop()
        self.state.telemetry.stop()
        try:
            if self._thread is not None:
                self.httpd.shutdown()
            self.httpd.server_close()
        except Exception:  # noqa: BLE001 — dying twice is fine
            pass
