"""Worker-side task execution: the engine's L5.

Reference: TaskResource (server/TaskResource.java:93 — createOrUpdateTask
:146, results :332, ack :372, fail :319) backed by SqlTaskManager
(execution/SqlTaskManager.java:107, updateTask:491) and SqlTaskExecution
(execution/SqlTaskExecution.java:81): the coordinator POSTs a plan fragment
plus split assignments; the worker runs the fragment over each split and
stages output pages for downstream pull.

TPU adaptation: a *fragment* is a pickled logical-plan subtree whose leaf
scan is replaced per split by a row-range of the table (split scheduling,
SourcePartitionedScheduler.java:247's batches); the worker executes it with
its own Executor (its slice of TPU devices) and serves *partial result
pages* (host numpy columns) — the PARTIAL side of Trino's exchange. The
final stage merges on the coordinator. Output pages use token-based pull
with acks, the OutputBuffer protocol (execution/buffer/
PartitionedOutputBuffer.java:42) reduced to its sequential-consumer core.
"""

from __future__ import annotations

import base64
import logging
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..metrics import TASK_OUTPUT_BYTES, TASK_OUTPUT_ROWS
from ..utils.tracing import NOOP, Tracer

log = logging.getLogger("trino_tpu.tasks")


# --------------------------------------------------------------------------
# wire serde: numpy column sets and plan fragments
# (PagesSerde's role, execution/buffer/CompressingEncryptingPageSerializer.java:60)
# Pages are length-prefixed binary frames with zstd/zlib compression
# (server/pageserde.py); the worker serves them raw on the binary results
# route and base64-wrapped on the legacy JSON route.
# --------------------------------------------------------------------------

def encode_columns(arrays: List[np.ndarray],
                   valids: List[np.ndarray]) -> bytes:
    from .pageserde import encode_page
    return encode_page(arrays, valids)


def decode_columns(page) -> tuple:
    """Accepts a binary frame (bytes), its base64 JSON wrapping
    ({"b64": ...}), or the round-3 dict layout (rolling upgrade)."""
    from .pageserde import decode_page
    if isinstance(page, (bytes, bytearray)):
        return decode_page(bytes(page))
    if isinstance(page, dict) and "b64" in page:
        return decode_page(base64.b64decode(page["b64"]))
    arrays, valids = [], []
    for c in page["columns"]:
        a = np.frombuffer(base64.b64decode(c["data"]),
                          dtype=np.dtype(c["dtype"]))
        v = np.frombuffer(base64.b64decode(c["valid"]), dtype=np.bool_)
        arrays.append(a)
        valids.append(v)
    return arrays, valids


def concat_pages(pages, out_types) -> tuple:
    """Decode + concatenate page frames into one (arrays, valids) column
    set; zero-row input yields empty columns typed from `out_types`
    (pairs of (name, dtype)). Shared by the coordinator merge and the
    exchange consumer."""
    cols = None
    for p in pages:
        arrs, vals = decode_columns(p)
        if len(arrs) == 0 or len(arrs[0]) == 0:
            continue
        if cols is None:
            cols = [[a] for a in arrs], [[v] for v in vals]
        else:
            for j, a in enumerate(arrs):
                cols[0][j].append(a)
                cols[1][j].append(vals[j])
    if cols is not None:
        return ([np.concatenate(c) for c in cols[0]],
                [np.concatenate(c) for c in cols[1]])
    arrs = [np.zeros(0, dtype=dt.np_dtype) for _, dt in out_types]
    return arrs, [np.zeros(0, dtype=np.bool_) for _ in arrs]


def encode_fragment(root) -> str:
    """Plan subtree -> wire form: a data-only JSON serde (server/serde.py),
    the analog of the reference's Jackson-serialized PlanFragment — a
    crafted POST body can at worst build a malformed plan, never run code."""
    from . import serde
    return serde.dumps(root)


def decode_fragment(blob: str):
    from . import serde
    return serde.loads(blob)


def _subtree_nodes_all(root):
    """Every node of a fragment subtree (id -> operator-name mapping for
    per-operator TaskStats)."""
    from ..planner.fragmenter import _subtree_nodes
    return _subtree_nodes(root)


def _static_subtrees(root, driver) -> list:
    """Maximal subtrees of `root` that do not contain the driver scan —
    join build sides and friends, constant across splits. Bare scans and
    values leaves are excluded (the scan cache already memoizes them)."""
    from ..planner import logical as L
    memo = {}

    def contains(n) -> bool:
        r = memo.get(id(n))
        if r is None:
            r = n is driver or any(contains(c) for c in L.children(n))
            memo[id(n)] = r
        return r

    out = []

    def walk(n):
        for c in L.children(n):
            if contains(c):
                walk(c)
            elif not isinstance(c, (L.ScanNode, L.ValuesNode)):
                out.append(c)

    if contains(root):
        walk(root)
    return out


@dataclass(frozen=True)
class Split:
    """A row-range of one table (ConnectorSplit reduced to the range case;
    the tpch/tpcds/memory connectors are all range-splittable)."""
    catalog: str
    schema_name: str
    table: str
    start: int
    count: int


# --------------------------------------------------------------------------
# hash partitioning for the worker<->worker exchange
# (operator/output/PagePartitioner.java:135's role; the hash must be
# identical on every worker so co-partitioned sides land together)
# --------------------------------------------------------------------------

def _splitmix64(x: np.ndarray) -> np.ndarray:
    """uint64 -> uint64 mix (same finalizer family as the reference's
    XxHash64-based partitioning — any good avalanche works, it only has
    to be consistent across workers)."""
    z = x + np.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def partition_assignment(arrays, valids, key_idxs, count: int):
    """Per-row partition ids from the key columns. Integer-typed keys
    only (dictionary varchar codes are per-table and would partition
    inconsistently across tables); NULLs hash to a fixed marker so every
    worker routes them identically."""
    n = len(arrays[0]) if arrays else 0
    h = np.zeros(n, np.uint64)
    with np.errstate(over="ignore"):
        for j, i in enumerate(key_idxs):
            a = arrays[i]
            if not np.issubdtype(a.dtype, np.integer) and \
                    a.dtype != np.bool_:
                raise ValueError(
                    f"partitioned exchange requires integer keys, "
                    f"got {a.dtype}")
            k = a.astype(np.int64).view(np.uint64)
            k = np.where(valids[i], k, np.uint64(0xA5A5A5A5A5A5A5A5))
            h ^= _splitmix64(k + np.uint64(j))
    return (h % np.uint64(count)).astype(np.int64)


# --------------------------------------------------------------------------
# task state + manager
# --------------------------------------------------------------------------

TASK_STATES = ("PENDING", "RUNNING", "FINISHED", "FAILED", "CANCELED",
               "ABANDONED")


@dataclass
class WorkerTask:
    """One task's state. Output is a set of numbered buffers: buffer 0
    for the plain single-consumer case, buffers 0..P-1 when `partition`
    is set (PartitionedOutputBuffer.java:42's role). `sources` makes the
    task an exchange CONSUMER: instead of splits it pulls its partition
    from upstream tasks on other workers (worker<->worker data plane,
    DirectExchangeClient.java:56)."""
    task_id: str
    fragment_blob: str
    splits: List[Split]
    # {"keys": [out col idx, ...], "count": P} -> partitioned output
    partition: Optional[dict] = None
    # {fragment_id(str): [{"uri","taskId","buffer"}, ...]} -> pull inputs
    sources: Optional[dict] = None
    state: str = "PENDING"
    error: str = ""
    buffers: Dict[int, List[bytes]] = field(default_factory=dict)
    acked: Dict[int, int] = field(default_factory=dict)
    splits_done: int = 0
    lock: threading.Lock = field(default_factory=threading.Lock)
    # observability: W3C trace context adopted from the coordinator's
    # POST, per-task output accounting (TaskStats), and the worker-side
    # spans shipped back with the terminal status for trace stitching
    traceparent: Optional[str] = None
    rows_out: int = 0
    bytes_out: int = 0
    stats: Dict[str, object] = field(default_factory=dict)
    spans: List[dict] = field(default_factory=list)
    # exchange backpressure: bytes currently staged across all buffers
    # (un-acked pages) and how often the producer had to pause for a
    # slow consumer (OutputBuffer's maxBufferedBytes + isFull blocking)
    buffered_bytes: int = 0
    backpressure_waits: int = 0
    # staged-file manifest for write tasks (rides terminal status stats;
    # publication is the coordinator's commit, never this worker's)
    manifest: Optional[dict] = None
    # live observability (round-21): a manager-global change sequence
    # stamped on every counter move (the heartbeat's delta cursor), the
    # task's start stamp (live wall), and device/host/compile ms
    # accumulated so far — terminal status_json never reads these, so
    # the terminal wire format stays byte-identical with heartbeats off
    live_seq: int = 0
    started_at: float = 0.0
    device_ms: float = 0.0
    host_ms: float = 0.0
    compile_ms: float = 0.0
    # query-lifetime enforcement (round-22): worker-monotonic execution
    # cutoff derived from the coordinator's clock-skew-normalized wall
    # deadline shipped with the task POST (None = no cap), and the
    # orphan reaper's liveness stamp — the last monotonic time a
    # coordinator request (status/results/delete/update) referenced
    # this task
    deadline: Optional[float] = None
    last_referenced: float = 0.0

    def __post_init__(self):
        self.last_referenced = time.monotonic()
        # producer/consumer rendezvous sharing the task lock: _emit
        # waits on it when the buffer is full, the results route
        # notifies as acks drain pages
        self.cond = threading.Condition(self.lock)

    @property
    def pages(self) -> List[bytes]:       # legacy single-buffer view
        return self.buffers.setdefault(0, [])

    def total_pages(self) -> int:
        return sum(len(v) for v in self.buffers.values()) + \
            sum(self.acked.values())


class TaskManager:
    """SqlTaskManager's role: registry + execution of tasks on this
    worker. Execution runs on a worker thread per task; the handler
    returns immediately (the reference's updateTask is async the same
    way)."""

    def __init__(self, catalog, injector=None, node_id: str = "worker"):
        import os
        self.catalog = catalog
        self.node_id = node_id            # span service attribution
        self.tasks: Dict[str, WorkerTask] = {}
        self._lock = threading.Lock()
        self.injector = injector          # FailureInjector hook
        self.tasks_run = 0                # observability counter
        # terminal-status push hook (WorkerServer wires it): fired once
        # from the task thread when a task reaches FINISHED/FAILED/
        # CANCELED, after stats finalize — the worker-initiated half of
        # status delivery that survives a coordinator failover
        self.on_terminal = None
        # exchange backpressure: per-task output-buffer byte bound — a
        # slow consumer pauses the producer instead of ballooning the
        # worker's memory (PartitionedOutputBuffer's max-buffered-bytes)
        self.max_buffer_bytes = int(os.environ.get(
            "TRINO_TPU_TASK_BUFFER_BYTES", 64 << 20))
        # hard cap on one producer pause so a dead consumer degrades to
        # an unbounded buffer (memory risk) rather than a hung task;
        # per-task deadlines cap it further, and the degrade is counted
        # + logged (round-22) so it is never silent
        self.backpressure_timeout_s = 300.0
        # orphan reaping (round-22): tasks no coordinator request has
        # referenced for this long are abandoned — buffers freed, state
        # ABANDONED — so a dead coordinator cannot leak worker memory
        self.task_abandonment_timeout_s = float(os.environ.get(
            "TRINO_TPU_TASK_ABANDONMENT_S", 600.0))
        # the task currently holding the exec lock (cancel propagation
        # target: a DELETE for it interrupts the running split
        # cooperatively via the executor's check_cancel points)
        self._current_task_id: Optional[str] = None
        # one Executor per worker: kernels are jitted process-wide anyway;
        # the lock serializes device use within this worker
        from ..exec.executor import Executor
        self._executor = Executor(catalog)
        # executor-side chaos points (e.g. SCAN_PREFETCH in the chunked
        # driver's prefetch worker) share this worker's injector, so the
        # same seeded schedule covers threads the task manager spawns
        self._executor.failure_injector = injector
        self._exec_lock = threading.Lock()
        # live observability (round-21): one monotonically-increasing
        # change sequence across ALL tasks (the heartbeat delta cursor)
        # plus cumulative split-execution busy time split by tier —
        # device (fenced dispatch wall from profiled runs) vs host
        # (interpreter wall). Both are plain counters bumped on the task
        # thread; no thread, no timer, nothing runs unless read.
        self._live_lock = threading.Lock()
        self._live_seq = 0
        self.busy_device_ms = 0.0
        self.busy_host_ms = 0.0

    def create_or_update(self, task_id: str, fragment_blob: str,
                         splits: List[Split], partition: dict = None,
                         sources: dict = None,
                         traceparent: str = None,
                         deadline: float = None) -> WorkerTask:
        if self.injector is not None:
            # chaos: fail/delay/drop task intake (the worker dies or
            # hangs between accept and ack — TaskResource's createOrUpdate
            # boundary); the coordinator sees a failed POST and reassigns
            self.injector.maybe_fail("WORKER_TASK_CREATE", task_id)
        with self._lock:
            task = self.tasks.get(task_id)
            if task is None:
                task = WorkerTask(task_id, fragment_blob, splits,
                                  partition=partition, sources=sources,
                                  traceparent=traceparent)
                if deadline is not None:
                    # `deadline` is wall time on THIS worker's clock (the
                    # coordinator normalized its absolute deadline by the
                    # node's announce-measured clock offset); convert to
                    # a monotonic cutoff so wall jumps can't extend it
                    task.deadline = time.monotonic() + max(
                        0.0, deadline - time.time())
                self.tasks[task_id] = task
                t = threading.Thread(target=self._run, args=(task,),
                                     name=f"task-{task_id}", daemon=True)
                t.start()
            else:
                task.last_referenced = time.monotonic()
            return task

    def get(self, task_id: str) -> Optional[WorkerTask]:
        return self.tasks.get(task_id)

    def touch(self, task_id: str) -> None:
        """Stamp a coordinator reference (status/results/delete pull) —
        the orphan reaper's liveness signal."""
        task = self.tasks.get(task_id)
        if task is not None:
            task.last_referenced = time.monotonic()

    def cancel(self, task_id: str) -> None:
        task = self.tasks.get(task_id)
        if task is not None:
            with task.cond:
                task.last_referenced = time.monotonic()
                if task.state in ("PENDING", "RUNNING"):
                    task.state = "CANCELED"
                # wake a producer paused on a full output buffer
                task.cond.notify_all()
            # cooperative interrupt: if this task holds the exec lock,
            # the running split bails at the executor's next
            # check_cancel point (chunk/partition/prefetch boundary)
            # instead of running the split to completion
            if self._current_task_id == task_id:
                self._executor.request_cancel(
                    f"task {task_id} canceled")
            self._note_live_change(task)

    def reap_orphans(self, timeout_s: Optional[float] = None) -> List[str]:
        """Abandon tasks no coordinator request has referenced for
        `timeout_s`: free their staged output buffers and mark them
        ABANDONED so running split loops bail at the next boundary.
        Returns the reaped task ids. The worker's announce loop drives
        this — and fences it off entirely around coordinator failover
        (worker.py) so a promoted standby reattaching to live tasks is
        never raced by the reaper."""
        if timeout_s is None:
            timeout_s = self.task_abandonment_timeout_s
        now = time.monotonic()
        reaped: List[str] = []
        with self._lock:
            tasks = list(self.tasks.values())
        for t in tasks:
            with t.cond:
                if t.state not in ("PENDING", "RUNNING", "FINISHED"):
                    continue
                if now - t.last_referenced < timeout_s:
                    continue
                t.state = "ABANDONED"
                t.buffers.clear()
                t.buffered_bytes = 0
                t.cond.notify_all()
            if self._current_task_id == t.task_id:
                self._executor.request_cancel(
                    f"task {t.task_id} abandoned (orphaned)")
            reaped.append(t.task_id)
            from ..metrics import TASKS_ABANDONED
            TASKS_ABANDONED.inc()
            log.warning("reaped orphaned task %s (unreferenced %.1fs)",
                        t.task_id, now - t.last_referenced)
            self._note_live_change(t)
        return reaped

    def inflight(self) -> List[str]:
        """Ids of tasks still PENDING/RUNNING (drain bookkeeping)."""
        with self._lock:
            return [t.task_id for t in self.tasks.values()
                    if t.state in ("PENDING", "RUNNING")]

    def inventory(self) -> List[dict]:
        """Compact id/state list of every task this worker holds. Rides
        each announce body so a promoted coordinator can reconcile its
        ledger-replayed task assignments against what actually survived
        the old primary's death."""
        with self._lock:
            return [{"taskId": t.task_id, "state": t.state}
                    for t in self.tasks.values()]

    # -- live observability (round-21) -------------------------------------

    def _note_live_change(self, task: WorkerTask) -> None:
        """Stamp `task` with the next global change sequence. Called on
        the task thread whenever a live-visible counter moves (split
        done, page staged, state transition) so the heartbeat's delta
        encoder can ship ONLY tasks that changed since its cursor."""
        with self._live_lock:
            self._live_seq += 1
            task.live_seq = self._live_seq

    def _note_busy(self, device_ms: float, host_ms: float) -> None:
        with self._live_lock:
            self.busy_device_ms += device_ms
            self.busy_host_ms += host_ms

    def busy_ms(self) -> dict:
        """Cumulative split-execution busy time by tier — the worker's
        utilization numerator (the heartbeat divides deltas of this by
        wall to get the per-interval busy fraction)."""
        with self._live_lock:
            return {"deviceMs": round(self.busy_device_ms, 3),
                    "hostMs": round(self.busy_host_ms, 3)}

    def live_status(self, task: WorkerTask) -> dict:
        """Bounded incremental TaskStats for one task: fixed scalar
        fields only (no operators/spans/manifest), so a 100-task fanout
        heartbeat stays byte-bounded."""
        with task.lock:
            if task.started_at and task.state == "RUNNING":
                wall_ms = (time.monotonic() - task.started_at) * 1000
            else:
                wall_ms = float(task.stats.get("wallMs", 0.0)) \
                    if task.stats else 0.0
            return {"taskId": task.task_id, "state": task.state,
                    "seq": task.live_seq,
                    "splitsDone": task.splits_done,
                    "splitsTotal": len(task.splits),
                    "rowsOut": task.rows_out,
                    "bytesOut": task.bytes_out,
                    "wallMs": round(wall_ms, 3),
                    "deviceMs": round(task.device_ms, 3),
                    "hostMs": round(task.host_ms, 3),
                    "compileMs": round(task.compile_ms, 3)}

    def live_delta(self, since: int = 0) -> tuple:
        """(cursor, entries): live status of every task whose change
        sequence advanced past `since`, plus the cursor to pass next
        time. Entries carry ABSOLUTE counter values (folds are
        idempotent), the delta encoding is in which tasks ship at all —
        an idle worker's heartbeat is an empty list."""
        with self._lock:
            tasks = list(self.tasks.values())
        with self._live_lock:
            cursor = self._live_seq
        entries = [self.live_status(t) for t in tasks
                   if t.live_seq > since]
        return cursor, entries

    def unflushed(self) -> List[str]:
        """Ids of finished tasks whose output buffers still hold
        un-acked pages — a draining worker keeps serving these until its
        downstream consumers pull them (or the drain deadline passes and
        the scheduler's retry machinery re-runs the work elsewhere)."""
        out = []
        with self._lock:
            tasks = list(self.tasks.values())
        for t in tasks:
            with t.cond:
                if t.state == "FINISHED" and any(t.buffers.values()):
                    out.append(t.task_id)
        return out

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Bounded graceful drain: wait for every in-flight task to reach
        a terminal state, then for every finished task's output buffers
        to be fully pulled/acked by their consumers. Returns True when
        the worker quiesced cleanly (no orphaned splits, no unflushed
        pages) within the budget. The caller stops accepting NEW task
        POSTs before calling this; existing buffers stay pullable
        throughout and after."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        while (self.inflight() or self.unflushed()) and \
                time.monotonic() < deadline:
            time.sleep(0.02)
        return not self.inflight() and not self.unflushed()

    def memory_info(self) -> dict:
        """Pool snapshot + staged output bytes, reported on /v1/status so
        heartbeats carry this worker's memory to the coordinator's
        ClusterMemoryManager."""
        snap = self._executor.pool.snapshot()
        with self._lock:
            snap["outputBufferBytes"] = sum(
                t.buffered_bytes for t in self.tasks.values())
        return snap

    def _stage_page(self, task: WorkerTask, buffer: int, page: bytes,
                    rows: int) -> None:
        """Append one page under backpressure: while the task's staged
        bytes exceed the bound, the producer waits for consumer acks —
        a slow consumer can no longer balloon this worker's memory. A
        single page larger than the bound always proceeds (progress
        guarantee), as does a task leaving RUNNING."""
        import time as _time
        deadline = _time.monotonic() + self.backpressure_timeout_s
        if task.deadline is not None:
            # the query's deadline caps the pause: a query about to
            # expire must not sit 300s behind a dead consumer first
            deadline = min(deadline, task.deadline)
        with task.cond:
            waited = False
            while task.buffered_bytes + len(page) > self.max_buffer_bytes \
                    and task.buffered_bytes > 0 \
                    and task.state == "RUNNING" \
                    and _time.monotonic() < deadline:
                if not waited:
                    waited = True
                    task.backpressure_waits += 1
                    from ..metrics import BACKPRESSURE_WAITS
                    BACKPRESSURE_WAITS.inc()
                task.cond.wait(0.05)
            if waited and task.state == "RUNNING" \
                    and task.buffered_bytes + len(page) > \
                    self.max_buffer_bytes \
                    and _time.monotonic() >= deadline:
                # the degrade-to-unbounded escape hatch fired: count it
                # and name the task so the memory risk is attributable
                from ..metrics import BACKPRESSURE_DEADLINE_DEGRADES
                BACKPRESSURE_DEADLINE_DEGRADES.inc()
                log.warning(
                    "task %s: backpressure wait expired; staging page "
                    "past the %d-byte buffer bound (consumer stalled)",
                    task.task_id, self.max_buffer_bytes)
            task.buffers.setdefault(buffer, []).append(page)
            task.buffered_bytes += len(page)
            task.rows_out += rows
            task.bytes_out += len(page)
        TASK_OUTPUT_ROWS.inc(rows)
        TASK_OUTPUT_BYTES.inc(len(page))
        self._note_live_change(task)

    def _emit(self, task: WorkerTask, arrs, vals) -> None:
        """Stage one result batch into the task's output buffers,
        hash-partitioned when the task has a partition spec. Rows/bytes
        are counted on the host arrays (already materialized — no device
        sync) into the task's TaskStats and the process metrics."""
        rows = len(arrs[0]) if arrs else 0
        if task.partition is None:
            self._stage_page(task, 0, encode_columns(arrs, vals), rows)
            return
        keys, count = task.partition["keys"], task.partition["count"]
        part = partition_assignment(arrs, vals, keys, count)
        for p in range(count):
            m = part == p
            if not m.any():
                continue
            page = encode_columns([a[m] for a in arrs],
                                  [v[m] for v in vals])
            self._stage_page(task, p, page, int(m.sum()))

    def _tracer_for(self, task: WorkerTask) -> Tracer:
        """Worker-side tracer adopting the coordinator's trace context —
        spans stitch under the coordinator span that POSTed the task. No
        traceparent (tracing off for the query) = zero-overhead NOOP."""
        if task.traceparent is None:
            return NOOP
        return Tracer.from_traceparent(task.traceparent,
                                       service=f"worker:{self.node_id}")

    @staticmethod
    def _fold_node_stats(ex, names: Dict[int, str],
                         op_agg: Dict[str, list]) -> None:
        """Aggregate one profiled run's per-node stats into per-operator
        totals [wall_ms, rows, calls, device_ms, host_ms, compile_ms]
        and reset for the next split. Fenced runs (exec/profiler.py)
        carry the device/host/compile split; the components sum to
        wall, so the rollup preserves that invariant per operator."""
        for nid, st in ex.node_stats.items():
            acc = op_agg.setdefault(names.get(nid, "?"),
                                    [0.0, 0, 0, 0.0, 0.0, 0.0])
            acc[0] += st[0] * 1000
            acc[1] += st[1]
            acc[2] += 1
            if len(st) >= 5:
                acc[3] += st[2] * 1000
                acc[4] += st[3] * 1000
                acc[5] += st[4] * 1000
        ex.node_stats = {}

    @staticmethod
    def _live_totals(op_agg: Dict[str, list]) -> tuple:
        """(device_ms, host_ms, compile_ms) totals of an op_agg rollup —
        differenced per split for the live tier attribution."""
        return (sum(v[3] for v in op_agg.values()),
                sum(v[4] for v in op_agg.values()),
                sum(v[5] for v in op_agg.values()))

    def _finalize_stats(self, task: WorkerTask, tracer: Tracer,
                        t_start: float, op_agg: Dict[str, list]) -> None:
        """Roll this task's TaskStats (rows/bytes/wall/operators) and its
        exported spans into the task record the coordinator fetches with
        the terminal status (OperatorStats pyramid: operator -> task).
        On success paths this runs BEFORE the FINISHED transition so a
        consumer that sees the terminal state always sees final stats."""
        strategies = getattr(self._executor, "strategy_decisions", {})
        ops = {op: {"wallMs": round(v[0], 3), "rows": int(v[1]),
                    "calls": int(v[2]), "deviceMs": round(v[3], 3),
                    "hostMs": round(v[4], 3),
                    "compileMs": round(v[5], 3),
                    "strategy": strategies.get(op, ""),
                    # mesh placement (broadcast vs partitioned) rides
                    # beside the strategy; only joins carry one
                    "distribution": strategies.get("JoinDistribution", "")
                    if op == "JoinNode" else ""}
               for op, v in op_agg.items()}
        with task.lock:
            task.stats = {"rowsOut": task.rows_out,
                          "bytesOut": task.bytes_out,
                          "wallMs": round(
                              (time.monotonic() - t_start) * 1000, 3),
                          "splitsDone": task.splits_done,
                          "operators": ops}
            if task.manifest is not None:
                task.stats["manifest"] = task.manifest
            if tracer.enabled:
                task.spans = tracer.export()
        self._executor.flush_metrics()

    def _run(self, task: WorkerTask) -> None:
        from ..batch import batch_from_numpy, batch_to_numpy, bucket_capacity
        with task.lock:
            if task.state != "PENDING":   # canceled before the thread ran
                return
            task.state = "RUNNING"
            task.started_at = time.monotonic()
        self._note_live_change(task)
        self.tasks_run += 1
        tracer = self._tracer_for(task)
        t_start = time.monotonic()
        op_agg: Dict[str, list] = {}
        try:
            if self.injector is not None:
                self.injector.maybe_fail("TASK", task.task_id)
                self.injector.maybe_fail("WORKER_TASK_RUN", task.task_id)
            if task.sources is not None:
                with tracer.span("worker-task", taskId=task.task_id,
                                 node=self.node_id, kind="exchange"):
                    self._run_exchange_consumer(task, tracer, op_agg)
                # final stats/spans land BEFORE the terminal state so a
                # status fetch racing the transition never sees partials
                self._finalize_stats(task, tracer, t_start, op_agg)
                with task.lock:
                    if task.state == "RUNNING":
                        task.state = "FINISHED"
                return
            fragment = decode_fragment(task.fragment_blob)
            root, driver_scan = fragment["root"], fragment["driver"]
            cap = bucket_capacity(max(s.count for s in task.splits)) \
                if task.splits else 1024
            # per-operator profiling: on for traced tasks AND for
            # fragments flagged by the coordinator (EXPLAIN ANALYZE) —
            # pays a per-node device sync for true operator times
            profiling = tracer.enabled or bool(fragment.get("profile"))
            names = {id(n): type(n).__name__ for n in
                     _subtree_nodes_all(root)} if profiling else {}
            # The executor (and its _subst/pool state) is shared by every
            # task on this worker, so the whole pin-builds + splits loop
            # holds _exec_lock: build state pinned across splits must not
            # be clobbered by a concurrent task's cleanup. Device work is
            # serialized by the chip anyway (Trino's analog: one lookup
            # source per build, drivers share it under memory context
            # locking).
            with self._exec_lock, \
                    tracer.span("worker-task", taskId=task.task_id,
                                node=self.node_id,
                                splits=len(task.splits)) as wspan:
                ex = self._executor
                ex._subst.clear()
                ex._subst_opaque.clear()
                # per-task lifetime enforcement: the executor's
                # check_cancel points (chunk/partition/prefetch
                # boundaries) observe this task's deadline and any
                # cancel posted while it runs
                ex._cancel_reason = None
                ex.deadline = task.deadline
                self._current_task_id = task.task_id
                saved_profile = ex.profile
                saved_node_stats = ex.node_stats
                if profiling:
                    ex.profile = True
                    ex.node_stats = {}
                try:
                    # pin maximal driver-free subtrees ONCE per task (join
                    # build sides, HashBuilderOperator's build-once-probe-
                    # many): else every split re-executes every build join
                    with tracer.span("pin-builds"):
                        for sub in _static_subtrees(root, driver_scan):
                            ex._subst[id(sub)] = ex.run(sub)
                    if profiling:
                        self._fold_node_stats(ex, names, op_agg)
                    live_prev = self._live_totals(op_agg)
                    for si, split in enumerate(task.splits):
                        if task.state in ("CANCELED", "ABANDONED"):
                            return
                        if task.deadline is not None and \
                                time.monotonic() > task.deadline:
                            from ..exec.executor import QueryDeadlineError
                            raise QueryDeadlineError(
                                "task deadline exceeded "
                                "(query_max_run_time_s)")
                        if self.injector is not None:
                            # chaos mid-split: CRASH kills the executor
                            # with work half-done (partial pages already
                            # buffered — the coordinator's all-or-nothing
                            # drain discards them), DELAY makes this
                            # worker a straggler (hedge-mitigation target)
                            self.injector.maybe_fail(
                                "WORKER_TASK_RUN",
                                f"{task.task_id}:{si}")
                        data = self.catalog.get_table(
                            split.catalog, split.schema_name, split.table)
                        arrays = [np.asarray(data.columns[i])
                                  [split.start:split.start + split.count]
                                  for i in driver_scan.column_indices]
                        valids = None
                        if data.valids is not None:
                            valids = [
                                None if data.valids[i] is None else
                                np.asarray(data.valids[i])
                                [split.start:split.start + split.count]
                                for i in driver_scan.column_indices]
                        chunk = batch_from_numpy(arrays, valids=valids,
                                                 capacity=cap)
                        ex._subst[id(driver_scan)] = chunk
                        ex._subst_opaque.add(id(driver_scan))
                        sp_t0 = time.monotonic()
                        try:
                            with tracer.span("split", index=si,
                                             rows=split.count):
                                out = ex.run(root)
                        finally:
                            ex._subst.pop(id(driver_scan), None)
                            ex._subst_opaque.discard(id(driver_scan))
                            # per-split outputs die here; pinned builds
                            # keep their reservations until task end
                            ex.release_path_reservations(
                                root, keep=ex._subst)
                        if profiling:
                            self._fold_node_stats(ex, names, op_agg)
                        arrs, vals = batch_to_numpy(out)
                        self._emit(task, arrs, vals)
                        # live tier attribution: fenced device/host/
                        # compile deltas when profiling; unprofiled
                        # splits ride entirely in host (the round-10
                        # convention), so the live so-far numbers match
                        # what _finalize_stats will report
                        sp_wall_ms = (time.monotonic() - sp_t0) * 1000
                        d_dev, d_host, d_comp = 0.0, sp_wall_ms, 0.0
                        if profiling:
                            tot = self._live_totals(op_agg)
                            d_dev = max(0.0, tot[0] - live_prev[0])
                            d_host = max(0.0, tot[1] - live_prev[1])
                            d_comp = max(0.0, tot[2] - live_prev[2])
                            live_prev = tot
                        with task.lock:
                            task.splits_done += 1
                            task.device_ms += d_dev
                            task.host_ms += d_host
                            task.compile_ms += d_comp
                        self._note_live_change(task)
                        self._note_busy(
                            d_dev, max(0.0, sp_wall_ms - d_dev))
                finally:
                    ex.profile = saved_profile
                    ex.node_stats = saved_node_stats
                    ex.deadline = None
                    ex._cancel_reason = None
                    self._current_task_id = None
                    ex._subst.clear()
                    ex._subst_opaque.clear()
                    for b in ex._node_bytes.values():
                        ex.pool.free(b)
                    ex._node_bytes.clear()
                    if wspan is not None and op_agg:
                        # fenced split totals ride the worker-task span
                        # so the stitched trace carries device time, not
                        # just host wall
                        wspan.attributes["deviceMs"] = round(
                            sum(v[3] for v in op_agg.values()), 3)
                        wspan.attributes["hostMs"] = round(
                            sum(v[4] for v in op_agg.values()), 3)
                        wspan.attributes["compileMs"] = round(
                            sum(v[5] for v in op_agg.values()), 3)
            self._finalize_stats(task, tracer, t_start, op_agg)
            with task.lock:
                # a cancel landing during the last split must not be
                # overwritten by FINISHED
                if task.state == "RUNNING":
                    task.state = "FINISHED"
        except Exception as e:        # noqa: BLE001 — task failure boundary
            task.error = f"{type(e).__name__}: {e}\n" + traceback.format_exc()
            with task.lock:
                if task.state not in ("CANCELED", "ABANDONED"):
                    task.state = "FAILED"
        finally:
            # failure/cancel paths (and early returns) still record what
            # completed; success paths already finalized pre-transition
            if not task.stats:
                self._finalize_stats(task, tracer, t_start, op_agg)
            self._note_live_change(task)   # terminal state is a change
            cb = self.on_terminal
            if cb is not None and task.state in ("FINISHED", "FAILED",
                                                 "CANCELED"):
                try:
                    cb(task)
                except Exception:  # noqa: BLE001 — push is best-effort;
                    pass           # the status long-poll still works

    # -- exchange consumer: worker<->worker partitioned shuffle ------------

    def _pull_buffer(self, uri: str, task_id: str, buffer: int,
                     deadline: float, task: WorkerTask,
                     tracer: Tracer = NOOP,
                     ack: bool = True) -> List[bytes]:
        """Pull one upstream buffer to completion (the worker-side twin
        of the coordinator's RemoteTask.drain — HttpPageBufferClient's
        loop, running worker-to-worker). The consumer's trace context
        rides the pull requests so cross-worker data-plane hops appear
        in the stitched query trace."""
        import json as _json
        import time as _time
        from urllib.request import Request, urlopen
        from .security import internal_headers
        headers = {"Accept": "application/x-trino-pages",
                   **internal_headers()}
        tp = tracer.traceparent()
        if tp is not None:
            headers["traceparent"] = tp
        pages: List[bytes] = []
        token = 0
        while _time.time() < deadline:
            if task.state in ("CANCELED", "ABANDONED"):
                raise RuntimeError("task canceled during exchange pull")
            req = Request(
                f"{uri}/v1/task/{task_id}/results/{buffer}/{token}"
                + ("" if ack else "?ack=0"),
                headers=headers)
            with urlopen(req, timeout=30.0) as resp:
                body = resp.read()
                if resp.headers.get("Content-Type", "").startswith(
                        "application/x-trino-pages"):
                    # worker<->worker frames get the same CRC32C gate as
                    # the coordinator drain; PageChecksumError fails THIS
                    # task, which the coordinator sees and retries
                    from .pageserde import verify_page
                    verify_page(bytes(body))
                    pages.append(bytes(body))
                    token += 1
                    continue
                out = _json.loads(body.decode()) if body else {}
            if out.get("state") == "FAILED":
                raise RuntimeError(
                    f"upstream task {task_id} failed: {out.get('error')}")
            if out.get("complete"):
                return pages
            _time.sleep(0.02)
        raise RuntimeError(f"exchange pull from {task_id} timed out")

    def _run_exchange_consumer(self, task: WorkerTask,
                               tracer: Tracer = NOOP,
                               op_agg: Dict[str, list] = None) -> None:
        """Execute a fragment whose leaves are RemoteSourceNodes: pull
        each source's partition from the upstream tasks, bind the
        concatenated batches, run once, emit (re-partitioned when the
        task has a partition spec). Pulls happen BEFORE taking the
        executor lock so an upstream task on this same worker can finish
        producing while we wait."""
        import time as _time

        from ..batch import batch_from_numpy
        from ..planner import logical as L
        fragment = decode_fragment(task.fragment_blob)
        root = fragment["root"]
        writer = None
        if isinstance(root, L.TableWriterNode):
            # write-stage task: execute the subtree, then stage the rows
            # to an attempt file instead of emitting exchange pages
            writer, root = root, root.child
        deadline = _time.time() + float(fragment.get("timeout_s", 300.0))
        if task.deadline is not None:
            # the query deadline caps exchange pulls too: a consumer must
            # not out-wait the query it feeds
            deadline = min(deadline, _time.time() + max(
                0.0, task.deadline - time.monotonic()))

        from ..planner.fragmenter import _subtree_nodes
        by_fid = {}
        for n in _subtree_nodes(root):
            if isinstance(n, L.RemoteSourceNode):
                by_fid.setdefault(n.fragment_id, []).append(n)
        batches = {}
        for fid_str, srcs in task.sources.items():
            fid = int(fid_str)
            pages = []
            for s in srcs:
                with tracer.span("exchange-pull", uri=s["uri"],
                                 upstreamTask=s["taskId"],
                                 buffer=int(s.get("buffer", 0))):
                    pages.extend(self._pull_buffer(
                        s["uri"], s["taskId"], int(s.get("buffer", 0)),
                        deadline, task, tracer,
                        ack=writer is None))
            nodes = by_fid.get(fid)
            arrs, vals = concat_pages(
                pages, nodes[0].output if nodes else ())
            batches[fid] = batch_from_numpy(arrs, valids=vals)

        from ..batch import batch_to_numpy
        names = {id(n): type(n).__name__
                 for n in _subtree_nodes_all(root)} if tracer.enabled else {}
        with self._exec_lock:
            ex = self._executor
            ex._subst.clear()
            ex._subst_opaque.clear()
            ex._cancel_reason = None
            ex.deadline = task.deadline
            self._current_task_id = task.task_id
            saved_merge = ex.enable_merge_join
            saved_profile = ex.profile
            saved_node_stats = ex.node_stats
            if tracer.enabled:
                ex.profile = True
                ex.node_stats = {}
            # partition sizes differ per consumer task, so the merge-sort
            # kernel's multi-operand XLA sort would recompile per shape —
            # and that compile is pathological (minutes even at tiny
            # shapes). The dense-LUT/expansion paths compile in seconds
            # at any size; pin the consumer to them.
            ex.enable_merge_join = False
            try:
                for fid, nodes in by_fid.items():
                    for n in nodes:
                        ex._subst[id(n)] = batches[fid]
                        ex._subst_opaque.add(id(n))
                with tracer.span("consume-run"):
                    out = ex.run(root)
                if tracer.enabled and op_agg is not None:
                    self._fold_node_stats(ex, names, op_agg)
                arrs, vals = batch_to_numpy(out)
            finally:
                ex.enable_merge_join = saved_merge
                ex.profile = saved_profile
                ex.node_stats = saved_node_stats
                ex.deadline = None
                ex._cancel_reason = None
                self._current_task_id = None
                ex._subst.clear()
                ex._subst_opaque.clear()
                for b in ex._node_bytes.values():
                    ex.pool.free(b)
                ex._node_bytes.clear()
        if writer is not None:
            self._stage_write(task, writer, arrs, vals)
            return
        self._emit(task, arrs, vals)
        # terminal state is set by _run AFTER stats finalize — a status
        # fetch racing completion must never see FINISHED + partial stats

    def _stage_write(self, task: WorkerTask, writer,
                     arrs, vals) -> None:
        """Write-stage terminal: rows land in a uniquely-named attempt
        file under `<table>/.staging/`; the manifest (path, rows, CRC,
        zone stats) rides the terminal task status. The write buffer is
        a memory-pool reservation for its lifetime — a worker near its
        memory limit fails the attempt instead of silently ballooning."""
        from ..batch import Schema
        from ..connectors.tpch.datagen import TableData
        from . import writeprotocol as wp
        arrays = [np.asarray(a) for a in arrs]
        valids = None
        if vals is not None and any(v is not None and not bool(np.all(v))
                                    for v in vals):
            valids = [None if v is None or bool(np.all(v))
                      else np.asarray(v) for v in vals]
        data = TableData(writer.table, Schema(tuple(writer.fields)),
                         arrays, valids=valids)
        nbytes = sum(a.nbytes for a in arrays)
        ex = self._executor
        ex.pool.reserve(nbytes, tag=f"write:{task.task_id}")
        try:
            m = wp.stage_table_data(
                writer.table_dir, data, writer.query_id, writer.stage,
                writer.partition, writer.attempt or task.task_id,
                writer.fmt, injector=self.injector)
        finally:
            ex.pool.free(nbytes, tag=f"write:{task.task_id}")
        with task.lock:
            task.manifest = m
            task.rows_out += m["rows"]
            task.bytes_out += m["bytes"]

    def status_json(self, task: WorkerTask) -> dict:
        with task.lock:      # buffers/acked mutate on the task thread
            done = task.state in ("FINISHED", "FAILED", "CANCELED",
                                  "ABANDONED")
            stats = dict(task.stats) if task.stats else {
                "rowsOut": task.rows_out, "bytesOut": task.bytes_out,
                "splitsDone": task.splits_done}
            out = {"taskId": task.task_id, "state": task.state,
                   "error": task.error.splitlines()[0]
                   if task.error else "",
                   "splitsDone": task.splits_done,
                   "pages": task.total_pages(),
                   "stats": stats}
            if done and task.spans:
                # spans ship only with terminal status (one fetch per
                # task, not per poll)
                out["spans"] = list(task.spans)
            return out
