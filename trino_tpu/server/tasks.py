"""Worker-side task execution: the engine's L5.

Reference: TaskResource (server/TaskResource.java:93 — createOrUpdateTask
:146, results :332, ack :372, fail :319) backed by SqlTaskManager
(execution/SqlTaskManager.java:107, updateTask:491) and SqlTaskExecution
(execution/SqlTaskExecution.java:81): the coordinator POSTs a plan fragment
plus split assignments; the worker runs the fragment over each split and
stages output pages for downstream pull.

TPU adaptation: a *fragment* is a pickled logical-plan subtree whose leaf
scan is replaced per split by a row-range of the table (split scheduling,
SourcePartitionedScheduler.java:247's batches); the worker executes it with
its own Executor (its slice of TPU devices) and serves *partial result
pages* (host numpy columns) — the PARTIAL side of Trino's exchange. The
final stage merges on the coordinator. Output pages use token-based pull
with acks, the OutputBuffer protocol (execution/buffer/
PartitionedOutputBuffer.java:42) reduced to its sequential-consumer core.
"""

from __future__ import annotations

import base64
import threading
import traceback
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np


# --------------------------------------------------------------------------
# wire serde: numpy column sets and plan fragments
# (PagesSerde's role, execution/buffer/CompressingEncryptingPageSerializer.java:60)
# Pages are length-prefixed binary frames with zstd/zlib compression
# (server/pageserde.py); the worker serves them raw on the binary results
# route and base64-wrapped on the legacy JSON route.
# --------------------------------------------------------------------------

def encode_columns(arrays: List[np.ndarray],
                   valids: List[np.ndarray]) -> bytes:
    from .pageserde import encode_page
    return encode_page(arrays, valids)


def decode_columns(page) -> tuple:
    """Accepts a binary frame (bytes), its base64 JSON wrapping
    ({"b64": ...}), or the round-3 dict layout (rolling upgrade)."""
    from .pageserde import decode_page
    if isinstance(page, (bytes, bytearray)):
        return decode_page(bytes(page))
    if isinstance(page, dict) and "b64" in page:
        return decode_page(base64.b64decode(page["b64"]))
    arrays, valids = [], []
    for c in page["columns"]:
        a = np.frombuffer(base64.b64decode(c["data"]),
                          dtype=np.dtype(c["dtype"]))
        v = np.frombuffer(base64.b64decode(c["valid"]), dtype=np.bool_)
        arrays.append(a)
        valids.append(v)
    return arrays, valids


def encode_fragment(root) -> str:
    """Plan subtree -> wire form: a data-only JSON serde (server/serde.py),
    the analog of the reference's Jackson-serialized PlanFragment — a
    crafted POST body can at worst build a malformed plan, never run code."""
    from . import serde
    return serde.dumps(root)


def decode_fragment(blob: str):
    from . import serde
    return serde.loads(blob)


def _static_subtrees(root, driver) -> list:
    """Maximal subtrees of `root` that do not contain the driver scan —
    join build sides and friends, constant across splits. Bare scans and
    values leaves are excluded (the scan cache already memoizes them)."""
    from ..planner import logical as L
    memo = {}

    def contains(n) -> bool:
        r = memo.get(id(n))
        if r is None:
            r = n is driver or any(contains(c) for c in L.children(n))
            memo[id(n)] = r
        return r

    out = []

    def walk(n):
        for c in L.children(n):
            if contains(c):
                walk(c)
            elif not isinstance(c, (L.ScanNode, L.ValuesNode)):
                out.append(c)

    if contains(root):
        walk(root)
    return out


@dataclass(frozen=True)
class Split:
    """A row-range of one table (ConnectorSplit reduced to the range case;
    the tpch/tpcds/memory connectors are all range-splittable)."""
    catalog: str
    schema_name: str
    table: str
    start: int
    count: int


# --------------------------------------------------------------------------
# task state + manager
# --------------------------------------------------------------------------

TASK_STATES = ("PENDING", "RUNNING", "FINISHED", "FAILED", "CANCELED")


@dataclass
class WorkerTask:
    task_id: str
    fragment_blob: str
    splits: List[Split]
    state: str = "PENDING"
    error: str = ""
    pages: List[bytes] = field(default_factory=list)  # binary page frames
    acked: int = 0                 # tokens below this are released
    splits_done: int = 0
    lock: threading.Lock = field(default_factory=threading.Lock)


class TaskManager:
    """SqlTaskManager's role: registry + execution of tasks on this
    worker. Execution runs on a worker thread per task; the handler
    returns immediately (the reference's updateTask is async the same
    way)."""

    def __init__(self, catalog, injector=None):
        self.catalog = catalog
        self.tasks: Dict[str, WorkerTask] = {}
        self._lock = threading.Lock()
        self.injector = injector          # FailureInjector hook
        self.tasks_run = 0                # observability counter
        # one Executor per worker: kernels are jitted process-wide anyway;
        # the lock serializes device use within this worker
        from ..exec.executor import Executor
        self._executor = Executor(catalog)
        self._exec_lock = threading.Lock()

    def create_or_update(self, task_id: str, fragment_blob: str,
                         splits: List[Split]) -> WorkerTask:
        with self._lock:
            task = self.tasks.get(task_id)
            if task is None:
                task = WorkerTask(task_id, fragment_blob, splits)
                self.tasks[task_id] = task
                t = threading.Thread(target=self._run, args=(task,),
                                     name=f"task-{task_id}", daemon=True)
                t.start()
            return task

    def get(self, task_id: str) -> Optional[WorkerTask]:
        return self.tasks.get(task_id)

    def cancel(self, task_id: str) -> None:
        task = self.tasks.get(task_id)
        if task is not None:
            with task.lock:
                if task.state in ("PENDING", "RUNNING"):
                    task.state = "CANCELED"


    def _run(self, task: WorkerTask) -> None:
        from ..batch import batch_from_numpy, batch_to_numpy, pad_capacity
        with task.lock:
            if task.state != "PENDING":   # canceled before the thread ran
                return
            task.state = "RUNNING"
        self.tasks_run += 1
        try:
            if self.injector is not None:
                self.injector.maybe_fail("TASK", task.task_id)
            fragment = decode_fragment(task.fragment_blob)
            root, driver_scan = fragment["root"], fragment["driver"]
            cap = pad_capacity(max(s.count for s in task.splits)) \
                if task.splits else 1024
            # The executor (and its _subst/pool state) is shared by every
            # task on this worker, so the whole pin-builds + splits loop
            # holds _exec_lock: build state pinned across splits must not
            # be clobbered by a concurrent task's cleanup. Device work is
            # serialized by the chip anyway (Trino's analog: one lookup
            # source per build, drivers share it under memory context
            # locking).
            with self._exec_lock:
                ex = self._executor
                ex._subst.clear()
                ex._subst_opaque.clear()
                try:
                    # pin maximal driver-free subtrees ONCE per task (join
                    # build sides, HashBuilderOperator's build-once-probe-
                    # many): else every split re-executes every build join
                    for sub in _static_subtrees(root, driver_scan):
                        ex._subst[id(sub)] = ex.run(sub)
                    for split in task.splits:
                        if task.state == "CANCELED":
                            return
                        data = self.catalog.get_table(
                            split.catalog, split.schema_name, split.table)
                        arrays = [np.asarray(data.columns[i])
                                  [split.start:split.start + split.count]
                                  for i in driver_scan.column_indices]
                        valids = None
                        if data.valids is not None:
                            valids = [
                                None if data.valids[i] is None else
                                np.asarray(data.valids[i])
                                [split.start:split.start + split.count]
                                for i in driver_scan.column_indices]
                        chunk = batch_from_numpy(arrays, valids=valids,
                                                 capacity=cap)
                        ex._subst[id(driver_scan)] = chunk
                        ex._subst_opaque.add(id(driver_scan))
                        try:
                            out = ex.run(root)
                        finally:
                            ex._subst.pop(id(driver_scan), None)
                            ex._subst_opaque.discard(id(driver_scan))
                            # per-split outputs die here; pinned builds
                            # keep their reservations until task end
                            ex.release_path_reservations(
                                root, keep=ex._subst)
                        arrs, vals = batch_to_numpy(out)
                        page = encode_columns(arrs, vals)
                        with task.lock:
                            task.pages.append(page)
                            task.splits_done += 1
                finally:
                    ex._subst.clear()
                    ex._subst_opaque.clear()
                    for b in ex._node_bytes.values():
                        ex.pool.free(b)
                    ex._node_bytes.clear()
            with task.lock:
                # a cancel landing during the last split must not be
                # overwritten by FINISHED
                if task.state == "RUNNING":
                    task.state = "FINISHED"
        except Exception as e:        # noqa: BLE001 — task failure boundary
            task.error = f"{type(e).__name__}: {e}\n" + traceback.format_exc()
            with task.lock:
                if task.state != "CANCELED":
                    task.state = "FAILED"

    def status_json(self, task: WorkerTask) -> dict:
        return {"taskId": task.task_id, "state": task.state,
                "error": task.error.splitlines()[0] if task.error else "",
                "splitsDone": task.splits_done,
                "pages": len(task.pages)}
