"""Parquet reader/writer — pure numpy, no external dependencies.

Reference: lib/trino-parquet (reader/ParquetReader.java:103, writer/) —
the columnar file format tier. This implementation covers the flat subset
the engine's column model needs:

- physical types BOOLEAN / INT32 / INT64 / DOUBLE / BYTE_ARRAY
- PLAIN value encoding; RLE/bit-packed hybrid definition levels
- optional (nullable) flat columns, required columns
- dictionary-encoded BYTE_ARRAY pages (PLAIN_DICTIONARY) on read
- UNCOMPRESSED codec (no compression libraries in this environment;
  the codec field is validated and other codecs rejected loudly)

The thrift compact protocol (footer metadata serde) is implemented here
directly — parquet's metadata is a small fixed set of structs and carrying
a thrift library for it would be the only use.

Layout written: PAR1 | column chunks (one data page each, dictionary page
first for dictionary-encoded columns) | FileMetaData | footer_len | PAR1.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

MAGIC = b"PAR1"

# thrift compact type codes
CT_BOOL_TRUE, CT_BOOL_FALSE = 1, 2
CT_BYTE, CT_I16, CT_I32, CT_I64, CT_DOUBLE = 3, 4, 5, 6, 7
CT_BINARY, CT_LIST, CT_SET, CT_MAP, CT_STRUCT = 8, 9, 10, 11, 12

# parquet enums
T_BOOLEAN, T_INT32, T_INT64, T_INT96, T_FLOAT, T_DOUBLE, T_BYTE_ARRAY = \
    0, 1, 2, 3, 4, 5, 6
REP_REQUIRED, REP_OPTIONAL, REP_REPEATED = 0, 1, 2
ENC_PLAIN, ENC_PLAIN_DICTIONARY, ENC_RLE, ENC_RLE_DICTIONARY = 0, 2, 3, 8
CODEC_UNCOMPRESSED = 0
PAGE_DATA, PAGE_INDEX, PAGE_DICTIONARY = 0, 1, 2


# --------------------------------------------------------------------------
# thrift compact protocol
# --------------------------------------------------------------------------

def _uvarint(b: bytes, pos: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        x = b[pos]
        pos += 1
        out |= (x & 0x7F) << shift
        if not x & 0x80:
            return out, pos
        shift += 7


def _zigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _enc_uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        if n < 0x80:
            out.append(n)
            return bytes(out)
        out.append((n & 0x7F) | 0x80)
        n >>= 7


def _enc_zigzag(n: int) -> bytes:
    return _enc_uvarint((n << 1) ^ (n >> 63) if n < 0 else n << 1)


class ThriftReader:
    def __init__(self, data: bytes, pos: int = 0):
        self.b = data
        self.pos = pos

    def read_struct(self) -> Dict[int, object]:
        """Generic struct -> {field_id: value}; nested structs/lists
        recurse. Types are resolved by the caller from field ids."""
        fields: Dict[int, object] = {}
        last_id = 0
        while True:
            header = self.b[self.pos]
            self.pos += 1
            if header == 0:
                return fields
            delta = header >> 4
            ctype = header & 0x0F
            if delta == 0:
                fid, self.pos = _uvarint(self.b, self.pos)
                fid = _zigzag(fid)
            else:
                fid = last_id + delta
            last_id = fid
            fields[fid] = self._read_value(ctype)

    def _read_value(self, ctype: int):
        if ctype == CT_BOOL_TRUE:
            return True
        if ctype == CT_BOOL_FALSE:
            return False
        if ctype in (CT_BYTE,):
            v = self.b[self.pos]
            self.pos += 1
            return v
        if ctype in (CT_I16, CT_I32, CT_I64):
            v, self.pos = _uvarint(self.b, self.pos)
            return _zigzag(v)
        if ctype == CT_DOUBLE:
            v = struct.unpack("<d", self.b[self.pos:self.pos + 8])[0]
            self.pos += 8
            return v
        if ctype == CT_BINARY:
            n, self.pos = _uvarint(self.b, self.pos)
            v = self.b[self.pos:self.pos + n]
            self.pos += n
            return v
        if ctype in (CT_LIST, CT_SET):
            header = self.b[self.pos]
            self.pos += 1
            size = header >> 4
            etype = header & 0x0F
            if size == 15:
                size, self.pos = _uvarint(self.b, self.pos)
            return [self._read_value(etype) for _ in range(size)]
        if ctype == CT_STRUCT:
            return self.read_struct()
        raise ValueError(f"unsupported thrift compact type {ctype}")


class ThriftWriter:
    def __init__(self):
        self.parts: List[bytes] = []

    def struct(self, fields: List[Tuple[int, int, object]]) -> bytes:
        """fields: [(field_id, ctype, value)] in ascending id order."""
        out = bytearray()
        last_id = 0
        for fid, ctype, value in fields:
            delta = fid - last_id
            wire_type = ctype
            if ctype in (CT_BOOL_TRUE, CT_BOOL_FALSE):
                wire_type = CT_BOOL_TRUE if value else CT_BOOL_FALSE
            if 0 < delta <= 15:
                out.append((delta << 4) | wire_type)
            else:
                out.append(wire_type)
                out += _enc_zigzag(fid)
            last_id = fid
            out += self._enc_value(ctype, value)
        out.append(0)
        return bytes(out)

    def _enc_value(self, ctype: int, value) -> bytes:
        if ctype in (CT_BOOL_TRUE, CT_BOOL_FALSE):
            return b""
        if ctype == CT_BYTE:
            return bytes([value & 0xFF])
        if ctype in (CT_I16, CT_I32, CT_I64):
            return _enc_zigzag(int(value))
        if ctype == CT_DOUBLE:
            return struct.pack("<d", value)
        if ctype == CT_BINARY:
            v = value.encode() if isinstance(value, str) else value
            return _enc_uvarint(len(v)) + v
        if ctype in (CT_STRUCT, CT_LIST, CT_SET):
            return value                  # pre-encoded struct/list bytes
        raise ValueError(f"cannot encode thrift type {ctype}")

    def list_of(self, etype: int, items: List[bytes]) -> bytes:
        n = len(items)
        if n < 15:
            header = bytes([(n << 4) | etype])
        else:
            header = bytes([0xF0 | etype]) + _enc_uvarint(n)
        return header + b"".join(items)


# --------------------------------------------------------------------------
# RLE / bit-packed hybrid (definition levels, dictionary indices)
# --------------------------------------------------------------------------

def rle_decode(data: bytes, bit_width: int, count: int) -> np.ndarray:
    out = np.empty(count, dtype=np.int32)
    pos = 0
    filled = 0
    byte_width = (bit_width + 7) // 8
    while filled < count:
        header, pos = _uvarint(data, pos)
        if header & 1:                      # bit-packed run
            groups = header >> 1
            n = groups * 8
            raw = np.frombuffer(data, dtype=np.uint8, count=groups *
                                bit_width, offset=pos)
            pos += groups * bit_width
            bits = np.unpackbits(raw, bitorder="little")
            vals = bits.reshape(-1, bit_width)
            weights = (1 << np.arange(bit_width)).astype(np.int64)
            decoded = (vals * weights).sum(axis=1).astype(np.int32)
            take = min(n, count - filled)
            out[filled:filled + take] = decoded[:take]
            filled += take
        else:                               # RLE run
            n = header >> 1
            v = int.from_bytes(data[pos:pos + byte_width], "little")
            pos += byte_width
            take = min(n, count - filled)
            out[filled:filled + take] = v
            filled += take
    return out


def rle_encode_bitpacked(values: np.ndarray, bit_width: int) -> bytes:
    """Encode as one bit-packed run (padded to a multiple of 8)."""
    n = len(values)
    groups = (n + 7) // 8
    padded = np.zeros(groups * 8, dtype=np.int64)
    padded[:n] = values
    bits = ((padded[:, None] >> np.arange(bit_width)) & 1).astype(np.uint8)
    packed = np.packbits(bits.reshape(-1), bitorder="little")
    return _enc_uvarint((groups << 1) | 1) + packed.tobytes()


# --------------------------------------------------------------------------
# writer
# --------------------------------------------------------------------------

_PHYS_FOR_DTYPE = {
    np.dtype(np.int64): T_INT64,
    np.dtype(np.int32): T_INT32,
    np.dtype(np.float64): T_DOUBLE,
    np.dtype(np.bool_): T_BOOLEAN,
}


def _plain_encode(phys: int, arr: np.ndarray) -> bytes:
    if phys == T_INT64:
        return np.ascontiguousarray(arr, dtype="<i8").tobytes()
    if phys == T_INT32:
        return np.ascontiguousarray(arr, dtype="<i4").tobytes()
    if phys == T_DOUBLE:
        return np.ascontiguousarray(arr, dtype="<f8").tobytes()
    if phys == T_BOOLEAN:
        return np.packbits(arr.astype(np.uint8),
                           bitorder="little").tobytes()
    if phys == T_BYTE_ARRAY:
        parts = []
        for s in arr:
            b = s.encode() if isinstance(s, str) else bytes(s)
            parts.append(struct.pack("<I", len(b)) + b)
        return b"".join(parts)
    raise ValueError(f"unsupported physical type {phys}")


CONV_UTF8, CONV_DECIMAL, CONV_DATE = 0, 5, 6


def write_parquet(path: str, names: List[str], arrays: List[np.ndarray],
                  valids: Optional[List[Optional[np.ndarray]]] = None,
                  logicals: Optional[List[Optional[tuple]]] = None) \
        -> None:
    """Write flat columns to a single-row-group parquet file.

    Object/str arrays become BYTE_ARRAY (UTF8). A valids mask marks the
    column OPTIONAL with RLE/bit-packed definition levels. `logicals`
    annotates columns with converted types: ("decimal", precision, scale)
    on INT64, ("date",) on INT32.
    """
    n_rows = len(arrays[0]) if arrays else 0
    valids = valids if valids is not None else [None] * len(arrays)
    logicals = logicals if logicals is not None else [None] * len(arrays)
    tw = ThriftWriter()
    body = bytearray(MAGIC)

    col_metas: List[bytes] = []
    for name, arr, valid in zip(names, arrays, valids):
        arr = np.asarray(arr)
        if arr.dtype.kind in ("U", "O", "S"):
            phys = T_BYTE_ARRAY
        else:
            if arr.dtype not in _PHYS_FOR_DTYPE:
                arr = arr.astype(np.int64)
            phys = _PHYS_FOR_DTYPE[arr.dtype]
        optional = valid is not None
        offset = len(body)

        if optional:
            defs = rle_encode_bitpacked(
                np.asarray(valid).astype(np.int64), 1)
            def_block = struct.pack("<I", len(defs)) + defs
            present = arr[np.asarray(valid)]
        else:
            def_block = b""
            present = arr
        payload = def_block + _plain_encode(phys, present)

        page_header = tw.struct([
            (1, CT_I32, PAGE_DATA),
            (2, CT_I32, len(payload)),
            (3, CT_I32, len(payload)),
            (5, CT_STRUCT, tw.struct([
                (1, CT_I32, n_rows),
                (2, CT_I32, ENC_PLAIN),
                (3, CT_I32, ENC_RLE),
                (4, CT_I32, ENC_RLE),
            ])),
        ])
        body += page_header + payload

        col_meta = tw.struct([
            (1, CT_I32, phys),
            (2, CT_LIST, tw.list_of(CT_I32, [_enc_zigzag(ENC_PLAIN),
                                             _enc_zigzag(ENC_RLE)])),
            (3, CT_LIST, tw.list_of(CT_BINARY,
                                    [_enc_uvarint(len(name.encode())) +
                                     name.encode()])),
            (4, CT_I32, CODEC_UNCOMPRESSED),
            (5, CT_I64, n_rows),
            (6, CT_I64, len(payload)),
            (7, CT_I64, len(payload)),
            (9, CT_I64, offset),
        ])
        col_metas.append(tw.struct([
            (2, CT_I64, offset),
            (3, CT_STRUCT, col_meta),
        ]))

    # schema: root group + one element per column
    schema_elems = [tw.struct([
        (4, CT_BINARY, "schema"),
        (5, CT_I32, len(names)),
    ])]
    for name, arr, valid, logical in zip(names, arrays, valids, logicals):
        arr = np.asarray(arr)
        if arr.dtype.kind in ("U", "O", "S"):
            phys = T_BYTE_ARRAY
        else:
            if arr.dtype not in _PHYS_FOR_DTYPE:
                arr = arr.astype(np.int64)
            phys = _PHYS_FOR_DTYPE[arr.dtype]
        fields = [(1, CT_I32, phys),
                  (3, CT_I32, REP_OPTIONAL if valid is not None
                   else REP_REQUIRED),
                  (4, CT_BINARY, name)]
        if phys == T_BYTE_ARRAY:
            fields.append((6, CT_I32, CONV_UTF8))
        elif logical is not None and logical[0] == "decimal":
            fields.append((6, CT_I32, CONV_DECIMAL))
            fields.append((7, CT_I32, logical[2]))     # scale
            fields.append((8, CT_I32, logical[1]))     # precision
        elif logical is not None and logical[0] == "date":
            fields.append((6, CT_I32, CONV_DATE))
        schema_elems.append(tw.struct(fields))

    row_group = tw.struct([
        (1, CT_LIST, tw.list_of(CT_STRUCT, col_metas)),
        (2, CT_I64, sum(len(c) for c in col_metas)),
        (3, CT_I64, n_rows),
    ])
    footer = tw.struct([
        (1, CT_I32, 1),
        (2, CT_LIST, tw.list_of(CT_STRUCT, schema_elems)),
        (3, CT_I64, n_rows),
        (4, CT_LIST, tw.list_of(CT_STRUCT, [row_group])),
    ])
    body += footer
    body += struct.pack("<I", len(footer))
    body += MAGIC
    with open(path, "wb") as f:
        f.write(bytes(body))


# --------------------------------------------------------------------------
# reader
# --------------------------------------------------------------------------

class ParquetColumn:
    def __init__(self, name: str, phys: int, optional: bool):
        self.name = name
        self.phys = phys
        self.optional = optional
        self.values: Optional[np.ndarray] = None
        self.valid: Optional[np.ndarray] = None


def _plain_decode(phys: int, data: bytes, count: int):
    if phys == T_INT64:
        return np.frombuffer(data, dtype="<i8", count=count)
    if phys == T_INT32:
        return np.frombuffer(data, dtype="<i4", count=count)
    if phys == T_DOUBLE:
        return np.frombuffer(data, dtype="<f8", count=count)
    if phys == T_BOOLEAN:
        bits = np.unpackbits(np.frombuffer(data, dtype=np.uint8),
                             bitorder="little")
        return bits[:count].astype(np.bool_)
    if phys == T_BYTE_ARRAY:
        out = []
        pos = 0
        for _ in range(count):
            (ln,) = struct.unpack_from("<I", data, pos)
            pos += 4
            out.append(data[pos:pos + ln].decode("utf-8", "replace"))
            pos += ln
        return np.array(out, dtype=object)
    raise ValueError(f"unsupported physical type {phys}")


def read_parquet(path: str):
    """Read a flat parquet file -> (names, columns, valids, logicals).

    logicals[i] is None, ("decimal", precision, scale), or ("date",)."""
    with open(path, "rb") as f:
        blob = f.read()
    if blob[:4] != MAGIC or blob[-4:] != MAGIC:
        raise ValueError("not a parquet file")
    (footer_len,) = struct.unpack("<I", blob[-8:-4])
    footer = ThriftReader(blob, len(blob) - 8 - footer_len).read_struct()

    schema = footer[2]
    num_rows = footer[3]
    elems = []
    for raw in schema[1:]:                      # skip the root group
        phys = raw.get(1)
        rep = raw.get(3, REP_REQUIRED)
        name = raw[4].decode()
        conv = raw.get(6)
        logical = None
        if conv == CONV_DECIMAL:
            logical = ("decimal", raw.get(8, 18), raw.get(7, 0))
        elif conv == CONV_DATE:
            logical = ("date",)
        elems.append((name, phys, rep == REP_OPTIONAL, logical))

    names: List[str] = []
    columns: List[np.ndarray] = []
    valids: List[Optional[np.ndarray]] = []
    logicals: List[Optional[tuple]] = []
    row_groups = footer[4]
    if len(row_groups) != 1:
        raise ValueError("multi-row-group files not supported yet")
    chunks = row_groups[0][1]
    for (name, phys, optional, logical), chunk in zip(elems, chunks):
        meta = chunk[3]
        if meta.get(4, CODEC_UNCOMPRESSED) != CODEC_UNCOMPRESSED:
            raise ValueError(
                f"column {name}: only UNCOMPRESSED codec supported")
        n_values = meta[5]
        offset = meta.get(9)
        dict_offset = meta.get(11)
        start = dict_offset if dict_offset is not None else offset
        vals, valid = _read_chunk(blob, start, phys, optional, n_values)
        names.append(name)
        columns.append(vals)
        valids.append(valid)
        logicals.append(logical)
    assert all(len(c) == num_rows for c in columns)
    return names, columns, valids, logicals


def _read_chunk(blob: bytes, pos: int, phys: int, optional: bool,
                n_values: int):
    """Read pages at `pos` until n_values are decoded. Handles an
    optional leading dictionary page (PLAIN_DICTIONARY data pages)."""
    dictionary = None
    values = np.empty(0, dtype=object)
    got = 0
    out_parts = []
    def_parts = []
    while got < n_values:
        tr = ThriftReader(blob, pos)
        header = tr.read_struct()
        page_type = header[1]
        size = header[3]
        data = blob[tr.pos:tr.pos + size]
        pos = tr.pos + size
        if page_type == PAGE_DICTIONARY:
            dph = header[7]
            dictionary = _plain_decode(phys, data, dph[1])
            continue
        dph = header[5]
        count = dph[1]
        encoding = dph[2]
        body = data
        valid = None
        if optional:
            (dl_len,) = struct.unpack_from("<I", body, 0)
            defs = rle_decode(body[4:4 + dl_len], 1, count)
            valid = defs.astype(np.bool_)
            body = body[4 + dl_len:]
            n_present = int(valid.sum())
        else:
            n_present = count
        if encoding in (ENC_PLAIN_DICTIONARY, ENC_RLE_DICTIONARY):
            bit_width = body[0]
            idx = rle_decode(body[1:], bit_width, n_present)
            present = dictionary[idx]
        else:
            present = _plain_decode(phys, body, n_present)
        if optional:
            full = np.zeros(count, dtype=present.dtype)
            if present.dtype == object:
                full = np.full(count, "", dtype=object)
            full[valid] = present
            out_parts.append(full)
            def_parts.append(valid)
        else:
            out_parts.append(present)
        got += count
    vals = np.concatenate(out_parts) if len(out_parts) > 1 else \
        out_parts[0]
    valid_arr = None
    if optional:
        valid_arr = np.concatenate(def_parts) if len(def_parts) > 1 else \
            def_parts[0]
    return vals, valid_arr
