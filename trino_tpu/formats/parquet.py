"""Parquet reader/writer — from scratch (numpy; zstandard only for the
optional ZSTD codec).

Reference: lib/trino-parquet (reader/ParquetReader.java:103, writer/) —
the columnar file format tier. Coverage:

- physical types BOOLEAN / INT32 / INT64 / DOUBLE / BYTE_ARRAY
- PLAIN value encoding; RLE/bit-packed hybrid definition levels
- optional (nullable) columns; repeated leaves (3-level LIST) read as
  per-row tuples via definition+repetition level assembly
- dictionary-encoded pages (PLAIN_DICTIONARY / RLE_DICTIONARY) on read
- codecs: UNCOMPRESSED always; SNAPPY and LZ4_RAW via from-scratch
  block decoders (the two formats are byte-oriented LZ77 variants);
  GZIP/ZLIB via the stdlib; ZSTD via the optional zstandard package
  (loud error when absent). BROTLI is rejected loudly.
- multiple row groups; per-chunk min/max statistics on write; row-group
  skipping from statistics given predicate ranges (the reader-side
  analog of trino-parquet's predicate pushdown,
  reader/ParquetReader.java row-group filtering)

The thrift compact protocol (footer metadata serde) is implemented here
directly — parquet's metadata is a small fixed set of structs and carrying
a thrift library for it would be the only use.

Layout written: PAR1 | row groups of column chunks (one data page each,
dictionary page first for dictionary-encoded columns) | FileMetaData |
footer_len | PAR1.
"""

from __future__ import annotations

import struct
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

MAGIC = b"PAR1"

# thrift compact type codes
CT_BOOL_TRUE, CT_BOOL_FALSE = 1, 2
CT_BYTE, CT_I16, CT_I32, CT_I64, CT_DOUBLE = 3, 4, 5, 6, 7
CT_BINARY, CT_LIST, CT_SET, CT_MAP, CT_STRUCT = 8, 9, 10, 11, 12

# parquet enums
T_BOOLEAN, T_INT32, T_INT64, T_INT96, T_FLOAT, T_DOUBLE, T_BYTE_ARRAY = \
    0, 1, 2, 3, 4, 5, 6
REP_REQUIRED, REP_OPTIONAL, REP_REPEATED = 0, 1, 2
ENC_PLAIN, ENC_PLAIN_DICTIONARY, ENC_RLE, ENC_RLE_DICTIONARY = 0, 2, 3, 8
CODEC_UNCOMPRESSED, CODEC_SNAPPY, CODEC_GZIP = 0, 1, 2
CODEC_LZO, CODEC_BROTLI, CODEC_LZ4, CODEC_ZSTD, CODEC_LZ4_RAW = \
    3, 4, 5, 6, 7
PAGE_DATA, PAGE_INDEX, PAGE_DICTIONARY, PAGE_DATA_V2 = 0, 1, 2, 3


# --------------------------------------------------------------------------
# codecs
# --------------------------------------------------------------------------

try:
    import zstandard as _zstandard
except Exception:                    # pragma: no cover — optional codec
    _zstandard = None


def _zstd_decompress(data: bytes, max_out: int) -> bytes:
    """ZSTD via the optional zstandard package; loud, actionable error
    when it is absent (shared by the parquet and ORC readers)."""
    if _zstandard is None:
        raise ValueError(
            "ZSTD-compressed file but the zstandard package is not "
            "installed")
    return _zstandard.ZstdDecompressor().decompress(
        data, max_output_size=max_out)


def snappy_decompress(data: bytes) -> bytes:
    """Snappy block format (format_description.txt): uvarint output
    length, then tagged elements — 2-bit tag selects literal or a copy
    with 1/2/4-byte offsets."""
    out_len, pos = _uvarint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:                       # literal
            ln = tag >> 2
            if ln >= 60:                    # 60..63: length in next bytes
                extra = ln - 59
                ln = int.from_bytes(data[pos:pos + extra], "little")
                pos += extra
            ln += 1
            out += data[pos:pos + ln]
            pos += ln
            continue
        if kind == 1:                       # copy, 1-byte offset
            ln = ((tag >> 2) & 0x7) + 4
            off = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:                     # copy, 2-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:                               # copy, 4-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if off == 0 or off > len(out):
            raise ValueError(f"snappy: copy offset {off} outside the "
                             f"{len(out)} bytes produced")
        _lz_copy(out, off, ln)
    if len(out) != out_len:
        raise ValueError(f"snappy: expected {out_len} bytes, "
                         f"got {len(out)}")
    return bytes(out)


def _lz_copy(out: bytearray, off: int, ln: int) -> None:
    """LZ77 back-reference copy. Disjoint copies are one slice; self-
    overlapping ones (RLE-style) extend in doubling chunks — both O(slices)
    instead of a Python loop per byte."""
    start = len(out) - off
    if off >= ln:
        out += out[start:start + ln]
        return
    remaining = ln
    while remaining > 0:
        chunk = out[start:start + min(remaining, len(out) - start)]
        out += chunk
        remaining -= len(chunk)


def lz4_raw_decompress(data: bytes, out_len: int) -> bytes:
    """LZ4 block format: token byte (literal len | match len nibbles),
    optional length continuations, 2-byte little-endian match offset."""
    out = bytearray()
    pos = 0
    n = len(data)
    while pos < n:
        token = data[pos]
        pos += 1
        lit = token >> 4
        if lit == 15:
            while True:
                x = data[pos]
                pos += 1
                lit += x
                if x != 255:
                    break
        out += data[pos:pos + lit]
        pos += lit
        if pos >= n:                        # last block ends with literals
            break
        off = int.from_bytes(data[pos:pos + 2], "little")
        pos += 2
        if off == 0 or off > len(out):
            raise ValueError(f"lz4: match offset {off} outside the "
                             f"{len(out)} bytes produced")
        mlen = token & 0xF
        if mlen == 15:
            while True:
                x = data[pos]
                pos += 1
                mlen += x
                if x != 255:
                    break
        mlen += 4
        _lz_copy(out, off, mlen)
    if out_len >= 0 and len(out) != out_len:
        raise ValueError(f"lz4: expected {out_len} bytes, got {len(out)}")
    return bytes(out)


def decompress(codec: int, data: bytes, out_len: int) -> bytes:
    if codec == CODEC_UNCOMPRESSED:
        return data
    if codec == CODEC_SNAPPY:
        return snappy_decompress(data)
    if codec == CODEC_GZIP:
        return zlib.decompress(data, wbits=zlib.MAX_WBITS | 32)
    if codec == CODEC_ZSTD:
        return _zstd_decompress(data, max(out_len, 1 << 20))
    if codec == CODEC_LZ4_RAW:
        return lz4_raw_decompress(data, out_len)
    raise ValueError(
        f"unsupported parquet codec {codec} "
        "(UNCOMPRESSED/SNAPPY/GZIP/LZ4_RAW supported)")


# --------------------------------------------------------------------------
# thrift compact protocol
# --------------------------------------------------------------------------

def _uvarint(b: bytes, pos: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        x = b[pos]
        pos += 1
        out |= (x & 0x7F) << shift
        if not x & 0x80:
            return out, pos
        shift += 7


def _zigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _enc_uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        if n < 0x80:
            out.append(n)
            return bytes(out)
        out.append((n & 0x7F) | 0x80)
        n >>= 7


def _enc_zigzag(n: int) -> bytes:
    return _enc_uvarint((n << 1) ^ (n >> 63) if n < 0 else n << 1)


class ThriftReader:
    def __init__(self, data: bytes, pos: int = 0):
        self.b = data
        self.pos = pos

    def read_struct(self) -> Dict[int, object]:
        """Generic struct -> {field_id: value}; nested structs/lists
        recurse. Types are resolved by the caller from field ids."""
        fields: Dict[int, object] = {}
        last_id = 0
        while True:
            header = self.b[self.pos]
            self.pos += 1
            if header == 0:
                return fields
            delta = header >> 4
            ctype = header & 0x0F
            if delta == 0:
                fid, self.pos = _uvarint(self.b, self.pos)
                fid = _zigzag(fid)
            else:
                fid = last_id + delta
            last_id = fid
            fields[fid] = self._read_value(ctype)

    def _read_value(self, ctype: int):
        if ctype == CT_BOOL_TRUE:
            return True
        if ctype == CT_BOOL_FALSE:
            return False
        if ctype in (CT_BYTE,):
            v = self.b[self.pos]
            self.pos += 1
            return v
        if ctype in (CT_I16, CT_I32, CT_I64):
            v, self.pos = _uvarint(self.b, self.pos)
            return _zigzag(v)
        if ctype == CT_DOUBLE:
            v = struct.unpack("<d", self.b[self.pos:self.pos + 8])[0]
            self.pos += 8
            return v
        if ctype == CT_BINARY:
            n, self.pos = _uvarint(self.b, self.pos)
            v = self.b[self.pos:self.pos + n]
            self.pos += n
            return v
        if ctype in (CT_LIST, CT_SET):
            header = self.b[self.pos]
            self.pos += 1
            size = header >> 4
            etype = header & 0x0F
            if size == 15:
                size, self.pos = _uvarint(self.b, self.pos)
            return [self._read_value(etype) for _ in range(size)]
        if ctype == CT_STRUCT:
            return self.read_struct()
        raise ValueError(f"unsupported thrift compact type {ctype}")


class ThriftWriter:
    def __init__(self):
        self.parts: List[bytes] = []

    def struct(self, fields: List[Tuple[int, int, object]]) -> bytes:
        """fields: [(field_id, ctype, value)] in ascending id order."""
        out = bytearray()
        last_id = 0
        for fid, ctype, value in fields:
            delta = fid - last_id
            wire_type = ctype
            if ctype in (CT_BOOL_TRUE, CT_BOOL_FALSE):
                wire_type = CT_BOOL_TRUE if value else CT_BOOL_FALSE
            if 0 < delta <= 15:
                out.append((delta << 4) | wire_type)
            else:
                out.append(wire_type)
                out += _enc_zigzag(fid)
            last_id = fid
            out += self._enc_value(ctype, value)
        out.append(0)
        return bytes(out)

    def _enc_value(self, ctype: int, value) -> bytes:
        if ctype in (CT_BOOL_TRUE, CT_BOOL_FALSE):
            return b""
        if ctype == CT_BYTE:
            return bytes([value & 0xFF])
        if ctype in (CT_I16, CT_I32, CT_I64):
            return _enc_zigzag(int(value))
        if ctype == CT_DOUBLE:
            return struct.pack("<d", value)
        if ctype == CT_BINARY:
            v = value.encode() if isinstance(value, str) else value
            return _enc_uvarint(len(v)) + v
        if ctype in (CT_STRUCT, CT_LIST, CT_SET):
            return value                  # pre-encoded struct/list bytes
        raise ValueError(f"cannot encode thrift type {ctype}")

    def list_of(self, etype: int, items: List[bytes]) -> bytes:
        n = len(items)
        if n < 15:
            header = bytes([(n << 4) | etype])
        else:
            header = bytes([0xF0 | etype]) + _enc_uvarint(n)
        return header + b"".join(items)


# --------------------------------------------------------------------------
# RLE / bit-packed hybrid (definition levels, dictionary indices)
# --------------------------------------------------------------------------

def rle_decode(data: bytes, bit_width: int, count: int) -> np.ndarray:
    out = np.empty(count, dtype=np.int32)
    pos = 0
    filled = 0
    byte_width = (bit_width + 7) // 8
    while filled < count:
        header, pos = _uvarint(data, pos)
        if header & 1:                      # bit-packed run
            groups = header >> 1
            n = groups * 8
            raw = np.frombuffer(data, dtype=np.uint8, count=groups *
                                bit_width, offset=pos)
            pos += groups * bit_width
            bits = np.unpackbits(raw, bitorder="little")
            vals = bits.reshape(-1, bit_width)
            weights = (1 << np.arange(bit_width)).astype(np.int64)
            decoded = (vals * weights).sum(axis=1).astype(np.int32)
            take = min(n, count - filled)
            out[filled:filled + take] = decoded[:take]
            filled += take
        else:                               # RLE run
            n = header >> 1
            v = int.from_bytes(data[pos:pos + byte_width], "little")
            pos += byte_width
            take = min(n, count - filled)
            out[filled:filled + take] = v
            filled += take
    return out


def rle_encode_bitpacked(values: np.ndarray, bit_width: int) -> bytes:
    """Encode as one bit-packed run (padded to a multiple of 8)."""
    n = len(values)
    groups = (n + 7) // 8
    padded = np.zeros(groups * 8, dtype=np.int64)
    padded[:n] = values
    bits = ((padded[:, None] >> np.arange(bit_width)) & 1).astype(np.uint8)
    packed = np.packbits(bits.reshape(-1), bitorder="little")
    return _enc_uvarint((groups << 1) | 1) + packed.tobytes()


# --------------------------------------------------------------------------
# writer
# --------------------------------------------------------------------------

_PHYS_FOR_DTYPE = {
    np.dtype(np.int64): T_INT64,
    np.dtype(np.int32): T_INT32,
    np.dtype(np.float64): T_DOUBLE,
    np.dtype(np.bool_): T_BOOLEAN,
}


def _plain_encode(phys: int, arr: np.ndarray) -> bytes:
    if phys == T_INT64:
        return np.ascontiguousarray(arr, dtype="<i8").tobytes()
    if phys == T_INT32:
        return np.ascontiguousarray(arr, dtype="<i4").tobytes()
    if phys == T_DOUBLE:
        return np.ascontiguousarray(arr, dtype="<f8").tobytes()
    if phys == T_BOOLEAN:
        return np.packbits(arr.astype(np.uint8),
                           bitorder="little").tobytes()
    if phys == T_BYTE_ARRAY:
        parts = []
        for s in arr:
            b = s.encode() if isinstance(s, str) else bytes(s)
            parts.append(struct.pack("<I", len(b)) + b)
        return b"".join(parts)
    raise ValueError(f"unsupported physical type {phys}")


CONV_UTF8, CONV_DECIMAL, CONV_DATE = 0, 5, 6


def _stats_encode(phys: int, present: np.ndarray,
                  null_count: int = 0) -> Optional[bytes]:
    """Statistics struct (null_count field 3, min_value/max_value fields
    6/5) for row-group pruning; None when the column has no present
    values or no ordering worth recording."""
    if len(present) == 0:
        return None
    tw = ThriftWriter()
    if phys in (T_INT32, T_INT64):
        lo, hi = int(present.min()), int(present.max())
        fmt = "<i" if phys == T_INT32 else "<q"
        return tw.struct([(3, CT_I64, null_count),
                          (5, CT_BINARY, struct.pack(fmt, hi)),
                          (6, CT_BINARY, struct.pack(fmt, lo))])
    if phys == T_DOUBLE:
        lo, hi = float(present.min()), float(present.max())
        return tw.struct([(3, CT_I64, null_count),
                          (5, CT_BINARY, struct.pack("<d", hi)),
                          (6, CT_BINARY, struct.pack("<d", lo))])
    if phys == T_BYTE_ARRAY:
        ss = [s if isinstance(s, str) else str(s) for s in present]
        return tw.struct([(3, CT_I64, null_count),
                          (5, CT_BINARY, max(ss).encode()),
                          (6, CT_BINARY, min(ss).encode())])
    return None


def write_parquet(path: str, names: List[str], arrays: List[np.ndarray],
                  valids: Optional[List[Optional[np.ndarray]]] = None,
                  logicals: Optional[List[Optional[tuple]]] = None,
                  compression: str = "none",
                  row_group_rows: Optional[int] = None) -> None:
    """Write flat columns to a parquet file.

    Object/str arrays become BYTE_ARRAY (UTF8). A valids mask marks the
    column OPTIONAL with RLE/bit-packed definition levels. `logicals`
    annotates columns with converted types: ("decimal", precision, scale)
    on INT64, ("date",) on INT32. `compression` is "none" or "gzip"
    (the stdlib codec; reading additionally handles snappy/lz4_raw).
    `row_group_rows` splits the data into multiple row groups, each
    carrying min/max statistics for reader-side pruning.
    """
    n_rows = len(arrays[0]) if arrays else 0
    valids = valids if valids is not None else [None] * len(arrays)
    logicals = logicals if logicals is not None else [None] * len(arrays)
    codec = {"none": CODEC_UNCOMPRESSED, "gzip": CODEC_GZIP}[compression]
    tw = ThriftWriter()
    body = bytearray(MAGIC)

    group_rows = row_group_rows or max(1, n_rows)
    row_group_blobs: List[bytes] = []
    for g_start in range(0, max(1, n_rows), group_rows):
        g_end = min(n_rows, g_start + group_rows)
        g_n = g_end - g_start
        col_metas: List[bytes] = []
        for name, arr, valid in zip(names, arrays, valids):
            arr = np.asarray(arr)[g_start:g_end]
            if arr.dtype.kind in ("U", "O", "S"):
                phys = T_BYTE_ARRAY
            else:
                if arr.dtype not in _PHYS_FOR_DTYPE:
                    arr = arr.astype(np.int64)
                phys = _PHYS_FOR_DTYPE[arr.dtype]
            optional = valid is not None
            offset = len(body)

            if optional:
                gvalid = np.asarray(valid)[g_start:g_end]
                defs = rle_encode_bitpacked(gvalid.astype(np.int64), 1)
                def_block = struct.pack("<I", len(defs)) + defs
                present = arr[gvalid]
            else:
                def_block = b""
                present = arr
            payload = def_block + _plain_encode(phys, present)
            if codec == CODEC_UNCOMPRESSED:
                wire = payload
            else:                          # gzip container for
                import gzip as _gz         # cross-reader compatibility
                wire = _gz.compress(payload, 6)

            page_header = tw.struct([
                (1, CT_I32, PAGE_DATA),
                (2, CT_I32, len(payload)),
                (3, CT_I32, len(wire)),
                (5, CT_STRUCT, tw.struct([
                    (1, CT_I32, g_n),
                    (2, CT_I32, ENC_PLAIN),
                    (3, CT_I32, ENC_RLE),
                    (4, CT_I32, ENC_RLE),
                ])),
            ])
            body += page_header + wire

            meta_fields = [
                (1, CT_I32, phys),
                (2, CT_LIST, tw.list_of(CT_I32, [_enc_zigzag(ENC_PLAIN),
                                                 _enc_zigzag(ENC_RLE)])),
                (3, CT_LIST, tw.list_of(
                    CT_BINARY, [_enc_uvarint(len(name.encode())) +
                                name.encode()])),
                (4, CT_I32, codec),
                (5, CT_I64, g_n),
                (6, CT_I64, len(page_header) + len(payload)),
                (7, CT_I64, len(page_header) + len(wire)),
                (9, CT_I64, offset),
            ]
            stats = _stats_encode(phys, present, g_n - len(present))
            if stats is not None:
                meta_fields.append((12, CT_STRUCT, stats))
            col_meta = tw.struct(meta_fields)
            col_metas.append(tw.struct([
                (2, CT_I64, offset),
                (3, CT_STRUCT, col_meta),
            ]))
        row_group_blobs.append(tw.struct([
            (1, CT_LIST, tw.list_of(CT_STRUCT, col_metas)),
            (2, CT_I64, sum(len(c) for c in col_metas)),
            (3, CT_I64, g_n),
        ]))
        if n_rows == 0:
            break

    # schema: root group + one element per column
    schema_elems = [tw.struct([
        (4, CT_BINARY, "schema"),
        (5, CT_I32, len(names)),
    ])]
    for name, arr, valid, logical in zip(names, arrays, valids, logicals):
        arr = np.asarray(arr)
        if arr.dtype.kind in ("U", "O", "S"):
            phys = T_BYTE_ARRAY
        else:
            if arr.dtype not in _PHYS_FOR_DTYPE:
                arr = arr.astype(np.int64)
            phys = _PHYS_FOR_DTYPE[arr.dtype]
        fields = [(1, CT_I32, phys),
                  (3, CT_I32, REP_OPTIONAL if valid is not None
                   else REP_REQUIRED),
                  (4, CT_BINARY, name)]
        if phys == T_BYTE_ARRAY:
            fields.append((6, CT_I32, CONV_UTF8))
        elif logical is not None and logical[0] == "decimal":
            fields.append((6, CT_I32, CONV_DECIMAL))
            fields.append((7, CT_I32, logical[2]))     # scale
            fields.append((8, CT_I32, logical[1]))     # precision
        elif logical is not None and logical[0] == "date":
            fields.append((6, CT_I32, CONV_DATE))
        schema_elems.append(tw.struct(fields))

    footer = tw.struct([
        (1, CT_I32, 1),
        (2, CT_LIST, tw.list_of(CT_STRUCT, schema_elems)),
        (3, CT_I64, n_rows),
        (4, CT_LIST, tw.list_of(CT_STRUCT, row_group_blobs)),
    ])
    body += footer
    body += struct.pack("<I", len(footer))
    body += MAGIC
    from trino_tpu.utils.atomicio import atomic_write_bytes
    atomic_write_bytes(path, bytes(body))


# --------------------------------------------------------------------------
# reader
# --------------------------------------------------------------------------

def _plain_decode(phys: int, data: bytes, count: int):
    if phys == T_INT64:
        return np.frombuffer(data, dtype="<i8", count=count)
    if phys == T_INT32:
        return np.frombuffer(data, dtype="<i4", count=count)
    if phys == T_DOUBLE:
        return np.frombuffer(data, dtype="<f8", count=count)
    if phys == T_BOOLEAN:
        bits = np.unpackbits(np.frombuffer(data, dtype=np.uint8),
                             bitorder="little")
        return bits[:count].astype(np.bool_)
    if phys == T_BYTE_ARRAY:
        out = []
        pos = 0
        for _ in range(count):
            (ln,) = struct.unpack_from("<I", data, pos)
            pos += 4
            out.append(data[pos:pos + ln].decode("utf-8", "replace"))
            pos += ln
        return np.array(out, dtype=object)
    raise ValueError(f"unsupported physical type {phys}")


class _Leaf:
    """One physical column: its schema path, levels, and logical type."""

    def __init__(self, name, phys, max_def, max_rep, logical, def_list):
        self.name = name                 # outermost field name
        self.phys = phys
        self.max_def = max_def           # def level meaning present value
        self.max_rep = max_rep           # 0 = flat, 1 = LIST element
        self.logical = logical
        self.def_list = def_list         # def level meaning empty list


def _walk_schema(schema: list) -> List[_Leaf]:
    """Flatten the SchemaElement preorder list into leaves with their
    max definition/repetition levels (the standard parquet level
    computation; nested depth >1 is rejected loudly)."""
    leaves: List[_Leaf] = []
    idx = 0

    def walk(max_def, max_rep, top_name, list_def):
        nonlocal idx
        raw = schema[idx]
        idx += 1
        rep = raw.get(3, REP_REQUIRED)
        name = raw[4].decode()
        n_children = raw.get(5)
        if rep == REP_OPTIONAL:
            max_def += 1
        elif rep == REP_REPEATED:
            max_def += 1
            max_rep += 1
            list_def = max_def - 1       # def at this level-1 = empty
        if top_name is None:
            top_name = name
        if n_children:                   # group node
            for _ in range(n_children):
                walk(max_def, max_rep, top_name, list_def)
            return
        phys = raw.get(1)
        conv = raw.get(6)
        logical = None
        if conv == CONV_DECIMAL:
            logical = ("decimal", raw.get(8, 18), raw.get(7, 0))
        elif conv == CONV_DATE:
            logical = ("date",)
        if max_rep > 1:
            raise ValueError(
                f"column {top_name}: nesting depth {max_rep} > 1 "
                "unsupported")
        leaves.append(_Leaf(top_name, phys, max_def, max_rep, logical,
                            list_def))

    root = schema[idx]
    idx += 1
    for _ in range(root.get(5, 0)):
        walk(0, 0, None, None)
    return leaves


def _stats_value(phys: int, raw: bytes):
    if raw is None:
        return None
    if phys == T_INT32:
        return struct.unpack("<i", raw)[0]
    if phys == T_INT64:
        return struct.unpack("<q", raw)[0]
    if phys == T_DOUBLE:
        return struct.unpack("<d", raw)[0]
    if phys == T_BYTE_ARRAY:
        return raw.decode("utf-8", "replace")
    if phys == T_BOOLEAN:
        return bool(raw[0])
    return None


class ParquetFile:
    """Decoded file plus read-side bookkeeping (skipped row groups)."""

    def __init__(self, names, columns, valids, logicals,
                 skipped_row_groups, total_row_groups):
        self.names = names
        self.columns = columns
        self.valids = valids
        self.logicals = logicals
        self.skipped_row_groups = skipped_row_groups
        self.total_row_groups = total_row_groups


def read_parquet(path: str, predicates: Optional[dict] = None):
    """Read a parquet file -> (names, columns, valids, logicals).

    logicals[i] is None, ("decimal", precision, scale), ("date",), or
    ("list", element_logical). LIST columns decode to object arrays of
    per-row tuples (None = NULL list). `predicates` maps column name ->
    (lo, hi) inclusive bounds; row groups whose chunk statistics prove
    no row can match are skipped wholesale."""
    f = read_parquet_file(path, predicates)
    return f.names, f.columns, f.valids, f.logicals


def read_parquet_file(path: str, predicates: Optional[dict] = None) \
        -> ParquetFile:
    with open(path, "rb") as f:
        blob = f.read()
    if blob[:4] != MAGIC or blob[-4:] != MAGIC:
        raise ValueError("not a parquet file")
    (footer_len,) = struct.unpack("<I", blob[-8:-4])
    footer = ThriftReader(blob, len(blob) - 8 - footer_len).read_struct()

    leaves = _walk_schema(footer[2])
    row_groups = footer[4]

    per_group: List[Optional[list]] = []
    skipped = 0
    for rg in row_groups:
        chunks = rg[1]
        if predicates and _group_excluded(leaves, chunks, predicates):
            skipped += 1
            per_group.append(None)
            continue
        group_cols = []
        for leaf, chunk in zip(leaves, chunks):
            meta = chunk[3]
            codec = meta.get(4, CODEC_UNCOMPRESSED)
            n_values = meta[5]
            offset = meta.get(9)
            dict_offset = meta.get(11)
            start = dict_offset if dict_offset is not None else offset
            group_cols.append(_read_chunk(blob, start, leaf, codec,
                                          n_values))
        per_group.append(group_cols)

    names = [lf.name for lf in leaves]
    logicals = []
    for lf in leaves:
        logicals.append(("list", lf.logical) if lf.max_rep else
                        lf.logical)
    kept = [g for g in per_group if g is not None]
    columns: List[np.ndarray] = []
    valids: List[Optional[np.ndarray]] = []
    empty_dtype = {T_INT64: np.int64, T_INT32: np.int32,
                   T_DOUBLE: np.float64, T_BOOLEAN: np.bool_}
    for i, lf in enumerate(leaves):
        if not kept:
            # dtype must follow the PHYSICAL type even with every group
            # pruned, or the connector's schema inference flips with the
            # predicate
            dt = object if lf.max_rep or lf.phys == T_BYTE_ARRAY else \
                empty_dtype.get(lf.phys, np.int64)
            columns.append(np.zeros(0, dtype=dt))
            valids.append(np.zeros(0, dtype=np.bool_)
                          if lf.max_def > 0 else None)
            continue
        vals = [g[i][0] for g in kept]
        vds = [g[i][1] for g in kept]
        columns.append(np.concatenate(vals) if len(vals) > 1 else vals[0])
        if any(v is not None for v in vds):
            vds = [v if v is not None else
                   np.ones(len(d), dtype=np.bool_)
                   for v, d in zip(vds, vals)]
            valids.append(np.concatenate(vds) if len(vds) > 1 else vds[0])
        else:
            valids.append(None)
    return ParquetFile(names, columns, valids, logicals, skipped,
                       len(row_groups))


def _group_excluded(leaves, chunks, predicates) -> bool:
    """True when some predicate column's [min,max] statistics prove the
    row group empty under (lo, hi) inclusive bounds."""
    for leaf, chunk in zip(leaves, chunks):
        rng = predicates.get(leaf.name)
        if rng is None or leaf.max_rep:
            continue
        stats = chunk[3].get(12)
        if not isinstance(stats, dict):
            continue
        cmin = _stats_value(leaf.phys, stats.get(6, stats.get(2)))
        cmax = _stats_value(leaf.phys, stats.get(5, stats.get(1)))
        lo, hi = rng
        if cmin is not None and hi is not None and cmin > hi:
            return True
        if cmax is not None and lo is not None and cmax < lo:
            return True
    return False


def _read_chunk(blob: bytes, pos: int, leaf: _Leaf, codec: int,
                n_values: int):
    """Read pages at `pos` until n_values level entries are decoded.
    Handles a leading dictionary page and compressed pages. Returns
    (values, valid) at ROW granularity — repeated leaves assemble rows
    from definition+repetition levels."""
    phys = leaf.phys
    dictionary = None
    got = 0
    out_parts, def_parts, rep_parts = [], [], []
    max_def, max_rep = leaf.max_def, leaf.max_rep
    while got < n_values:
        tr = ThriftReader(blob, pos)
        header = tr.read_struct()
        page_type = header[1]
        uncomp_size = header[2]
        size = header[3]
        data = blob[tr.pos:tr.pos + size]
        pos = tr.pos + size
        if page_type == PAGE_DICTIONARY:
            dph = header[7]
            data = decompress(codec, data, uncomp_size)
            dictionary = _plain_decode(phys, data, dph[1])
            continue
        if page_type != PAGE_DATA:
            raise ValueError(f"unsupported page type {page_type} "
                             "(data page v2 not supported)")
        dph = header[5]
        count = dph[1]
        encoding = dph[2]
        body = decompress(codec, data, uncomp_size)
        reps = None
        if max_rep > 0:
            (rl_len,) = struct.unpack_from("<I", body, 0)
            bw = max(1, (max_rep).bit_length())
            reps = rle_decode(body[4:4 + rl_len], bw, count)
            body = body[4 + rl_len:]
        defs = None
        if max_def > 0:
            (dl_len,) = struct.unpack_from("<I", body, 0)
            bw = max(1, (max_def).bit_length())
            defs = rle_decode(body[4:4 + dl_len], bw, count)
            body = body[4 + dl_len:]
            n_present = int((defs == max_def).sum())
        else:
            n_present = count
        if encoding in (ENC_PLAIN_DICTIONARY, ENC_RLE_DICTIONARY):
            bit_width = body[0]
            idx = rle_decode(body[1:], bit_width, n_present)
            present = dictionary[idx]
        else:
            present = _plain_decode(phys, body, n_present)
        out_parts.append(present)
        if defs is not None:
            def_parts.append(defs)
        if reps is not None:
            rep_parts.append(reps)
        got += count

    present = np.concatenate(out_parts) if len(out_parts) > 1 else \
        out_parts[0]
    defs = (np.concatenate(def_parts) if len(def_parts) > 1 else
            def_parts[0]) if def_parts else None
    if max_rep == 0:
        if defs is None:
            return present, None
        valid = defs == max_def
        full = np.zeros(len(defs), dtype=present.dtype)
        if present.dtype == object:
            full = np.full(len(defs), "", dtype=object)
        full[valid] = present
        return full, valid
    # LIST assembly: rep==0 starts a row; def semantics per level
    reps = (np.concatenate(rep_parts) if len(rep_parts) > 1 else
            rep_parts[0])
    rows: List[Optional[tuple]] = []
    valid_rows: List[bool] = []
    cur: Optional[list] = None
    vi = 0
    for d, r in zip(defs.tolist(), reps.tolist()):
        if r == 0:
            if cur is not None:
                rows.append(tuple(cur))
            if d < leaf.def_list:
                # NULL list (def strictly below the list group's own
                # level; a REQUIRED list group has def_list == 0, where
                # d == 0 means EMPTY, never NULL)
                rows.append(None)
                valid_rows.append(False)
                cur = None
                if d == max_def:          # cannot happen, defensive
                    vi += 1
                continue
            valid_rows.append(True)
            cur = []
            if d == leaf.def_list:        # empty list
                continue
        if d == max_def:
            cur.append(present[vi])
            vi += 1
        elif d == max_def - 1 and max_def > leaf.def_list:
            cur.append(None)              # NULL element
    if cur is not None:
        rows.append(tuple(cur))
    vals = np.empty(len(rows), dtype=object)
    for i, rowv in enumerate(rows):
        vals[i] = rowv
    return vals, np.asarray(valid_rows, dtype=np.bool_)
