"""ORC reader + writer — from scratch (numpy; zstandard only for the
optional ZSTD codec).

Reference: lib/trino-orc (reader/OrcRecordReader.java:83 and
OrcWriter.java, the stripe / stream / RLE stack). Coverage, built from
the ORC v1 spec:

- protobuf wire decoding/encoding for PostScript / Footer /
  StripeFooter metadata (ORC metadata is plain proto2)
- compression kinds NONE / ZLIB (raw deflate) / SNAPPY / LZ4 / ZSTD,
  applied per ORC's 3-byte chunk framing (header = len << 1|isOriginal)
- column types BOOLEAN / BYTE / SHORT / INT / LONG / FLOAT / DOUBLE /
  STRING / VARCHAR / CHAR / DATE / DECIMAL (<=18 digits) / TIMESTAMP
  inside a top-level STRUCT; LIST/MAP/UNION are rejected loudly
- integer RLE v1 and v2 (SHORT_REPEAT / DIRECT / PATCHED_BASE / DELTA),
  boolean/byte RLE for presence bits, string DIRECT_V2 and
  DICTIONARY_V2 encodings
- multiple stripes; NULLs via PRESENT streams
- writer: RLE v1 / DIRECT encodings, NONE compression, multi-stripe —
  the simplest spec-legal choices, readable by any conforming reader
  (pyarrow-verified)
"""

from __future__ import annotations

import struct
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from .parquet import (_zstd_decompress, lz4_raw_decompress,
                      snappy_decompress)

# compression kinds (PostScript field 2)
C_NONE, C_ZLIB, C_SNAPPY, C_LZO, C_LZ4, C_ZSTD = 0, 1, 2, 3, 4, 5

# type kinds (Footer Type field 1)
(K_BOOLEAN, K_BYTE, K_SHORT, K_INT, K_LONG, K_FLOAT, K_DOUBLE, K_STRING,
 K_BINARY, K_TIMESTAMP, K_LIST, K_MAP, K_STRUCT, K_UNION, K_DECIMAL,
 K_DATE, K_VARCHAR, K_CHAR) = range(18)

# stream kinds (StripeFooter Stream field 2)
S_PRESENT, S_DATA, S_LENGTH, S_DICTIONARY_DATA, S_DICTIONARY_COUNT, \
    S_SECONDARY = 0, 1, 2, 3, 4, 5

# column encodings
E_DIRECT, E_DICTIONARY, E_DIRECT_V2, E_DICTIONARY_V2 = 0, 1, 2, 3


# --------------------------------------------------------------------------
# protobuf wire format
# --------------------------------------------------------------------------

def _pb_varint(b: bytes, pos: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        x = b[pos]
        pos += 1
        out |= (x & 0x7F) << shift
        if not x & 0x80:
            return out, pos
        shift += 7


def pb_decode(b: bytes) -> Dict[int, list]:
    """Generic proto2 message -> {field: [raw values]} (varints stay
    ints, length-delimited stay bytes; callers interpret)."""
    fields: Dict[int, list] = {}
    pos = 0
    n = len(b)
    while pos < n:
        key, pos = _pb_varint(b, pos)
        fid, wire = key >> 3, key & 7
        if wire == 0:
            v, pos = _pb_varint(b, pos)
        elif wire == 1:
            v = struct.unpack_from("<q", b, pos)[0]
            pos += 8
        elif wire == 2:
            ln, pos = _pb_varint(b, pos)
            v = b[pos:pos + ln]
            pos += ln
        elif wire == 5:
            v = struct.unpack_from("<i", b, pos)[0]
            pos += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wire}")
        fields.setdefault(fid, []).append(v)
    return fields


def _zz(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _pb_double(v: int) -> float:
    """Reinterpret a pb_decode wire-type-1 value (read as int64) as the
    IEEE double it actually is (DoubleStatistics min/max)."""
    return struct.unpack("<d", struct.pack("<q", v))[0]


def pb_ints(msg: Dict[int, list], fid: int) -> List[int]:
    """Repeated integer field, handling proto2 packed encoding (the
    values arrive as one length-delimited blob of varints)."""
    out: List[int] = []
    for v in msg.get(fid, []):
        if isinstance(v, bytes):
            pos = 0
            while pos < len(v):
                x, pos = _pb_varint(v, pos)
                out.append(x)
        else:
            out.append(v)
    return out


# --------------------------------------------------------------------------
# compression chunk framing
# --------------------------------------------------------------------------

def _decompress_stream(kind: int, data: bytes) -> bytes:
    if kind == C_NONE:
        return data
    out = bytearray()
    pos = 0
    n = len(data)
    while pos < n:
        header = int.from_bytes(data[pos:pos + 3], "little")
        pos += 3
        length = header >> 1
        chunk = data[pos:pos + length]
        pos += length
        if header & 1:                   # isOriginal
            out += chunk
        elif kind == C_ZLIB:
            out += zlib.decompress(chunk, wbits=-15)
        elif kind == C_SNAPPY:
            out += snappy_decompress(chunk)
        elif kind == C_LZ4:
            out += lz4_raw_decompress(chunk, -1)
        elif kind == C_ZSTD:
            out += _zstd_decompress(chunk, 1 << 26)
        else:
            raise ValueError(f"unsupported ORC compression kind {kind}")
    return bytes(out)


# --------------------------------------------------------------------------
# RLE decoders
# --------------------------------------------------------------------------

def _bool_rle(data: bytes, count: int) -> np.ndarray:
    """Byte-RLE then bit expansion, MSB first."""
    by = _byte_rle(data, (count + 7) // 8)
    bits = np.unpackbits(np.frombuffer(by, dtype=np.uint8),
                         bitorder="big")
    return bits[:count].astype(np.bool_)


def _byte_rle(data: bytes, count: int) -> bytes:
    out = bytearray()
    pos = 0
    while len(out) < count and pos < len(data):
        h = data[pos]
        pos += 1
        if h < 128:                      # run of h+3 repeats
            out += bytes([data[pos]]) * (h + 3)
            pos += 1
        else:                            # 256-h literals
            n = 256 - h
            out += data[pos:pos + n]
            pos += n
    return bytes(out[:count])


def _unpack_bits_be(data: bytes, width: int, count: int,
                    pos: int) -> Tuple[np.ndarray, int]:
    """Big-endian bit-packed integers (RLEv2 DIRECT/PATCHED payloads)."""
    nbits = width * count
    nbytes = (nbits + 7) // 8
    raw = np.frombuffer(data, dtype=np.uint8, count=nbytes, offset=pos)
    bits = np.unpackbits(raw, bitorder="big")[:nbits]
    vals = np.zeros(count, dtype=np.int64)
    bm = bits.reshape(count, width).astype(np.int64)
    for i in range(width):
        vals = (vals << 1) | bm[:, i]
    return vals, pos + nbytes


# RLEv2 5-bit width encoding -> actual bit width
_W5 = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18,
       19, 20, 21, 22, 23, 24, 26, 28, 30, 32, 40, 48, 56, 64]


def _varint(data: bytes, pos: int) -> Tuple[int, int]:
    return _pb_varint(data, pos)


def int_rle_v2(data: bytes, count: int, signed: bool) -> np.ndarray:
    out = np.empty(count, dtype=np.int64)
    filled = 0
    pos = 0
    while filled < count:
        h = data[pos]
        enc = h >> 6
        if enc == 0:                     # SHORT_REPEAT
            width = ((h >> 3) & 0x7) + 1
            run = (h & 0x7) + 3
            v = int.from_bytes(data[pos + 1:pos + 1 + width], "big")
            pos += 1 + width
            if signed:
                v = _zz(v)
            out[filled:filled + run] = v
            filled += run
        elif enc == 1:                   # DIRECT
            width = _W5[(h >> 1) & 0x1F]
            run = (((h & 1) << 8) | data[pos + 1]) + 1
            pos += 2
            vals, pos = _unpack_bits_be(data, width, run, pos)
            if signed:
                vals = (vals >> 1) ^ -(vals & 1)
            out[filled:filled + run] = vals
            filled += run
        elif enc == 2:                   # PATCHED_BASE
            width = _W5[(h >> 1) & 0x1F]
            run = (((h & 1) << 8) | data[pos + 1]) + 1
            b3, b4 = data[pos + 2], data[pos + 3]
            bw = (b3 >> 5) + 1           # base value width, bytes
            pw = _W5[b3 & 0x1F]          # patch value width, bits
            pgw = (b4 >> 5) + 1          # patch gap width, bits
            pll = b4 & 0x1F              # patch list length
            pos += 4
            base = int.from_bytes(data[pos:pos + bw], "big")
            sign = base >> (bw * 8 - 1)
            if sign:
                base = -(base & ((1 << (bw * 8 - 1)) - 1))
            pos += bw
            vals, pos = _unpack_bits_be(data, width, run, pos)
            patch_width = pgw + pw
            patches, pos = _unpack_bits_be(
                data, ((patch_width + 7) // 8) * 8, pll, pos)
            gap_acc = 0
            for p in patches.tolist():
                gap = p >> pw
                patch = p & ((1 << pw) - 1)
                gap_acc += gap
                vals[gap_acc] |= patch << width
            out[filled:filled + run] = base + vals
            filled += run
        else:                            # DELTA
            width_code = (h >> 1) & 0x1F
            width = _W5[width_code] if width_code else 0
            run = (((h & 1) << 8) | data[pos + 1]) + 1
            pos += 2
            v0, pos = _varint(data, pos)
            base = _zz(v0) if signed else v0
            delta0, pos = _varint(data, pos)
            delta0 = _zz(delta0)
            seq = [base]
            if run > 1:
                seq.append(base + delta0)
            if run > 2:
                if width:
                    deltas, pos = _unpack_bits_be(data, width, run - 2,
                                                  pos)
                    sgn = 1 if delta0 >= 0 else -1
                    for d in deltas.tolist():
                        seq.append(seq[-1] + sgn * d)
                else:
                    for _ in range(run - 2):
                        seq.append(seq[-1] + delta0)
            out[filled:filled + run] = seq
            filled += run
    return out


def int_rle_v1(data: bytes, count: int, signed: bool) -> np.ndarray:
    out = np.empty(count, dtype=np.int64)
    filled = 0
    pos = 0
    while filled < count:
        h = data[pos]
        pos += 1
        if h < 128:                      # run
            run = h + 3
            delta = struct.unpack_from("<b", data, pos)[0]
            pos += 1
            v, pos = _varint(data, pos)
            if signed:
                v = _zz(v)
            out[filled:filled + run] = v + delta * np.arange(run)
            filled += run
        else:                            # literals
            n = 256 - h
            for i in range(n):
                v, pos = _varint(data, pos)
                out[filled + i] = _zz(v) if signed else v
            filled += n
    return out


# --------------------------------------------------------------------------
# reader
# --------------------------------------------------------------------------

class OrcFile:
    def __init__(self, names, columns, valids, logicals,
                 skipped_stripes: int = 0, total_stripes: int = 0):
        self.names = names
        self.columns = columns
        self.valids = valids
        self.logicals = logicals
        self.skipped_stripes = skipped_stripes
        self.total_stripes = total_stripes


def read_orc(path: str, predicates: Optional[dict] = None):
    """Read an ORC file -> (names, columns, valids, logicals).

    `predicates` maps column name -> (lo, hi) inclusive bounds in the
    engine's physical representation (dates as epoch days, decimals as
    scaled integers); stripes whose StripeStatistics prove no row can
    match are skipped without decoding (the caller's residual filter
    keeps results exact)."""
    f = read_orc_file(path, predicates)
    return f.names, f.columns, f.valids, f.logicals


def _stats_range(cs: Dict[int, list], kind: int, tmeta) \
        -> Tuple[Optional[object], Optional[object]]:
    """(min, max) of one ColumnStatistics message in engine physical
    values, or (None, None) when absent/unusable."""
    if kind in (K_BYTE, K_SHORT, K_INT, K_LONG):
        m = cs.get(2)
        if m:
            st = pb_decode(m[0])
            lo, hi = st.get(1, [None])[0], st.get(2, [None])[0]
            return (None if lo is None else _zz(lo),
                    None if hi is None else _zz(hi))
    elif kind == K_DATE:
        m = cs.get(7)
        if m:
            st = pb_decode(m[0])
            lo, hi = st.get(1, [None])[0], st.get(2, [None])[0]
            return (None if lo is None else _zz(lo),
                    None if hi is None else _zz(hi))
    elif kind in (K_FLOAT, K_DOUBLE):
        m = cs.get(3)
        if m:
            st = pb_decode(m[0])
            lo, hi = st.get(1, [None])[0], st.get(2, [None])[0]
            return (None if lo is None else _pb_double(lo),
                    None if hi is None else _pb_double(hi))
    elif kind == K_DECIMAL:
        m = cs.get(6)
        if m:
            from decimal import Decimal
            st = pb_decode(m[0])
            scale = tmeta.get(6, [0])[0]
            lo, hi = st.get(1, [None])[0], st.get(2, [None])[0]
            try:
                return (None if lo is None else
                        int(Decimal(lo.decode()).scaleb(scale)),
                        None if hi is None else
                        int(Decimal(hi.decode()).scaleb(scale)))
            except Exception:   # noqa: BLE001 — malformed decimal stat
                return None, None
    elif kind in (K_STRING, K_VARCHAR, K_CHAR):
        m = cs.get(4)
        if m:
            st = pb_decode(m[0])
            lo, hi = st.get(1, [None])[0], st.get(2, [None])[0]
            return (None if lo is None else lo.decode("utf-8", "replace"),
                    None if hi is None else hi.decode("utf-8", "replace"))
    return None, None


def _stripe_excluded(col_stats, child_ids, names, types,
                     predicates: dict) -> bool:
    """True when some predicate column's stripe statistics prove the
    stripe empty under (lo, hi) inclusive bounds (parquet's
    _group_excluded, for ORC StripeStatistics)."""
    for j, cid in enumerate(child_ids):
        if j >= len(names) or cid >= len(col_stats):
            continue
        rng = predicates.get(names[j])
        if rng is None:
            continue
        kind = types[cid].get(1, [None])[0]
        cmin, cmax = _stats_range(col_stats[cid], kind, types[cid])
        lo, hi = rng
        try:
            if cmin is not None and hi is not None and cmin > hi:
                return True
            if cmax is not None and lo is not None and cmax < lo:
                return True
        except TypeError:       # incomparable stat/bound types: keep
            continue
    return False


def read_orc_file(path: str,
                  predicates: Optional[dict] = None) -> OrcFile:
    with open(path, "rb") as f:
        blob = f.read()
    ps_len = blob[-1]
    ps = pb_decode(blob[-1 - ps_len:-1])
    footer_len = ps[1][0]
    comp = ps.get(2, [C_NONE])[0]
    magic = ps.get(8000, [b""])[0]
    if magic != b"ORC":
        raise ValueError("not an ORC file")
    footer_raw = blob[-1 - ps_len - footer_len:-1 - ps_len]
    footer = pb_decode(_decompress_stream(comp, footer_raw))
    # Metadata section (StripeStatistics) sits just before the footer;
    # PostScript field 5 carries its length
    metadata_len = ps.get(5, [0])[0]
    stripe_stats: List[list] = []
    if metadata_len:
        meta_raw = blob[-1 - ps_len - footer_len - metadata_len:
                        -1 - ps_len - footer_len]
        meta = pb_decode(_decompress_stream(comp, meta_raw))
        for ss in meta.get(1, []):
            stripe_stats.append(
                [pb_decode(cs) for cs in pb_decode(ss).get(1, [])])

    types = [pb_decode(t) for t in footer.get(4, [])]
    root = types[0]
    if root.get(1, [K_STRUCT])[0] != K_STRUCT:
        raise ValueError("ORC root type must be STRUCT")
    child_ids = pb_ints(root, 2)
    names = [n.decode() for n in root.get(3, [])]
    for cid in child_ids:
        k = types[cid].get(1, [None])[0]
        if k in (K_LIST, K_MAP, K_UNION, K_BINARY):
            raise ValueError(f"unsupported ORC column kind {k}")

    stripes = [pb_decode(s) for s in footer.get(3, [])]
    col_parts: Dict[int, list] = {cid: [] for cid in child_ids}
    val_parts: Dict[int, list] = {cid: [] for cid in child_ids}
    skipped = 0
    for si, st in enumerate(stripes):
        if predicates and si < len(stripe_stats) and _stripe_excluded(
                stripe_stats[si], child_ids, names, types, predicates):
            skipped += 1
            continue
        offset = st.get(1, [0])[0]
        index_len = st.get(2, [0])[0]
        data_len = st.get(3, [0])[0]
        sfooter_len = st.get(4, [0])[0]
        n_rows = st.get(5, [0])[0]
        sf_raw = blob[offset + index_len + data_len:
                      offset + index_len + data_len + sfooter_len]
        sfooter = pb_decode(_decompress_stream(comp, sf_raw))
        streams = [pb_decode(s) for s in sfooter.get(1, [])]
        encodings = [pb_decode(e) for e in sfooter.get(2, [])]
        # stream placement: sequential after the index region
        spos = offset
        placed = []
        for s in streams:
            kind = s.get(1, [S_DATA])[0]
            col = s.get(2, [0])[0]
            ln = s.get(3, [0])[0]
            placed.append((kind, col, spos, ln))
            spos += ln
        for cid in child_ids:
            kind = types[cid].get(1, [None])[0]
            enc = encodings[cid].get(1, [E_DIRECT])[0] \
                if cid < len(encodings) else E_DIRECT
            dict_size = encodings[cid].get(2, [0])[0] \
                if cid < len(encodings) else 0
            mine = {k: blob[p:p + ln]
                    for (k, c, p, ln) in placed if c == cid}
            vals, valid = _read_column(kind, enc, dict_size, mine, comp,
                                       n_rows, types[cid])
            col_parts[cid].append(vals)
            val_parts[cid].append(valid)

    # dtype of an all-stripes-pruned column must still follow its ORC
    # kind, or the connector's schema inference flips with the predicate
    _empty_dtype = {K_BOOLEAN: np.bool_, K_FLOAT: np.float64,
                    K_DOUBLE: np.float64, K_STRING: object,
                    K_VARCHAR: object, K_CHAR: object}
    columns, valids, logicals = [], [], []
    for cid in child_ids:
        parts = col_parts[cid]
        vparts = val_parts[cid]
        kind0 = types[cid].get(1, [None])[0]
        columns.append(np.concatenate(parts) if len(parts) > 1 else
                       (parts[0] if parts else
                        np.zeros(0, _empty_dtype.get(kind0, np.int64))))
        if any(v is not None for v in vparts):
            vs = [v if v is not None else np.ones(len(p), np.bool_)
                  for v, p in zip(vparts, parts)]
            valids.append(np.concatenate(vs) if len(vs) > 1 else vs[0])
        else:
            valids.append(None)
        kind = types[cid].get(1, [None])[0]
        if kind == K_DECIMAL:
            logicals.append(("decimal",
                             types[cid].get(5, [18])[0],
                             types[cid].get(6, [0])[0]))
        elif kind == K_DATE:
            logicals.append(("date",))
        elif kind == K_TIMESTAMP:
            logicals.append(("timestamp",))
        else:
            logicals.append(None)
    return OrcFile(names, columns, valids, logicals,
                   skipped_stripes=skipped, total_stripes=len(stripes))


def timestamp_micros(secs: np.ndarray, nraw: np.ndarray) -> np.ndarray:
    """Compose TIMESTAMP microseconds from the raw (seconds-from-2015,
    encoded-nanos) stream pair.

    Nanos: low 3 bits k != 0 => (k+1) trailing zeros were stripped
    (verified against pyarrow: 1000ns -> (1<<3)|2, 2.5e8 -> 25|6).

    Negative-time adjustment: Java ORC writers store trunc-toward-zero
    seconds with a POSITIVE sub-second part, so a pre-1970 timestamp
    with fractional seconds carries seconds one above the floor — a
    conforming reader subtracts one second when the 1970-relative
    seconds are negative and nanos are non-zero (TreeReaderFactory's
    TimestampTreeReader). The C++ writer (pyarrow) instead truncates
    toward zero WITH sign-carrying nanos; those rows arrive here with
    nanos < 0 and must NOT be adjusted — hence the nanos > 0 condition,
    which distinguishes the two encodings exactly."""
    zeros = nraw & 7
    nanos = np.where(zeros == 0, nraw >> 3,
                     (nraw >> 3) * np.power(10, zeros + 1))
    base = 1420070400      # 2015-01-01T00:00:00Z
    abs_secs = secs + base
    abs_secs = np.where((abs_secs < 0) & (nanos > 0), abs_secs - 1,
                        abs_secs)
    return abs_secs * 1_000_000 + nanos // 1000


def _read_column(kind, enc, dict_size, streams, comp, n_rows, tmeta):
    present = streams.get(S_PRESENT)
    valid = None
    if present is not None:
        valid = _bool_rle(_decompress_stream(comp, present), n_rows)
    n_present = int(valid.sum()) if valid is not None else n_rows
    data = _decompress_stream(comp, streams.get(S_DATA, b""))

    def rle_ints(raw, cnt, signed=True):
        if enc in (E_DIRECT_V2, E_DICTIONARY_V2):
            return int_rle_v2(raw, cnt, signed)
        return int_rle_v1(raw, cnt, signed)

    if kind in (K_SHORT, K_INT, K_LONG, K_DATE):
        vals_p = rle_ints(data, n_present)
    elif kind == K_BYTE:
        vals_p = np.frombuffer(_byte_rle(data, n_present),
                               dtype=np.int8).astype(np.int64)
    elif kind == K_BOOLEAN:
        vals_p = _bool_rle(data, n_present)
    elif kind == K_FLOAT:
        vals_p = np.frombuffer(data, dtype="<f4",
                               count=n_present).astype(np.float64)
    elif kind == K_DOUBLE:
        vals_p = np.frombuffer(data, dtype="<f8", count=n_present)
    elif kind in (K_STRING, K_VARCHAR, K_CHAR):
        lens_raw = _decompress_stream(comp, streams.get(S_LENGTH, b""))
        if enc in (E_DICTIONARY, E_DICTIONARY_V2):
            dict_raw = _decompress_stream(
                comp, streams.get(S_DICTIONARY_DATA, b""))
            lens = rle_ints(lens_raw, dict_size, signed=False)
            pool, pos = [], 0
            for ln in lens.tolist():
                pool.append(dict_raw[pos:pos + ln].decode(
                    "utf-8", "replace"))
                pos += ln
            idx = rle_ints(data, n_present, signed=False)
            vals_p = np.array([pool[i] for i in idx.tolist()],
                              dtype=object)
        else:
            lens = rle_ints(lens_raw, n_present, signed=False)
            out, pos = [], 0
            for ln in lens.tolist():
                out.append(data[pos:pos + ln].decode("utf-8", "replace"))
                pos += ln
            vals_p = np.array(out, dtype=object)
    elif kind == K_DECIMAL:
        # unbounded base-128 varints (sign in zigzag), scale SECONDARY
        sec = _decompress_stream(comp, streams.get(S_SECONDARY, b""))
        scales = rle_ints(sec, n_present)
        scale = tmeta.get(6, [0])[0]
        vals = []
        pos = 0
        for i in range(n_present):
            v, pos = _varint(data, pos)
            v = _zz(v)
            s = int(scales[i])
            vals.append(v * (10 ** (scale - s)) if s != scale else v)
        vals_p = np.asarray(vals, dtype=np.int64)
    elif kind == K_TIMESTAMP:
        # DATA = seconds from 2015-01-01 UTC (signed RLE); SECONDARY =
        # nanos with the trailing-zero trick (low 3 bits k != 0 =>
        # nanos = (v >> 3) * 10^(k+1)). Engine lanes are microseconds.
        secs = rle_ints(data, n_present).astype(np.int64)
        sec_raw = _decompress_stream(comp, streams.get(S_SECONDARY,
                                                       b""))
        nraw = rle_ints(sec_raw, n_present, signed=False).astype(
            np.int64)
        vals_p = timestamp_micros(secs, nraw)
    else:
        raise ValueError(f"unsupported ORC column kind {kind}")

    if valid is None:
        return vals_p, None
    if vals_p.dtype == object:
        full = np.full(n_rows, "", dtype=object)
    else:
        full = np.zeros(n_rows, dtype=vals_p.dtype)
    full[valid] = vals_p
    return full, valid


# --------------------------------------------------------------------------
# writer — minimal valid ORC (RLE v1 / DIRECT encodings, NONE compression)
# Reference role: lib/trino-orc OrcWriter.java. The simplest spec-legal
# encodings are chosen for writability; any conforming reader (including
# this module's own and pyarrow's) decodes them.
# --------------------------------------------------------------------------

def _pb_varint_enc(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def pb_encode(fields: Dict[int, list]) -> bytes:
    """Inverse of pb_decode: {field id: [int | bytes | float, ...]} ->
    proto2 wire bytes (varint for ints, length-delimited for bytes,
    fixed64 for Python floats — DoubleStatistics)."""
    out = bytearray()
    for fid in sorted(fields):
        for v in fields[fid]:
            if isinstance(v, (bytes, bytearray)):
                out += _pb_varint_enc((fid << 3) | 2)
                out += _pb_varint_enc(len(v))
                out += v
            elif isinstance(v, float):
                out += _pb_varint_enc((fid << 3) | 1)
                out += struct.pack("<d", v)
            else:
                out += _pb_varint_enc((fid << 3) | 0)
                out += _pb_varint_enc(int(v))
    return bytes(out)


def _compress_stream(kind: int, data: bytes, block: int = 262144) -> bytes:
    """Writer-side inverse of _decompress_stream: ORC 3-byte chunk
    framing (header = len << 1 | isOriginal). A chunk that deflate does
    not shrink is stored original, per spec."""
    if kind == C_NONE:
        return data
    if kind != C_ZLIB:
        raise ValueError(f"unsupported ORC write compression kind {kind}")
    out = bytearray()
    for i in range(0, len(data), block):
        chunk = data[i:i + block]
        co = zlib.compressobj(6, zlib.DEFLATED, -15)   # raw deflate
        comp = co.compress(chunk) + co.flush()
        if len(comp) < len(chunk):
            out += (len(comp) << 1).to_bytes(3, "little") + comp
        else:
            out += ((len(chunk) << 1) | 1).to_bytes(3, "little") + chunk
    return bytes(out)


def _dec_str(v: int, scale: int) -> str:
    """Scaled-int64 decimal -> ORC DecimalStatistics string ("‑1.23")."""
    if scale <= 0:
        return str(v)
    sign = "-" if v < 0 else ""
    v = abs(v)
    return f"{sign}{v // 10 ** scale}.{v % 10 ** scale:0{scale}d}"


def _col_stats(kind: int, present: np.ndarray, has_null: bool,
               logical) -> bytes:
    """ColumnStatistics proto for one column's stripe slice: value
    count, hasNull, and a kind-appropriate min/max message (sint64
    zigzag for integers/dates, IEEE doubles, decimal strings, UTF-8
    strings) — what _stats_range/_stripe_excluded prune against."""
    msg: Dict[int, list] = {1: [len(present)]}
    if has_null:
        msg[10] = [1]
    if len(present):
        if kind in (K_BYTE, K_SHORT, K_INT, K_LONG, K_DATE):
            lo, hi = int(np.min(present)), int(np.max(present))
            fid = 7 if kind == K_DATE else 2
            msg[fid] = [pb_encode({1: [_zz_enc(lo)], 2: [_zz_enc(hi)]})]
        elif kind == K_DOUBLE:
            a = np.asarray(present, dtype=np.float64)
            if not np.isnan(a).any():
                msg[3] = [pb_encode({1: [float(a.min())],
                                     2: [float(a.max())]})]
        elif kind == K_DECIMAL:
            scale = logical[2] if logical else 0
            lo, hi = int(np.min(present)), int(np.max(present))
            msg[6] = [pb_encode({1: [_dec_str(lo, scale).encode()],
                                 2: [_dec_str(hi, scale).encode()]})]
        elif kind == K_STRING:
            ss = [("" if s is None else str(s)) for s in present]
            msg[4] = [pb_encode({1: [min(ss).encode()],
                                 2: [max(ss).encode()]})]
        # K_BOOLEAN: counts only (BucketStatistics adds nothing here)
    return pb_encode(msg)


def _zz_enc(v: int) -> int:
    return (v << 1) ^ (v >> 63) if v < 0 else v << 1


def _rle_v1_ints(vals, signed=True) -> bytes:
    """Integer RLE v1, all-literal runs (header = -(n) as signed byte,
    then n base-128 varints, zigzag when signed)."""
    out = bytearray()
    vals = [int(v) for v in vals]
    for i in range(0, len(vals), 128):
        group = vals[i:i + 128]
        out.append(256 - len(group))          # -n two's complement
        for v in group:
            out += _pb_varint_enc(_zz_enc(v) if signed else v)
    return bytes(out)


def _byte_rle_enc(data: bytes) -> bytes:
    """Byte RLE, all-literal runs."""
    out = bytearray()
    for i in range(0, len(data), 128):
        group = data[i:i + 128]
        out.append(256 - len(group))
        out += group
    return bytes(out)


def _bool_rle_enc(bits: np.ndarray) -> bytes:
    packed = np.packbits(bits.astype(np.uint8))
    return _byte_rle_enc(packed.tobytes())


def write_orc(path: str, names, columns, valids=None, logicals=None,
              stripe_rows: int = 1 << 20,
              compression: str = "none") -> None:
    """Write columns to an ORC file. Types map from numpy dtypes unless
    `logicals[i]` overrides: ("decimal", p, s) or ("date",). Strings
    pass as object/str arrays. NULLs via `valids` boolean masks.
    `compression` is "none" or "zlib" (raw deflate inside ORC's 3-byte
    chunk framing, applied to streams and metadata sections alike).
    Every stripe's min/max/null statistics are recorded in the file's
    Metadata section for reader-side stripe pruning."""
    comp = {"none": C_NONE, "zlib": C_ZLIB}.get(compression.lower())
    if comp is None:
        raise ValueError(f"unsupported ORC compression: {compression!r}")
    n = len(columns[0]) if columns else 0
    valids = valids or [None] * len(columns)
    logicals = logicals or [None] * len(columns)

    def orc_kind(i):
        lg = logicals[i]
        if lg is not None:
            if lg[0] == "decimal":
                return K_DECIMAL
            if lg[0] == "date":
                return K_DATE
        a = columns[i]
        if a.dtype == np.bool_:
            return K_BOOLEAN
        if np.issubdtype(a.dtype, np.integer):
            return K_INT if a.dtype.itemsize <= 4 else K_LONG
        if np.issubdtype(a.dtype, np.floating):
            return K_DOUBLE
        return K_STRING

    kinds = [orc_kind(i) for i in range(len(columns))]

    body = bytearray(b"ORC")
    stripe_infos = []
    stripe_stat_msgs = []       # one StripeStatistics message per stripe
    for start in range(0, max(n, 1), stripe_rows):
        count = min(stripe_rows, n - start)
        if count <= 0 and n > 0:
            break
        streams = []        # (kind, col_id, bytes)
        encodings = [{1: [E_DIRECT]}]          # root struct
        col_stat_blobs = [pb_encode({1: [count]})]     # root struct stats
        for ci, arr in enumerate(columns):
            cid = ci + 1
            a = arr[start:start + count]
            v = None if valids[ci] is None else \
                np.asarray(valids[ci][start:start + count], dtype=bool)
            if v is not None and not v.all():
                streams.append((S_PRESENT, cid, _bool_rle_enc(v)))
                sel = v
            else:
                sel = np.ones(count, dtype=bool)
                v = None
            present_vals = a[sel] if v is not None else a
            col_stat_blobs.append(_col_stats(
                kinds[ci], present_vals, v is not None, logicals[ci]))
            k = kinds[ci]
            enc = {1: [E_DIRECT]}
            if k == K_BOOLEAN:
                streams.append((S_DATA, cid, _bool_rle_enc(
                    np.asarray(present_vals, dtype=bool))))
            elif k in (K_INT, K_LONG, K_DATE):
                streams.append((S_DATA, cid,
                                _rle_v1_ints(present_vals)))
            elif k == K_DOUBLE:
                streams.append((S_DATA, cid, np.asarray(
                    present_vals, dtype="<f8").tobytes()))
            elif k == K_DECIMAL:
                out = bytearray()
                for x in present_vals:
                    out += _pb_varint_enc(_zz_enc(int(x)))
                streams.append((S_DATA, cid, bytes(out)))
                scale = logicals[ci][2]
                streams.append((S_SECONDARY, cid, _rle_v1_ints(
                    [scale] * len(present_vals))))
            else:                               # strings
                strs = [("" if s is None else str(s)).encode()
                        for s in present_vals]
                streams.append((S_DATA, cid, b"".join(strs)))
                streams.append((S_LENGTH, cid, _rle_v1_ints(
                    [len(s) for s in strs], signed=False)))
            encodings.append(enc)

        offset = len(body)
        data_len = 0
        stream_msgs = []
        for skind, cid, blob in streams:
            framed = _compress_stream(comp, blob)
            body += framed
            data_len += len(framed)
            stream_msgs.append(pb_encode(
                {1: [skind], 2: [cid], 3: [len(framed)]}))
        sfooter = _compress_stream(comp, pb_encode({
            1: [bytes(m) for m in stream_msgs],
            2: [pb_encode(e) for e in encodings],
        }))
        body += sfooter
        stripe_infos.append(pb_encode({
            1: [offset], 2: [0], 3: [data_len], 4: [len(sfooter)],
            5: [count]}))
        stripe_stat_msgs.append(pb_encode({1: col_stat_blobs}))
        if n == 0:
            break

    # footer: type tree (root STRUCT + one child per column)
    types = [pb_encode({1: [K_STRUCT],
                        2: list(range(1, len(columns) + 1)),
                        3: [nm.encode() for nm in names]})]
    for ci in range(len(columns)):
        t = {1: [kinds[ci]]}
        if kinds[ci] == K_DECIMAL:
            t[5] = [logicals[ci][1]]
            t[6] = [logicals[ci][2]]
        types.append(pb_encode(t))
    content_len = len(body)
    # Metadata section (StripeStatistics): between the stripes and the
    # footer; readers prune stripes against it without touching data
    metadata = _compress_stream(comp, pb_encode({1: stripe_stat_msgs}))
    body += metadata
    footer = _compress_stream(comp, pb_encode({
        1: [3],                                # headerLength: "ORC" magic
        2: [content_len],                      # contentLength
        3: stripe_infos,
        4: types,
        6: [n],                                # numberOfRows
        8: [10000],                            # rowIndexStride
    }))
    body += footer
    ps = pb_encode({
        1: [len(footer)],
        2: [comp],
        3: [262144],
        4: [0, 12],                            # version 0.12
        5: [len(metadata)],                    # metadataLength
        6: [6],                                # writerVersion
        8000: [b"ORC"],
    })
    body += ps
    body.append(len(ps))
    from trino_tpu.utils.atomicio import atomic_write_bytes
    atomic_write_bytes(path, bytes(body))
