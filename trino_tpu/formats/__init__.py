"""File format libraries (the lib/trino-parquet / trino-orc tier)."""
