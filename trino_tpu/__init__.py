"""trino_tpu — a TPU-native distributed SQL query engine.

A brand-new framework with the capabilities of Trino (reference:
/root/reference, see SURVEY.md): SQL text in, cost-based planning into
fragmented distributed plans, and a columnar operator pipeline executed as
JAX/XLA programs sharded over a TPU mesh.

Where Trino generates JVM bytecode per query (sql/gen/ExpressionCompiler.java:38),
we trace per-stage array programs and let XLA fuse them; where Trino shuffles
serialized pages over HTTP (operator/HttpPageBufferClient.java:355), we use
lax.all_to_all / psum collectives over ICI inside jitted stage programs.
"""

import jax

# SQL semantics need 64-bit integers (BIGINT, scaled DECIMAL arithmetic).
# This must run before any array is created anywhere in the package.
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
