"""TPC-H connector: schema catalog over the deterministic generator.

Reference: plugin/trino-tpch (TpchMetadata.java:100 exposes schemas
tiny/sf1/sf100/..., TpchRecordSet.java:44 generates rows on demand).
Generated tables are cached per scale factor for the process lifetime.
"""

from __future__ import annotations

import re
from typing import Dict, Optional

from .datagen import TableData, generate

_SCHEMAS = {"tiny": 0.01, "sf1": 1.0, "sf10": 10.0, "sf100": 100.0,
            "sf1000": 1000.0}

TABLE_NAMES = ["region", "nation", "supplier", "customer", "part",
               "partsupp", "orders", "lineitem"]


class TpchConnector:
    name = "tpch"

    def __init__(self):
        self._cache: Dict[float, Dict[str, TableData]] = {}

    @staticmethod
    def scale_for_schema(schema: str) -> Optional[float]:
        if schema in _SCHEMAS:
            return _SCHEMAS[schema]
        m = re.fullmatch(r"sf([0-9.]+)", schema)
        if m:
            return float(m.group(1))
        return None

    def schema_names(self):
        return list(_SCHEMAS)

    def table_names(self, schema: str):
        return list(TABLE_NAMES)

    # scales at/above this persist to the on-disk cache: generation there
    # costs minutes (SF10 ~ the round-2 bench timeout) while tiny/0.01
    # regenerates in milliseconds
    DISK_CACHE_MIN_SCALE = 1.0

    def get_table(self, schema: str, table: str) -> TableData:
        scale = self.scale_for_schema(schema)
        if scale is None:
            raise KeyError(f"tpch schema {schema!r} not found")
        if table not in TABLE_NAMES:
            raise KeyError(f"tpch table {table!r} not found")
        from ..diskcache import get_or_generate
        return get_or_generate(
            f"tpch_sf{scale:g}", table, self._cache.setdefault(scale, {}),
            lambda: generate(scale), TableData,
            use_disk=scale >= self.DISK_CACHE_MIN_SCALE)

    def get_table_schema(self, schema: str, table: str):
        """Schema without materializing data (information_schema must not
        trigger SF1000 generation); scale-independent, so read from the
        smallest scale."""
        return self.get_table("tiny", table).schema
