"""Deterministic in-memory TPC-H data generator.

Role of the reference's ``plugin/trino-tpch`` connector (TpchRecordSet.java:44):
a deterministic benchmark data source that needs no files. We generate with
vectorized numpy from a fixed seed, following dbgen's schema, referential
structure, and key distributions:

- sparse orderkeys (8 per 32-block, like dbgen)
- only 2/3 of customers place orders (custkey % 3 != 0)
- retail price formula p_retailprice(partkey) per dbgen
- l_extendedprice = quantity * retailprice(partkey)
- returnflag/linestatus driven by ship/receipt dates vs 1995-06-17
- o_totalprice aggregated from line items

Value *distributions* match dbgen; exact dbgen text streams are not
reproduced (comments come from a seeded lexicon). Correctness testing always
runs the oracle on *this* data (SURVEY.md §4.4's H2QueryRunner pattern), so
engine results are checked end-to-end regardless.

All decimals are scaled int64 (cents, or 1e-2 units).
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ...batch import Field, Schema
from ...types import BIGINT, DATE, DOUBLE, INTEGER, VARCHAR, decimal

EPOCH = datetime.date(1970, 1, 1)


def days(s: str) -> int:
    return (datetime.date.fromisoformat(s) - EPOCH).days


STARTDATE = days("1992-01-01")
CURRENTDATE = days("1995-06-17")
ENDDATE = days("1998-12-31")

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = [  # (name, regionkey) — dbgen's nation table
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
INSTRUCTIONS = ["COLLECT COD", "DELIVER IN PERSON", "NONE",
                "TAKE BACK RETURN"]
TYPE_SYL1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_SYL2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_SYL3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINER_SYL1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_SYL2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
LEXICON = (
    "the special packages requests accounts deposits foxes ideas theodolites "
    "pinto beans instructions dependencies excuses platelets asymptotes "
    "courts dolphins carefully quickly furiously slyly blithely express "
    "regular final ironic pending unusual even bold silent").split()
# dbgen's P_NAME color vocabulary (subset): q9 greps '%green%', q20
# 'forest%' — part names must be built from these words to exercise them
COLORS = (
    "almond antique aquamarine azure beige bisque black blanched blue "
    "blush brown burlywood burnished chartreuse chiffon chocolate coral "
    "cornflower cornsilk cream cyan dark deep dim dodger drab firebrick "
    "floral forest frosted gainsboro ghost goldenrod green grey honeydew "
    "hot indian ivory khaki lace lavender lawn lemon light lime linen "
    "magenta maroon medium metallic midnight mint misty moccasin navajo "
    "navy olive orange orchid pale papaya peach peru pink plum powder "
    "puff purple red rose rosy royal saddle salmon sandy seashell sienna "
    "sky slate smoke snow spring steel tan thistle tomato turquoise "
    "violet wheat white yellow").split()


@dataclass
class TableData:
    """Host-side generated table: schema + numpy columns (valids all-true).

    VARCHAR columns are already dictionary codes; pools live in the schema.
    `primary_key` feeds the planner's build-side uniqueness reasoning (the
    role statistics play in DetermineJoinDistributionType.java:51).
    Optional `valids` carries per-column null masks (None = all valid).
    """
    name: str
    schema: Schema
    columns: List[np.ndarray]
    primary_key: tuple = ()
    valids: Optional[List[Optional[np.ndarray]]] = None

    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0


PRIMARY_KEYS = {
    "region": ("r_regionkey",),
    "nation": ("n_nationkey",),
    "supplier": ("s_suppkey",),
    "customer": ("c_custkey",),
    "part": ("p_partkey",),
    "partsupp": ("ps_partkey", "ps_suppkey"),
    "orders": ("o_orderkey",),
    "lineitem": ("l_orderkey", "l_linenumber"),
}


def _dict_field(name: str, pool: List[str]) -> Field:
    return Field(name, VARCHAR, dictionary=tuple(pool))


def _codes_for(values: List[str], pool: List[str]) -> np.ndarray:
    index = {s: i for i, s in enumerate(pool)}
    return np.array([index[v] for v in values], dtype=np.int32)


_POOL_CAP = 1 << 16


def _comments(rng: np.random.Generator, n: int, words: int = 4,
              lexicon=None, inject=None, inject_every: int = 0) -> tuple:
    """Seeded comment strings from the lexicon; returns (codes, pool).

    The pool is bounded at 64k distinct strings and rows draw codes from
    it vectorized — Python-level string work is O(pool), not O(rows), so
    SF10/SF100 tables generate in numpy time and the engine's dictionary
    pools stay HBM-friendly (the DictionaryBlock discipline). Text
    *diversity* differs from dbgen above 64k rows; distributions and the
    grep-able patterns benchmark predicates rely on are preserved, and
    the oracle always runs on this same data.

    inject/inject_every: stamp a two-word marker (e.g. 'Customer',
    'Complaints') into every k-th string, mirroring dbgen's deliberate
    pattern injection that q13/q16 predicates grep for."""
    lex = np.array(lexicon if lexicon is not None else LEXICON)
    pool_n = int(min(n, _POOL_CAP))
    picks = rng.integers(0, len(lex), size=(pool_n, words))
    base = [" ".join(lex[row]) for row in picks]
    variants = []
    if inject and inject_every:
        a, b = inject
        n_var = max(1, min(64, pool_n))
        variants = [f"{base[i][:4]}{a} the slyly {b} {base[i]}"
                    for i in range(n_var)]
    pool = sorted(set(base + variants))
    index = {s: i for i, s in enumerate(pool)}
    base_codes = np.array([index[s] for s in base], dtype=np.int32)
    codes = base_codes[rng.integers(0, pool_n, size=n)]
    if inject and inject_every:
        var_codes = np.array([index[s] for s in variants], dtype=np.int32)
        pos = np.arange(0, n, inject_every)
        codes[pos] = var_codes[rng.integers(0, len(var_codes),
                                            size=len(pos))]
    return codes, pool


def _phones(nationkey: np.ndarray) -> tuple:
    """dbgen phone format: '<country>-ddd-ddd-dddd', country = nation+10
    (q22 takes substring(phone,1,2) as the country code). The local part
    is a pure function of nationkey, so the pool has 25 entries and codes
    come from a LUT gather — no per-row strings."""
    per_nation = [f"{10 + nk}-{100 + (nk * 7919) % 900}"
                  f"-{100 + (nk * 7919) % 900}-{100 + (nk * 7919) % 900}0"
                  for nk in range(25)]
    pool = sorted(set(per_nation))
    index = {s: i for i, s in enumerate(pool)}
    lut = np.array([index[s] for s in per_nation], dtype=np.int32)
    return lut[nationkey], pool


def _formula_names(prefix: str, keys: np.ndarray) -> tuple:
    strings = [f"{prefix}#{k:09d}" for k in keys]
    # keys ascending => pool is sorted already
    pool = list(strings)
    return np.arange(len(strings), dtype=np.int32), pool


def retail_price_cents(partkey: np.ndarray) -> np.ndarray:
    """dbgen: 90000 + ((partkey/10) % 20001) + 100 * (partkey % 1000)."""
    pk = partkey.astype(np.int64)
    return 90000 + (pk // 10) % 20001 + 100 * (pk % 1000)


def generate(scale: float, seed: int = 19920101) -> Dict[str, TableData]:
    rng = np.random.default_rng(seed)
    tables: Dict[str, TableData] = {}

    # ---- region / nation --------------------------------------------------
    r_comment_codes, r_comment_pool = _comments(rng, len(REGIONS))
    tables["region"] = TableData(
        "region",
        Schema.of(Field("r_regionkey", BIGINT),
                  _dict_field("r_name", sorted(REGIONS)),
                  _dict_field("r_comment", r_comment_pool)),
        [np.arange(5, dtype=np.int64),
         _codes_for(REGIONS, sorted(REGIONS)),
         r_comment_codes])

    n_names = [n for n, _ in NATIONS]
    n_comment_codes, n_comment_pool = _comments(rng, len(NATIONS))
    tables["nation"] = TableData(
        "nation",
        Schema.of(Field("n_nationkey", BIGINT),
                  _dict_field("n_name", sorted(n_names)),
                  Field("n_regionkey", BIGINT),
                  _dict_field("n_comment", n_comment_pool)),
        [np.arange(25, dtype=np.int64),
         _codes_for(n_names, sorted(n_names)),
         np.array([r for _, r in NATIONS], dtype=np.int64),
         n_comment_codes])

    # ---- supplier ---------------------------------------------------------
    n_supp = max(1, int(scale * 10_000))
    suppkey = np.arange(1, n_supp + 1, dtype=np.int64)
    s_name_codes, s_name_pool = _formula_names("Supplier", suppkey)
    s_addr_codes, s_addr_pool = _comments(rng, n_supp, words=2)
    s_nation = rng.integers(0, 25, n_supp).astype(np.int64)
    # dbgen plants 'Customer ... Complaints' in a sliver of supplier
    # comments (q16's NOT IN subquery greps for it)
    s_comment_codes, s_comment_pool = _comments(
        rng, n_supp, inject=("Customer", "Complaints"), inject_every=13)
    s_phone_codes, s_phone_pool = _phones(s_nation)
    tables["supplier"] = TableData(
        "supplier",
        Schema.of(Field("s_suppkey", BIGINT),
                  _dict_field("s_name", s_name_pool),
                  _dict_field("s_address", s_addr_pool),
                  Field("s_nationkey", BIGINT),
                  _dict_field("s_phone", s_phone_pool),
                  Field("s_acctbal", decimal(12, 2)),
                  _dict_field("s_comment", s_comment_pool)),
        [suppkey, s_name_codes, s_addr_codes,
         s_nation,
         s_phone_codes,
         rng.integers(-99999, 999999, n_supp).astype(np.int64),
         s_comment_codes])

    # ---- customer ---------------------------------------------------------
    n_cust = max(1, int(scale * 150_000))
    custkey = np.arange(1, n_cust + 1, dtype=np.int64)
    c_name_codes, c_name_pool = _formula_names("Customer", custkey)
    c_addr_codes, c_addr_pool = _comments(rng, n_cust, words=2)
    c_comment_codes, c_comment_pool = _comments(rng, n_cust)
    c_nation = rng.integers(0, 25, n_cust).astype(np.int64)
    c_phone_codes, c_phone_pool = _phones(c_nation)
    seg_pool = sorted(SEGMENTS)
    tables["customer"] = TableData(
        "customer",
        Schema.of(Field("c_custkey", BIGINT),
                  _dict_field("c_name", c_name_pool),
                  _dict_field("c_address", c_addr_pool),
                  Field("c_nationkey", BIGINT),
                  _dict_field("c_phone", c_phone_pool),
                  Field("c_acctbal", decimal(12, 2)),
                  _dict_field("c_mktsegment", seg_pool),
                  _dict_field("c_comment", c_comment_pool)),
        [custkey, c_name_codes, c_addr_codes,
         c_nation,
         c_phone_codes,
         rng.integers(-99999, 999999, n_cust).astype(np.int64),
         rng.integers(0, 5, n_cust).astype(np.int32),
         c_comment_codes])

    # ---- part -------------------------------------------------------------
    n_part = max(1, int(scale * 200_000))
    partkey = np.arange(1, n_part + 1, dtype=np.int64)
    p_name_codes, p_name_pool = _comments(rng, n_part, words=3,
                                          lexicon=COLORS)
    mfgr_id = rng.integers(1, 6, n_part)
    brand_id = mfgr_id * 10 + rng.integers(1, 6, n_part)
    mfgr_pool = [f"Manufacturer#{i}" for i in range(1, 6)]
    brand_pool = [f"Brand#{m}{b}" for m in range(1, 6) for b in range(1, 6)]
    brand_pool_sorted = sorted(brand_pool)
    _brand_index = {s: i for i, s in enumerate(brand_pool_sorted)}
    _brand_lut = np.array(
        [_brand_index.get(f"Brand#{v}", 0) for v in range(56)],
        dtype=np.int32)
    brand_codes = _brand_lut[brand_id]
    types = [f"{a} {b} {c}" for a in TYPE_SYL1 for b in TYPE_SYL2
             for c in TYPE_SYL3]
    type_pool = sorted(types)
    type_codes = rng.integers(0, len(type_pool), n_part).astype(np.int32)
    containers = [f"{a} {b}" for a in CONTAINER_SYL1 for b in CONTAINER_SYL2]
    cont_pool = sorted(containers)
    p_comment_codes, p_comment_pool = _comments(rng, n_part, words=2)
    tables["part"] = TableData(
        "part",
        Schema.of(Field("p_partkey", BIGINT),
                  _dict_field("p_name", p_name_pool),
                  _dict_field("p_mfgr", mfgr_pool),
                  _dict_field("p_brand", brand_pool_sorted),
                  _dict_field("p_type", type_pool),
                  Field("p_size", INTEGER),
                  _dict_field("p_container", cont_pool),
                  Field("p_retailprice", decimal(12, 2)),
                  _dict_field("p_comment", p_comment_pool)),
        [partkey, p_name_codes,
         (mfgr_id - 1).astype(np.int32),
         brand_codes,
         type_codes,
         rng.integers(1, 51, n_part).astype(np.int32),
         rng.integers(0, len(cont_pool), n_part).astype(np.int32),
         retail_price_cents(partkey),
         p_comment_codes])

    # ---- partsupp ---------------------------------------------------------
    # dbgen: 4 suppliers per part, spread deterministically
    ps_partkey = np.repeat(partkey, 4)
    i = np.tile(np.arange(4, dtype=np.int64), n_part)
    ps_suppkey = ((ps_partkey + i * (n_supp // 4 + (ps_partkey - 1)
                                     // n_supp)) % n_supp) + 1
    n_ps = len(ps_partkey)
    ps_comment_codes, ps_comment_pool = _comments(rng, n_ps, words=2)
    tables["partsupp"] = TableData(
        "partsupp",
        Schema.of(Field("ps_partkey", BIGINT),
                  Field("ps_suppkey", BIGINT),
                  Field("ps_availqty", INTEGER),
                  Field("ps_supplycost", decimal(12, 2)),
                  _dict_field("ps_comment", ps_comment_pool)),
        [ps_partkey, ps_suppkey,
         rng.integers(1, 10_000, n_ps).astype(np.int32),
         rng.integers(100, 100_001, n_ps).astype(np.int64),
         ps_comment_codes])

    # ---- orders + lineitem ------------------------------------------------
    n_ord = max(1, int(scale * 1_500_000))
    idx = np.arange(n_ord, dtype=np.int64)
    orderkey = (idx // 8) * 32 + (idx % 8) + 1      # sparse, like dbgen
    # dbgen: only customers with custkey % 3 != 0 place orders;
    # j-th such key is j + (j-1)//2 (1,2,4,5,7,8,...)
    m_active = max(1, n_cust - n_cust // 3)
    j = rng.integers(1, m_active + 1, n_ord).astype(np.int64)
    o_custkey = np.clip(j + (j - 1) // 2, 1, n_cust)
    o_orderdate = rng.integers(STARTDATE, ENDDATE - 151 + 1,
                               n_ord).astype(np.int32)
    lines_per_order = rng.integers(1, 8, n_ord)
    o_comment_codes, o_comment_pool = _comments(rng, n_ord)
    o_clerk_codes, o_clerk_pool = _formula_names(
        "Clerk", np.arange(1, max(2, int(scale * 1000)) + 1))
    clerk_assign = rng.integers(0, len(o_clerk_pool), n_ord).astype(np.int32)

    # lineitem (expand orders)
    l_orderkey = np.repeat(orderkey, lines_per_order)
    l_orderdate = np.repeat(o_orderdate, lines_per_order)
    n_li = len(l_orderkey)
    starts = np.concatenate([[0], np.cumsum(lines_per_order)[:-1]])
    l_linenumber = (np.arange(n_li, dtype=np.int64)
                    - np.repeat(starts, lines_per_order) + 1)
    l_partkey = rng.integers(1, n_part + 1, n_li).astype(np.int64)
    # supplier for (part, i): same formula as partsupp with i in 0..3
    li_i = rng.integers(0, 4, n_li).astype(np.int64)
    l_suppkey = ((l_partkey + li_i * (n_supp // 4 + (l_partkey - 1)
                                      // n_supp)) % n_supp) + 1
    l_quantity = rng.integers(1, 51, n_li).astype(np.int64)
    l_extendedprice = l_quantity * retail_price_cents(l_partkey)
    l_discount = rng.integers(0, 11, n_li).astype(np.int64)   # 0.00-0.10
    l_tax = rng.integers(0, 9, n_li).astype(np.int64)         # 0.00-0.08
    l_shipdate = l_orderdate + rng.integers(1, 122, n_li)
    l_commitdate = l_orderdate + rng.integers(30, 91, n_li)
    l_receiptdate = l_shipdate + rng.integers(1, 31, n_li)
    shipped = l_receiptdate <= CURRENTDATE
    rf = np.where(shipped,
                  np.where(rng.random(n_li) < 0.5, 0, 2),  # A or R
                  1)                                        # N
    rf_pool = ["A", "N", "R"]
    ls = np.where(l_shipdate > CURRENTDATE, 1, 0)           # O else F
    ls_pool = ["F", "O"]
    l_comment_codes, l_comment_pool = _comments(rng, n_li, words=2)

    tables["lineitem"] = TableData(
        "lineitem",
        Schema.of(Field("l_orderkey", BIGINT),
                  Field("l_partkey", BIGINT),
                  Field("l_suppkey", BIGINT),
                  Field("l_linenumber", BIGINT),
                  Field("l_quantity", decimal(12, 2)),
                  Field("l_extendedprice", decimal(12, 2)),
                  Field("l_discount", decimal(12, 2)),
                  Field("l_tax", decimal(12, 2)),
                  _dict_field("l_returnflag", rf_pool),
                  _dict_field("l_linestatus", ls_pool),
                  Field("l_shipdate", DATE),
                  Field("l_commitdate", DATE),
                  Field("l_receiptdate", DATE),
                  _dict_field("l_shipinstruct", sorted(INSTRUCTIONS)),
                  _dict_field("l_shipmode", sorted(SHIPMODES)),
                  _dict_field("l_comment", l_comment_pool)),
        [l_orderkey, l_partkey, l_suppkey, l_linenumber,
         l_quantity * 100,       # decimal(12,2) representation
         l_extendedprice, l_discount, l_tax,
         rf.astype(np.int32), ls.astype(np.int32),
         l_shipdate.astype(np.int32), l_commitdate.astype(np.int32),
         l_receiptdate.astype(np.int32),
         rng.integers(0, 4, n_li).astype(np.int32),
         rng.integers(0, 7, n_li).astype(np.int32),
         l_comment_codes])

    # order status/totalprice from line items
    disc_price = l_extendedprice * (100 - l_discount) // 100
    charge = disc_price * (100 + l_tax) // 100
    order_index = np.repeat(np.arange(n_ord), lines_per_order)
    # bincount-based segment reductions (np.add.at's buffered scatter is
    # ~20x slower at SF10's 60M rows)
    o_totalprice = np.bincount(order_index, weights=charge,
                               minlength=n_ord).astype(np.int64)
    n_f_lines = np.bincount(order_index, weights=(ls == 0),
                            minlength=n_ord)
    all_f = n_f_lines == lines_per_order
    any_f = n_f_lines > 0
    status_pool = ["F", "O", "P"]
    status_codes = np.where(all_f, 0, np.where(any_f, 2, 1))  # F / P / O

    tables["orders"] = TableData(
        "orders",
        Schema.of(Field("o_orderkey", BIGINT),
                  Field("o_custkey", BIGINT),
                  _dict_field("o_orderstatus", status_pool),
                  Field("o_totalprice", decimal(12, 2)),
                  Field("o_orderdate", DATE),
                  _dict_field("o_orderpriority", sorted(PRIORITIES)),
                  _dict_field("o_clerk", o_clerk_pool),
                  Field("o_shippriority", INTEGER),
                  _dict_field("o_comment", o_comment_pool)),
        [orderkey, o_custkey, status_codes.astype(np.int32), o_totalprice,
         o_orderdate,
         rng.integers(0, 5, n_ord).astype(np.int32),
         clerk_assign, np.zeros(n_ord, dtype=np.int32), o_comment_codes])

    for name, t in tables.items():
        t.primary_key = PRIMARY_KEYS[name]
    return tables
