"""ORC file connector.

Reference role: the ORC storage tier (lib/trino-orc
reader/OrcRecordReader.java:83 feeding the hive-style connectors). A
root directory holds schemas as subdirectories and tables as
`<name>.orc` files; the type mapping mirrors the parquet connector —
strings dictionary-encode at load, DECIMAL/DATE carry their logical
annotations.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..batch import Field, Schema
from ..formats.orc import read_orc_file
from ..types import BIGINT, BOOLEAN, DOUBLE, TypeKind, VARCHAR
from .dirtable import StagedWriteMixin
from .tpch.datagen import TableData


def load_orc(path: str, name: str,
             predicates: Optional[dict] = None) -> TableData:
    """Decode an ORC file into engine TableData. `predicates` (column
    name -> (lo, hi) physical bounds) skips stripes whose statistics
    prove no match; the result then holds only surviving stripes' rows
    and records skipped_stripes/total_stripes for observability."""
    from ..types import DATE, decimal
    f = read_orc_file(path, predicates)
    names, columns, valids, logicals = \
        f.names, f.columns, f.valids, f.logicals
    fields: List[Field] = []
    arrays: List[np.ndarray] = []
    out_valids: List[Optional[np.ndarray]] = []
    for cname, col, valid, logical in zip(names, columns, valids,
                                          logicals):
        if col.dtype == object:              # STRING -> dict varchar
            mask = valid if valid is not None else \
                np.ones(len(col), dtype=np.bool_)
            pool = sorted({s for s, v in zip(col, mask) if v})
            index = {s: i for i, s in enumerate(pool)}
            codes = np.fromiter((index.get(s, 0) for s in col),
                                dtype=np.int32, count=len(col))
            arrays.append(codes)
            fields.append(Field(cname, VARCHAR, dictionary=tuple(pool)))
        elif logical is not None and logical[0] == "decimal":
            arrays.append(np.asarray(col, dtype=np.int64))
            fields.append(Field(cname, decimal(logical[1], logical[2])))
        elif logical is not None and logical[0] == "date":
            arrays.append(np.asarray(col, dtype=np.int32))
            fields.append(Field(cname, DATE))
        elif logical is not None and logical[0] == "timestamp":
            from ..types import TIMESTAMP
            arrays.append(np.asarray(col, dtype=np.int64))
            fields.append(Field(cname, TIMESTAMP))
        elif col.dtype == np.bool_:
            arrays.append(np.asarray(col))
            fields.append(Field(cname, BOOLEAN))
        elif np.issubdtype(col.dtype, np.integer):
            arrays.append(np.asarray(col, dtype=np.int64))
            fields.append(Field(cname, BIGINT))
        elif np.issubdtype(col.dtype, np.floating):
            arrays.append(np.asarray(col, dtype=np.float64))
            fields.append(Field(cname, DOUBLE))
        else:
            raise ValueError(f"{name}.{cname}: unsupported ORC dtype "
                             f"{col.dtype}")
        out_valids.append(valid)
    if all(v is None for v in out_valids):
        out_valids = None
    data = TableData(name, Schema(tuple(fields)), arrays,
                     valids=out_valids)
    data.skipped_stripes = f.skipped_stripes
    data.total_stripes = f.total_stripes
    return data


class OrcConnector(StagedWriteMixin):
    name = "orc"
    ext = "orc"
    fmt = "orc"

    def __init__(self, root: str):
        self.root = root
        self._cache: Dict[Tuple[str, str], TableData] = {}
        # unclean-shutdown recovery: roll forward / sweep any staged
        # write state before the first scan can observe it
        self.sweep_on_startup()

    @staticmethod
    def _load(path: str, name: str,
              predicates: Optional[dict] = None) -> TableData:
        return load_orc(path, name, predicates)

    def _schema_dir(self, schema: str) -> str:
        return os.path.join(self.root, schema)

    def schema_names(self):
        if not os.path.isdir(self.root):
            return []
        return sorted(d for d in os.listdir(self.root)
                      if os.path.isdir(os.path.join(self.root, d))
                      and not d.startswith("."))

    def table_names(self, schema: str):
        return self._list_tables(schema)

    def get_table(self, schema: str, table: str) -> TableData:
        key = (schema, table)
        if key not in self._cache:
            self._cache[key] = self._load_table(schema, table)
        return self._cache[key]

    def get_table_schema(self, schema: str, table: str) -> Schema:
        return self.get_table(schema, table).schema

    def get_table_pruned(self, schema: str, table: str,
                         ranges: dict) -> TableData:
        """Predicate-pruned decode: stripes whose statistics cannot
        match `ranges` are never decompressed or decoded. The result is
        NOT cached as the table (its row set is predicate-specific);
        callers own caching under a predicate-aware key."""
        return self._load_table(schema, table, predicates=ranges)


def export_table(data: TableData, path: str,
                 compression: str = "none") -> None:
    """Engine TableData -> ORC file (formats/orc.py write_orc), the
    write-parity twin of parquetdir.export_table (lib/trino-orc
    OrcWriter.java's role); flattening is shared with the parquet
    exporter. `compression` is "none" or "zlib"."""
    from ..formats.orc import write_orc
    from .parquetdir import flatten_table
    write_orc(path, *flatten_table(data, "ORC"),
              compression=compression)
